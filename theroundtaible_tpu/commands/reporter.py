"""Console Reporter — the CLI's personality-heavy display of the round loop.

Implements core.orchestrator.Reporter over the terminal, covering the
reference's inline chalk/ora output (src/orchestrator.ts:295, 361-535).
"""

from __future__ import annotations

from typing import Callable, Optional

from ..core.consensus import summarize_consensus
from ..core.orchestrator import Reporter
from ..core.types import ConsensusBlock
from ..utils.context import ProjectContext
from ..utils.ui import (
    Spinner,
    knight_color,
    round_header,
    score_bar,
    style,
    thinking_message,
)


class ConsoleReporter(Reporter):
    def __init__(self):
        self._context_spinner: Optional[Spinner] = None

    def context_start(self) -> None:
        self._context_spinner = Spinner(
            "  Gathering intel from the codebase...").start()

    def context_done(self, context: ProjectContext, manifest_features: int,
                     decree_count: int) -> None:
        detail = (f"manifest: {manifest_features} features, "
                  f"decrees: {decree_count}")
        if context.source_file_contents:
            kb = round(len(context.source_file_contents) / 1024)
            detail = f"source: {kb}KB, {detail}"
        if self._context_spinner:
            self._context_spinner.succeed(f"Context assembled ({detail})")
            self._context_spinner = None

    def session_started(self, session_path: str, resumed: bool) -> None:
        if resumed:
            print(style.bold(style.yellow(
                "\n  The King has spoken. Back to the table, knights!\n")))
        else:
            print(style.dim(f"  Session: {session_path}"))

    def round_started(self, round_num: int, order: list[str],
                      shuffled: bool) -> None:
        if shuffled:
            print(style.dim(f"  Speaking order: {' → '.join(order)}"))
        print(style.bold(style.blue(f"\n  {round_header(round_num)}\n")))

    def knight_skipped(self, knight: str) -> None:
        print(style.yellow(f"  {knight} didn't show up today. Typical."))

    def knight_thinking(self, knight: str) -> Callable[[], None]:
        spinner = Spinner(
            knight_color(knight, f"  {knight} {thinking_message(knight)}"))
        spinner.start()
        return spinner.stop

    def knight_spoke(self, knight: str, round_num: int, display_text: str,
                     consensus: Optional[ConsensusBlock]) -> None:
        divider = knight_color(knight, "─" * 50)
        print(divider)
        print(knight_color(knight, f"  {knight}")
              + style.dim(f" (Round {round_num})"))
        print(divider)
        indented = "\n".join(f"  {line}"
                             for line in display_text.split("\n"))
        print(indented)
        if consensus is not None:
            print("")
            print(f"  {knight_color(knight, knight)} score: "
                  f"{score_bar(consensus.consensus_score)}")
            if consensus.agrees_with:
                print(style.dim(
                    f"  Agrees with: {', '.join(consensus.agrees_with)}"))
            if consensus.pending_issues:
                print(style.yellow(
                    f"  Open issues: {', '.join(consensus.pending_issues)}"))
        else:
            print(style.yellow(
                "\n  (no consensus block found — the knight forgot the rules)"))
        print("")

    def knight_failed(self, knight: str, kind: str, message: str,
                      hint: Optional[str]) -> None:
        print(style.red(f"  {knight} crashed and burned"))
        print(style.red(f"  Error ({kind}): {message}"))
        if hint:
            print(style.dim(f"  Hint: {hint}"))

    def fallback_engaged(self, knight: str, fallback_id: str) -> None:
        print(style.yellow(
            f"  {knight} primary adapter failed, switching to fallback "
            f"({fallback_id})..."))

    def resolving_files(self, knight: str, requests: list[str]) -> None:
        print(style.dim(f"  Requesting files: {', '.join(requests)}"))

    def resolving_commands(self, knight: str) -> None:
        print(style.dim("  Verification commands:"))

    def verify_event(self, kind: str, message: str) -> None:
        if kind == "denied" or kind == "warning":
            print(style.yellow(f"  {message}"))
        else:
            print(style.dim(f"  {message}"))

    def consensus_reached(self, blocks: list[ConsensusBlock],
                          allowed_files: list[str]) -> None:
        print(style.bold(style.green(
            "\n  Against all odds... they actually agree.")))
        print(summarize_consensus(blocks))
        if allowed_files:
            print(style.cyan(
                f"\n  Scope: {len(allowed_files)} file(s) in modification "
                "scope:"))
            for f in allowed_files:
                if f.upper().startswith("NEW:"):
                    print(style.green(f"    + {f[4:]} (new)"))
                else:
                    print(style.dim(f"    ~ {f}"))

    def unanimous_rejection(self, blocks: list[ConsensusBlock]) -> None:
        print(style.bold(style.red(
            "\n  A rare sight — the knights actually agree on something.")))
        print(style.bold(style.red(
            "  Unfortunately, they agree that your idea is terrible.\n")))
        print(summarize_consensus(blocks))

    def escalation_warning(self, round_num: int, rounds_left: int) -> None:
        print(style.yellow(
            f"\n  Round {round_num}: Still no consensus. {rounds_left} "
            "round(s) left before escalation."))

    def escalated(self, blocks: list[ConsensusBlock]) -> None:
        print(style.bold(style.yellow(
            "\n  The knights have agreed to disagree. Your move.")))
        print(summarize_consensus(blocks))

    def overflow_warning(self, skipped: int, max_chars: int) -> None:
        kb = round(max_chars / 1024)
        print(style.yellow(
            f"\n  ⚔️  The scrolls overflow! {skipped} file(s) skipped or cut "
            f"— the knights can only carry {kb}KB into battle."))
        print(style.dim(
            "  Tip: narrow the scope with ignore patterns in "
            ".roundtable/config.json, or seat knights with bigger context.\n"))

    def round_footer(self, round_metric) -> None:
        """Per-round timing + engine throughput (SURVEY.md §5.1 — the
        tok/s surfaced where the reference had only spinner theater)."""
        from ..utils.metrics import aggregate_engine_stats
        agg = aggregate_engine_stats(round_metric.turns)
        line = f"\n  ⏱  Round {round_metric.round}: {round_metric.wall_s:.1f}s"
        if agg["prefill_tokens"] or agg["decode_tokens"]:
            total_in = agg["prefill_tokens"] + agg["reused_tokens"]
            pct = (round(100 * agg["reused_tokens"] / total_in)
                   if total_in else 0)
            tps = (f" @ {agg['decode_tps']:.0f} tok/s"
                   if agg["decode_seconds"] else "")
            line += (f" · prefill {agg['prefill_tokens']} tok "
                     f"({pct}% cache reuse)"
                     f" · decode {agg['decode_tokens']} tok{tps}")
        print(style.dim(line))
