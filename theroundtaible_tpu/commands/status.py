"""`roundtable status` — show the latest session.

Parity with reference src/commands/status.ts:11-77.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from ..utils.session import find_latest_session
from ..utils.ui import style

PHASE_DISPLAY = {
    "discussing": ("⚔️", "The knights are discussing", style.blue),
    "consensus_reached": ("✓", "Consensus reached", style.green),
    "escalated": ("!", "Escalated to the King", style.yellow),
    "applying": ("…", "The Lead Knight is applying the decision", style.cyan),
    "completed": ("✓", "Completed", style.green),
}

DECISIONS_PREVIEW_LINES = 10


def phase_display(status) -> tuple[str, str, object]:
    """(icon, label, color) for a SessionStatus, rejection-aware.

    The reference writes phase "consensus_reached" for unanimous rejection
    too (orchestrator.ts:616) and can't distinguish them afterward; we
    persist `unanimous_rejection` in status.json so the session lists
    don't misreport a rejected idea as an agreed decision.
    """
    if status.phase == "consensus_reached" and status.unanimous_rejection:
        return ("✗", "Unanimously rejected", style.red)
    return PHASE_DISPLAY.get(status.phase, ("?", status.phase, style.white))


def status_command(project_root: Optional[str] = None,
                   telemetry_view: bool = False,
                   perf_view: bool = False,
                   kv_view: bool = False,
                   health_view: bool = False,
                   gateway_view: bool = False,
                   fleet_view: bool = False,
                   capacity_view: bool = False,
                   slo_view: bool = False) -> int:
    project_root = project_root or os.getcwd()
    if health_view:
        # Fleet health needs no session dir — it reads the live
        # process's breaker/scheduler/supervisor state.
        return health_status()
    if gateway_view:
        # Gateway ledger is live-registry state too — no session dir.
        return gateway_status()
    if fleet_view:
        # Multi-replica serving view — live router + registry state.
        return fleet_status()
    if capacity_view:
        # Capacity frontier: file-based record vs live gateway gauges.
        return capacity_status(project_root)
    if slo_view:
        # SLO burn-rate view: capacity-record baseline vs live burn
        # gauges + trace retention (ISSUE 20).
        return slo_status(project_root)
    session = find_latest_session(project_root)
    if session is None:
        print(style.dim("\n  No sessions yet. "
                        'Start one with "roundtable discuss".\n'))
        return 0
    if kv_view:
        return kv_status(session)
    if perf_view:
        return perf_status(session)
    if telemetry_view:
        return telemetry_status(session)

    print(style.bold(f"\n  Latest session: {session.name}"))
    if session.topic:
        print(f"  Topic: {session.topic}")
    if session.status:
        s = session.status
        icon, label, color = phase_display(s)
        print(f"  Phase: {color(f'{icon} {label}')}")
        print(f"  Round: {s.round}")
        # consensus_reached is True for unanimous rejection too (schema
        # parity with the reference) — the display must not contradict
        # the rejection phase line above it
        consensus = ("unanimous rejection" if s.unanimous_rejection
                     else "yes" if s.consensus_reached else "no")
        print(f"  Consensus: {consensus}")
        if s.current_knight:
            print(f"  Current knight: {s.current_knight}")
        if s.lead_knight:
            print(f"  Lead knight: {s.lead_knight}")
        print(style.dim(f"  Started: {s.started_at}"))
        print(style.dim(f"  Updated: {s.updated_at}"))

    decisions = Path(session.path) / "decisions.md"
    if decisions.exists():
        lines = decisions.read_text(encoding="utf-8").split("\n")
        print(style.bold("\n  Decision preview:"))
        for line in lines[:DECISIONS_PREVIEW_LINES]:
            print(style.dim(f"    {line}"))
        if len(lines) > DECISIONS_PREVIEW_LINES:
            print(style.dim("    ..."))
    print("")
    return 0


METRICS_PREVIEW_LINES = 40
SPAN_PREVIEW_LINES = 8


def telemetry_status(session) -> int:
    """`roundtable status --telemetry` — render the latest session's
    view of the unified registry (ISSUE 5): the per-round Prometheus
    snapshot metrics.json's writer drops, the span-tree summary from
    spans.jsonl, and any flight-recorder dumps. All file-based: the
    serving process owns the live registry; these files are its
    per-round export (plus this process's own registry when serving
    in-process, e.g. `roundtable serve` foreground)."""
    import json as _json

    from ..utils import telemetry

    tdir = Path(session.path) / "telemetry"
    print(style.bold(f"\n  Telemetry — session {session.name}"))
    if not tdir.exists() and not telemetry.ACTIVE:
        print(style.dim(
            "  No telemetry captured. Run with ROUNDTABLE_TELEMETRY=1 "
            "to arm span tracing and the registry snapshot.\n"))
        return 0

    prom = tdir / "metrics.prom"
    if prom.exists():
        print(style.bold("\n  Registry snapshot (metrics.prom):"))
        lines = [ln for ln in
                 prom.read_text(encoding="utf-8").splitlines()
                 if ln and not ln.startswith("#")
                 and "_bucket{" not in ln]
        for ln in lines[:METRICS_PREVIEW_LINES]:
            print(style.dim(f"    {ln}"))
        if len(lines) > METRICS_PREVIEW_LINES:
            print(style.dim(f"    ... ({len(lines)} series total)"))
    elif telemetry.ACTIVE:
        # In-process view (serve foreground / tests): the live registry.
        print(style.bold("\n  Registry (live, this process):"))
        for k, v in sorted(
                telemetry.REGISTRY.snapshot_compact().items()):
            print(style.dim(f"    {k} {v:g}"))

    spans = tdir / "spans.jsonl"
    if spans.exists():
        per_rung: dict[str, int] = {}
        total = 0
        tail: list[dict] = []
        for line in spans.read_text(encoding="utf-8").splitlines():
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            total += 1
            per_rung[rec.get("rung", "?")] = \
                per_rung.get(rec.get("rung", "?"), 0) + 1
            tail.append(rec)
        print(style.bold(f"\n  Spans ({total} in spans.jsonl):"))
        print(style.dim("    " + "  ".join(
            f"{r}:{per_rung[r]}" for r in sorted(per_rung))))
        for rec in tail[-SPAN_PREVIEW_LINES:]:
            attrs = rec.get("attrs", {})
            who = attrs.get("session") or attrs.get("engine") or ""
            print(style.dim(
                f"    {rec.get('rung', '?'):<10} "
                f"{rec.get('dur_s', 0):>9.3f}s  "
                f"{rec.get('status', '')}  {who}"))

    dumps = sorted(Path(telemetry.dump_dir()).glob("flight-*.json")) \
        if Path(telemetry.dump_dir()).exists() else []
    if dumps:
        print(style.bold(f"\n  Flight-recorder dumps ({len(dumps)}):"))
        for p in dumps[-5:]:
            print(style.dim(f"    {p}"))
    print("")
    return 0


# --- `roundtable status --health` (ISSUE 12) ---


def health_status() -> int:
    """`roundtable status --health` — the fleet-health view: breaker
    state, the admission gate, scheduler queues, and the ISSUE 12
    supervision roll-up (restart totals, dead engines and why, and each
    engine's bounded restart history). Live-process state: meaningful
    from the serving process (serve foreground, tests, a REPL driving
    the fleet) — a fresh CLI process reports an idle fleet."""
    from ..engine.fleet import fleet_health

    h = fleet_health()
    print(style.bold("\n  Fleet health"))
    print(style.dim(
        f"    engines={h['total']}  breakers_open={h['open']}  "
        f"degraded={h['degraded']}  draining={h['draining']}  "
        f"hangs={h['hangs']}  queued_sessions={h['queued_sessions']}"))
    for s in h["schedulers"]:
        gate = ("closed" if s.get("closed")
                else f"paused:{s['paused']}" if s.get("paused")
                else "open")
        print(style.dim(
            f"    scheduler[{s['engine']}] queued={s['queued']} "
            f"active_rows={s['active_rows']} "
            f"sessions={len(s['sessions'])} (admission {gate})"))

    sup = h["supervisor"]
    print(style.bold("\n  Supervision (engine restarts):"))
    print(style.dim(
        f"    restarts={sup['restarts']}  "
        f"sessions_recovered={sup['sessions_recovered']}  "
        f"sessions_lost={sup['sessions_lost']}  "
        f"dead_engines={sup['dead_engines']}"))
    if not sup["engines"]:
        print(style.dim("    (no engine has ever needed a restart)"))
    for e in sup["engines"]:
        state = (style.red(f"DEAD: {e['dead_reason']}") if e["dead"]
                 else style.green("alive"))
        print(f"    {e['engine']}: {e['restarts']} restart(s), "
              f"{e['failed_restarts']} failed — {state}")
        for ev in e["history"][-5:]:
            ok = "ok" if ev.get("ok") else "FAILED"
            extra = ""
            if ev.get("restored_sessions") is not None:
                extra = f", restored {ev['restored_sessions']} session(s)"
            print(style.dim(
                f"      #{ev.get('restart', '?')} {ev.get('reason')}: "
                f"{ok} in {ev.get('wall_s', 0):.3f}s{extra}"))
    print("")
    return 0


# --- `roundtable status --gateway` (ISSUE 16) ---


def gateway_status() -> int:
    """`roundtable status --gateway` — the serving gateway's
    admission/shed ledger, rendered from the live registry's
    roundtable_gateway_* series: admitted/shed/queued/expired totals
    broken down by reason label, the inflight-stream gauge, and the
    resume / drop-to-summary counters. Live-process state like
    --health: meaningful from the serving process; a fresh CLI process
    reports an idle gateway."""
    from ..utils import telemetry

    series = telemetry.REGISTRY.snapshot_compact()
    print(style.bold("\n  Serving gateway"))

    def by_reason(outcome: str) -> dict[str, float]:
        name = f"roundtable_gateway_{outcome}_total"
        out: dict[str, float] = {}
        for key, val in series.items():
            if key.split("{", 1)[0] != name:
                continue
            out[_labels(key).get("reason", "?")] = val
        return out

    any_out = False
    for outcome in ("admitted", "shed", "queued", "expired"):
        reasons = by_reason(outcome)
        if not reasons:
            continue
        any_out = True
        total = sum(reasons.values())
        print(style.bold(f"\n  {outcome.capitalize()}: {total:g}"))
        for reason in sorted(reasons):
            print(style.dim(f"    {reason:<20} {reasons[reason]:g}"))

    inflight = [k for k in series
                if k.split("{", 1)[0]
                == "roundtable_gateway_inflight_streams"]
    if inflight:
        any_out = True
        print(style.bold(f"\n  Inflight streams: {len(inflight)}"))
        for k in sorted(inflight):
            lb = _labels(k)
            print(style.dim(f"    {lb.get('request', '?')}"))

    extras = [("roundtable_gateway_resumed_streams_total",
               "reconnects resumed"),
              ("roundtable_gateway_dropped_events_total",
               "events coalesced to summary (slow consumers)")]
    lines = []
    for name, label in extras:
        vals = [v for k, v in series.items()
                if k.split("{", 1)[0] == name]
        if vals:
            lines.append(f"    {label:<44} {sum(vals):g}")
    if lines:
        any_out = True
        print(style.bold("\n  Resilience:"))
        for ln in lines:
            print(style.dim(ln))

    # ISSUE 20: the TTFT stage split — the former one-lump TTFT
    # decomposed into the critical-path stages the tracer attributes,
    # aggregated over this process's recent traces.
    from ..utils import tracing
    recent = [r for r in tracing.store().recent()
              if r.get("stages")]
    if recent:
        any_out = True
        agg: dict[str, list[float]] = {}
        for r in recent:
            for stage, dur in r["stages"].items():
                agg.setdefault(stage, []).append(dur)
        print(style.bold(
            f"\n  TTFT stage split ({len(recent)} recent traces):"))
        print(style.dim("    stage            n      mean_s       p95_s"))
        for stage in tracing.STAGES:
            vals = sorted(agg.get(stage, ()))
            if not vals:
                continue
            p95 = vals[min(int(len(vals) * 0.95), len(vals) - 1)]
            print(style.dim(
                f"    {stage:<14}{len(vals):>4}"
                f"{sum(vals) / len(vals):>12.4f}{p95:>12.4f}"))

    if not any_out:
        print(style.dim(
            "\n  No gateway series in this process. Run `roundtable "
            "gateway` (or drive a Gateway in-process) to populate the "
            "admission/shed ledger.\n"))
    print("")
    return 0


# --- `roundtable status --slo` (ISSUE 20) ---


def slo_surface(frontier, record_path, series) -> dict:
    """The SLO view's machine shape: the capacity record's p95 SLO
    baseline joined with the live burn-rate gauges and trace
    retention. Keys are bound in telemetry.SURFACE_BINDINGS
    ["slo_status"] (RT-SURFACE-DRIFT)."""
    from ..utils import tracing

    th = (frontier or {}).get("derived_thresholds", {})
    p95 = float(th.get("p95_slo_s") or 0.0)
    mon = tracing.SloBurnMonitor(
        p95_slo_s=p95,
        source="capacity_record" if frontier else "default")

    def gauge(name: str, **labels) -> float:
        total = 0.0
        for key, val in series.items():
            if key.split("{", 1)[0] != name:
                continue
            lb = _labels(key)
            if any(lb.get(k) != v for k, v in labels.items()):
                continue
            total += val
        return total

    return {
        "armed": mon.armed,
        "p95_slo_s": p95,
        "source": mon.source,
        "record_path": record_path,
        "error_budget": mon.error_budget,
        "threshold": mon.threshold,
        "burn_fast": gauge("roundtable_slo_burn_rate", window="fast"),
        "burn_slow": gauge("roundtable_slo_burn_rate", window="slow"),
        "breaches": gauge("roundtable_slo_breaches_total"),
        "slo_dumps": gauge("roundtable_flight_dumps_total",
                           trigger="slo_burn"),
        "traces_retained": gauge("roundtable_traces_retained_total"),
    }


def slo_status(project_root: str) -> int:
    """`roundtable status --slo` — the SLO burn-rate view (ISSUE 20):
    the p95 TTFT SLO from the capacity frontier record, the live
    fast/slow burn-rate gauges against the error budget, breach /
    flight-dump counters, and trace retention. Live-process gauges
    like --gateway: a fresh CLI process shows the armed baseline with
    zero burn."""
    from ..utils import telemetry, tracing

    print(style.bold("\n  SLO burn rate"))
    path, frontier = _find_capacity_record(project_root)
    series = telemetry.REGISTRY.snapshot_compact()
    surf = slo_surface(frontier, path, series)

    armed = ("armed" if surf["armed"]
             else "DISARMED (no p95 SLO — sweep a capacity record)")
    print(style.dim(
        f"    {armed}  p95_slo_s={surf['p95_slo_s']:g}  "
        f"source={surf['source']}"))
    if surf["record_path"]:
        print(style.dim(f"    record: {surf['record_path']}"))
    print(style.bold("\n  Burn (bad-fraction / error budget):"))
    print(style.dim(
        f"    fast={surf['burn_fast']:g}  slow={surf['burn_slow']:g}  "
        f"budget={surf['error_budget']:g}  "
        f"fires at >{surf['threshold']:g} on BOTH windows"))
    print(style.bold("\n  Incidents:"))
    print(style.dim(
        f"    breaches={surf['breaches']:g}  "
        f"slo_burn flight dumps={surf['slo_dumps']:g}  "
        f"traces retained={surf['traces_retained']:g}"))
    recent = [r for r in tracing.store().recent()
              if "slo_violation" in r.get("flags", ())]
    if recent:
        print(style.bold("\n  Recent SLO-violating traces:"))
        for r in recent[-5:]:
            print(style.dim(
                f"    {r['trace_id']}  ttft={r.get('ttft_s', 0):g}s  "
                f"{r.get('session', '')}"))
    print("")
    return 0


# --- `roundtable status --fleet` (ISSUE 17) ---


def fleet_status() -> int:
    """`roundtable status --fleet` — the multi-replica serving view:
    per-replica liveness, session assignment and queue/row gauges from
    the live router (when this process serves one), plus every
    replica-labeled registry series — so an operator sees WHERE the
    sessions live, which replica is rolling/dead, and the router's
    migration / failover / roll history. Live-process state like
    --health: a fresh CLI process reports no fleet."""
    from ..router import active_router
    from ..utils import telemetry

    print(style.bold("\n  Multi-replica serving"))
    router = active_router()
    if router is not None:
        d = router.describe()
        print(style.dim(
            f"    replicas={len(d['replicas'])}  "
            f"sessions={d['sessions']}  "
            f"migrations={d['migrations']}  "
            f"failovers={d['failovers']}  rolls={d['rolls']}"
            + (f"  rolling={','.join(d['rolling'])}"
               if d["rolling"] else "")
            + (f"  retired={','.join(d['retired'])}"
               if d["retired"] else "")))
        for name, rep in sorted(d["replicas"].items()):
            state = (style.red(f"DEAD: {rep['dead']}") if rep["dead"]
                     else style.yellow(f"paused:{rep['paused']}")
                     if rep["paused"] else style.green("live"))
            print(f"    {name} [{rep['engine']}]: {state}")
            print(style.dim(
                f"      sessions={rep['sessions']}  "
                f"queued={rep['queued']}  "
                f"active_rows={rep['active_rows']}"))

    series = telemetry.REGISTRY.snapshot_compact()
    labeled = {k: v for k, v in series.items()
               if "replica=" in k}
    if labeled:
        print(style.bold("\n  Replica-labeled series:"))
        for k in sorted(labeled):
            print(style.dim(f"    {k} {labeled[k]:g}"))
    if router is None and not labeled:
        print(style.dim(
            "\n  No replica fleet in this process. Serve with "
            "`roundtable gateway --replicas N` (or `serve --replicas "
            "N`) to route sessions across N engine replicas.\n"))
    print("")
    return 0


# --- `roundtable status --capacity` (ISSUE 19) ---


def _find_capacity_record(project_root: str):
    """(path, frontier) of the capacity record to render:
    ROUNDTABLE_GATEWAY_CAPACITY_FILE when set, else the newest
    CAPACITY_r19.json under the project root. (None, None) when there
    is nothing loadable — an unreadable record prints WHY."""
    from ..gateway.admission import CAPACITY_FILE_ENV
    from ..loadgen.capacity import load_record

    candidates = []
    envp = os.environ.get(CAPACITY_FILE_ENV)
    if envp:
        candidates.append(envp)
    local = Path(project_root) / "CAPACITY_r19.json"
    if local.exists():
        candidates.append(str(local))
    for path in candidates:
        try:
            return path, load_record(path)
        except ValueError as e:
            print(style.red(f"  unreadable capacity record: {e}"))
    return None, None


def capacity_surface(frontier, record_path, series) -> dict:
    """The capacity view's machine shape: the measured frontier record
    next to the LIVE gateway ledger, so predicted-vs-measured and
    configured-vs-derived drift is one lookup. Keys are bound in
    telemetry.SURFACE_BINDINGS["capacity_status"] (RT-SURFACE-DRIFT)."""
    knee = frontier.get("knee", {})
    predicted = frontier.get("predicted") or {}
    gap = frontier.get("gap") or {}
    live_inflight = sum(
        1 for k in series
        if k.split("{", 1)[0] == "roundtable_gateway_inflight_streams")
    shed = sum(v for k, v in series.items()
               if k.split("{", 1)[0] == "roundtable_gateway_shed_total")
    admitted = sum(
        v for k, v in series.items()
        if k.split("{", 1)[0] == "roundtable_gateway_admitted_total")
    record_errors = sum(
        v for k, v in series.items()
        if k.split("{", 1)[0]
        == "roundtable_gateway_capacity_record_errors_total")
    return {
        "record_path": record_path,
        "knee_rate": knee.get("rate"),
        "knee_ttft_p95_s": knee.get("ttft_p95_s"),
        "measured_tok_s": knee.get("accepted_tok_s"),
        "predicted_tok_s": predicted.get("decode_ceiling_tps"),
        "gap_frac": gap.get("gap_frac"),
        "derived_thresholds": dict(
            frontier.get("derived_thresholds", {})),
        "points": len(frontier.get("points", [])),
        "live_inflight": live_inflight,
        "live_admitted": admitted,
        "live_shed": shed,
        "record_errors": record_errors,
    }


def capacity_status(project_root: str) -> int:
    """`roundtable status --capacity` — the measured capacity frontier
    (latest CAPACITY_r19.json / ROUNDTABLE_GATEWAY_CAPACITY_FILE)
    rendered against the live gateway gauges: per-rate frontier table,
    the perfmodel predicted curve vs the measured knee, the derived
    admission thresholds, and this process's admission ledger so an
    operator sees at a glance whether live load sits inside the
    measured envelope."""
    from ..utils import telemetry

    print(style.bold("\n  Capacity frontier"))
    path, frontier = _find_capacity_record(project_root)
    series = telemetry.REGISTRY.snapshot_compact()
    if frontier is None:
        print(style.dim(
            "\n  No capacity record found. Sweep one with `roundtable "
            "loadgen` (or `python bench_load.py`) — it writes "
            "CAPACITY_r19.json and ROUNDTABLE_GATEWAY_CAPACITY_FILE "
            "feeds it back into admission.\n"))
        return 0
    surf = capacity_surface(frontier, path, series)
    print(style.dim(f"    record: {path}"))
    if frontier.get("chip"):
        ch = frontier["chip"]
        print(style.dim(f"    chip: {ch.get('name')} "
                        f"({ch.get('source', '?')}), "
                        f"n_devices={frontier.get('n_devices', 1)}"))

    print(style.bold("\n  Frontier (measured):"))
    print(style.dim("    offered_rps  admitted  shed_rate  ttft_p95_s"
                    "  accepted_tok_s  sessions/chip"))
    for p in frontier.get("points", []):
        p95 = p.get("ttft_p95_s")
        print(style.dim(
            f"    {p['offered_rps']:>11.2f}  {p['admitted']:>8.0f}"
            f"  {p['shed_rate']:>9.3f}"
            f"  {p95 if p95 is None else f'{p95:.3f}':>10}"
            f"  {p['accepted_tok_s']:>14.1f}"
            f"  {p['sessions_per_chip']:>13.2f}"))
    knee = frontier.get("knee", {})
    rate = surf["knee_rate"]
    print(style.bold(
        f"\n  Knee: {f'{rate:.2f}' if rate is not None else '?'} "
        "sessions/s"))
    print(style.dim(f"    {knee.get('reason', '')}"))

    if surf["predicted_tok_s"] is not None:
        meas = surf["measured_tok_s"] or 0.0
        gapf = surf["gap_frac"]
        print(style.bold("\n  Predicted vs measured:"))
        print(style.dim(
            f"    roofline decode ceiling: "
            f"{surf['predicted_tok_s']:.1f} tok/s"))
        print(style.dim(f"    measured at knee:        {meas:.1f} tok/s"
                        + (f"  (gap {gapf * 100:.1f}%)"
                           if gapf is not None else "")))
        for name, frac in (frontier.get("gap", {})
                           .get("overheads", {}).items()):
            if isinstance(frac, (int, float)):
                print(style.dim(f"      {name:<24} {frac * 100:6.1f}%"))

    th = surf["derived_thresholds"]
    if th:
        print(style.bold("\n  Derived admission thresholds:"))
        print(style.dim(
            f"    max_inflight={th.get('max_inflight')}  "
            f"max_queue_depth={th.get('max_queue_depth')}  "
            f"p95_slo_s={th.get('p95_slo_s')}"))

    print(style.bold("\n  Live gateway (this process):"))
    print(style.dim(
        f"    inflight_streams={surf['live_inflight']:g}  "
        f"admitted={surf['live_admitted']:g}  "
        f"shed={surf['live_shed']:g}  "
        f"record_errors={surf['record_errors']:g}"))
    if not surf["live_admitted"] and not surf["live_inflight"]:
        print(style.dim(
            "    (idle — run the gateway in-process to compare live "
            "load against the frontier)"))
    print("")
    return 0


# --- `roundtable status --kv` (ISSUE 7) ---


def kv_status(session) -> int:
    """`roundtable status --kv` — the KV-tier view: the paged-pool
    memory ledger with its cross-session sharing split (shared pages
    counted once), the prefix cache's hit/miss/eviction series, the
    host-RAM offload tier's spill state, and per-session KV footprints.
    Same sourcing as --perf: the session's metrics.prom export overlaid
    with this process's live registry."""
    print(style.bold(f"\n  KV tiers — session {session.name}"))
    series = _series_for_perf(session)

    def section(title: str, prefixes: tuple[str, ...]) -> bool:
        keys = sorted(k for k in series
                      if k.split("{")[0].startswith(prefixes))
        if not keys:
            return False
        print(style.bold(f"\n  {title}:"))
        for k in keys:
            print(style.dim(f"    {k} {series[k]:g}"))
        return True

    any_out = section("Memory ledger (HBM tier)", (
        "roundtable_kv_slots", "roundtable_kv_slot_",
        "roundtable_kv_cached", "roundtable_kv_pages",
        "roundtable_kv_page_", "roundtable_kv_fragmentation",
        "roundtable_kv_shared_pages", "roundtable_kv_exclusive_pages",
        "roundtable_kv_hbm_bytes", "roundtable_hbm_"))
    # ISSUE 11: the quantized-page dtype split — kv_dtype rendered
    # from the bits gauge (0 = bf16 pool), logical vs resident bytes
    # and the saved delta next to each other so the compression claim
    # is auditable from the same screen as the residency it frees.
    quant_keys = [k for k in series
                  if k.split("{")[0] == "roundtable_kv_quant_bits"]
    if quant_keys:
        print(style.bold("\n  Quantized KV pages (ISSUE 11):"))
        for k in sorted(quant_keys):
            lb = _labels(k)
            bits = int(series[k])
            dtype = {8: "int8", 4: "int4"}.get(bits, "bf16")
            eng = lb.get("engine", "?")
            logical = series.get(
                f"roundtable_kv_bytes_logical{{engine={eng}}}", 0)
            saved = series.get(
                f"roundtable_kv_quant_bytes_saved{{engine={eng}}}", 0)
            print(style.dim(
                f"    {eng:<16} kv_dtype={dtype:<5} "
                f"kv_bytes_logical={logical:g} "
                f"kv_bytes_resident={logical - saved:g} "
                f"saved={saved:g}"))
        any_out = True
    any_out |= section("Prefix cache (cross-session index)",
                       ("roundtable_prefix_",))
    any_out |= section("Host-RAM offload tier", (
        "roundtable_kv_spill", "roundtable_kv_restores",
        "roundtable_kv_spilled_sessions", "roundtable_kv_host_bytes"))

    sess_keys = [k for k in series
                 if k.split("{")[0] == "roundtable_session_kv_bytes"
                 and series[k] > 0]
    if sess_keys:
        print(style.bold("\n  Per-session KV footprint:"))
        for k in sorted(sess_keys):
            lb = _labels(k)
            print(style.dim(f"    {lb.get('session', '?'):<24}"
                            f"{series[k] / 1e6:10.2f} MB"))
        any_out = True
    if not any_out:
        print(style.dim(
            "\n  No KV series captured. Serve a paged engine with "
            "ROUNDTABLE_TELEMETRY=1 (kv_layout: paged) to populate the "
            "ledger, prefix-cache and offload series.\n"))
    print("")
    return 0


# --- `roundtable status --perf` (ISSUE 6) ---


def _series_for_perf(session) -> dict[str, float]:
    """Perf registry series, compact-key → value: the session's
    metrics.prom export where present, overlaid with the LIVE registry
    when this process is serving (live values are fresher)."""
    from ..utils import telemetry

    series: dict[str, float] = {}
    prom = Path(session.path) / "telemetry" / "metrics.prom"
    if prom.exists():
        for ln in prom.read_text(encoding="utf-8").splitlines():
            if not ln or ln.startswith("#") or "_bucket{" in ln:
                continue
            key, _, val = ln.rpartition(" ")
            try:
                series[key.replace('"', "")] = float(val)
            except ValueError:
                continue
    series.update(telemetry.REGISTRY.snapshot_compact())
    return series


def _labels(key: str) -> dict[str, str]:
    if "{" not in key:
        return {}
    body = key[key.index("{") + 1:key.rindex("}")]
    return dict(part.split("=", 1) for part in body.split(",") if "=" in
                part)


def _by_engine(series: dict[str, float],
               name: str) -> dict[str, tuple[float, dict]]:
    """{engine: (value, labels)} for one series name."""
    out: dict[str, tuple[float, dict]] = {}
    for key, val in series.items():
        if key.split("{", 1)[0] != name:
            continue
        labels = _labels(key)
        eng = labels.get("engine", "?")
        out[eng] = (val, labels)
    return out


def perf_status(session) -> int:
    """`roundtable status --perf` — live performance attribution from
    the unified registry (ISSUE 6): the per-engine roofline table
    (ceiling, bw_utilization, MFU), the compile observatory's history
    and steady-state sentinel state, the memory ledger, and the
    span-tree overhead breakdown."""
    from ..utils import perfmodel, telemetry

    print(style.bold(f"\n  Performance — session {session.name}"))
    series = _series_for_perf(session)
    perf = perfmodel.perf_series(series)

    # --- roofline table ---
    ceilings = _by_engine(perf, "roundtable_decode_ceiling_tps")
    engines = sorted(
        set(ceilings)
        | {lb.get("engine", "?") for k in perf
           for lb in [_labels(k)] if "engine" in lb})
    if engines and any(k.split("{")[0].startswith(
            ("roundtable_decode", "roundtable_bw", "roundtable_mfu"))
            for k in perf):
        print(style.bold("\n  Roofline (per engine):"))
        print(style.dim("    engine            ceiling_tps  decode_tps"
                        "  bw_util    mfu"))
        for eng in engines:
            def val(name, phase=None):
                for key, v in perf.items():
                    if key.split("{", 1)[0] != name:
                        continue
                    lb = _labels(key)
                    if lb.get("engine") != eng:
                        continue
                    if phase and lb.get("phase") != phase:
                        continue
                    return v
                return None

            def fmt(v, pct=False):
                if v is None:
                    return "      -"
                return f"{v * 100:6.1f}%" if pct else f"{v:10.1f}"

            print(style.dim(
                f"    {eng:<18}{fmt(val('roundtable_decode_ceiling_tps'))}"
                f"{fmt(val('roundtable_decode_tps'))}"
                f"  {fmt(val('roundtable_bw_utilization', 'decode'), True)}"
                f"{fmt(val('roundtable_mfu', 'prefill'), True)}"))

    # --- compile observatory ---
    from ..engine import compile_watch
    summary = compile_watch.summary(recent=6)
    print(style.bold("\n  Compile observatory:"))
    print(style.dim(
        f"    mode={summary['mode']}  compiles={summary['compiles']}  "
        f"cache_hits={summary['cache_hits']}  "
        f"steady_state={summary['steady_state'] or 'not declared'}  "
        f"steady_compiles={summary['steady_state_compiles']}"
        + ("  STRICT" if summary["strict"] else "")))
    for e in summary.get("recent", []):
        flag = " [STEADY-STATE]" if e.get("steady_state") else ""
        hit = " (cache hit)" if e.get("cache_hit") else ""
        print(style.dim(f"    {e['label']:<32} {e['dur_s']:>8.3f}s"
                        f"{hit}{flag}"))
    total = sum(v for k, v in perf.items()
                if k.split("{")[0] == "roundtable_compiles_total")
    steady = sum(v for k, v in perf.items()
                 if k.split("{")[0]
                 == "roundtable_steady_state_compiles_total")
    if total:
        print(style.dim(f"    registry: {total:g} compiles recorded, "
                        f"{steady:g} in steady state"))

    # --- memory ledger ---
    mem_keys = [k for k in perf if k.split("{")[0].startswith(
        ("roundtable_kv_", "roundtable_hbm_"))]
    if mem_keys:
        print(style.bold("\n  Memory ledger:"))
        for k in sorted(mem_keys):
            print(style.dim(f"    {k} {perf[k]:g}"))
    sess_keys = [k for k in perf
                 if k.split("{")[0] == "roundtable_session_kv_bytes"
                 and perf[k] > 0]
    if sess_keys:
        print(style.bold("\n  Per-session KV footprint:"))
        for k in sorted(sess_keys):
            lb = _labels(k)
            print(style.dim(f"    {lb.get('session', '?'):<24}"
                            f"{perf[k] / 1e6:10.2f} MB"))

    # --- span-tree overheads ---
    spans = telemetry.recorder().span_events()
    if not spans:
        spans_file = Path(session.path) / "telemetry" / "spans.jsonl"
        if spans_file.exists():
            import json as _json
            spans = []
            for ln in spans_file.read_text(encoding="utf-8").splitlines():
                try:
                    spans.append(_json.loads(ln))
                except ValueError:
                    continue
    over = perfmodel.span_overheads(spans) if spans else {}
    rungs = {k: v for k, v in over.items() if isinstance(v, dict)}
    if rungs:
        print(style.bold("\n  Overhead breakdown (per rung):"))
        print(style.dim("    rung        total_s  dispatch  host_sync"
                        "   gap"))
        for rung, a in sorted(rungs.items()):
            print(style.dim(
                f"    {rung:<10}{a['total_s']:>9.3f}"
                f"  {a['dispatch_frac'] * 100:6.1f}%"
                f"  {a['host_sync_frac'] * 100:7.1f}%"
                f"  {a['gap_frac'] * 100:5.1f}%"))
        if "queue_wait_s" in over:
            print(style.dim(
                f"    queue wait  {over['queue_wait_s']:.3f}s total"))
    if not perf and not spans:
        print(style.dim(
            "\n  No perf series captured. Serve with "
            "ROUNDTABLE_TELEMETRY=1 (and on CPU set "
            "ROUNDTABLE_PERF_CHIP=v5e for an assumed roofline).\n"))
    print("")
    return 0
