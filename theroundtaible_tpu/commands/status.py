"""`roundtable status` — show the latest session.

Parity with reference src/commands/status.ts:11-77.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from ..utils.session import find_latest_session
from ..utils.ui import style

PHASE_DISPLAY = {
    "discussing": ("⚔️", "The knights are discussing", style.blue),
    "consensus_reached": ("✓", "Consensus reached", style.green),
    "escalated": ("!", "Escalated to the King", style.yellow),
    "applying": ("…", "The Lead Knight is applying the decision", style.cyan),
    "completed": ("✓", "Completed", style.green),
}

DECISIONS_PREVIEW_LINES = 10


def phase_display(status) -> tuple[str, str, object]:
    """(icon, label, color) for a SessionStatus, rejection-aware.

    The reference writes phase "consensus_reached" for unanimous rejection
    too (orchestrator.ts:616) and can't distinguish them afterward; we
    persist `unanimous_rejection` in status.json so the session lists
    don't misreport a rejected idea as an agreed decision.
    """
    if status.phase == "consensus_reached" and status.unanimous_rejection:
        return ("✗", "Unanimously rejected", style.red)
    return PHASE_DISPLAY.get(status.phase, ("?", status.phase, style.white))


def status_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    session = find_latest_session(project_root)
    if session is None:
        print(style.dim("\n  No sessions yet. "
                        'Start one with "roundtable discuss".\n'))
        return 0

    print(style.bold(f"\n  Latest session: {session.name}"))
    if session.topic:
        print(f"  Topic: {session.topic}")
    if session.status:
        s = session.status
        icon, label, color = phase_display(s)
        print(f"  Phase: {color(f'{icon} {label}')}")
        print(f"  Round: {s.round}")
        # consensus_reached is True for unanimous rejection too (schema
        # parity with the reference) — the display must not contradict
        # the rejection phase line above it
        consensus = ("unanimous rejection" if s.unanimous_rejection
                     else "yes" if s.consensus_reached else "no")
        print(f"  Consensus: {consensus}")
        if s.current_knight:
            print(f"  Current knight: {s.current_knight}")
        if s.lead_knight:
            print(f"  Lead knight: {s.lead_knight}")
        print(style.dim(f"  Started: {s.started_at}"))
        print(style.dim(f"  Updated: {s.updated_at}"))

    decisions = Path(session.path) / "decisions.md"
    if decisions.exists():
        lines = decisions.read_text(encoding="utf-8").split("\n")
        print(style.bold("\n  Decision preview:"))
        for line in lines[:DECISIONS_PREVIEW_LINES]:
            print(style.dim(f"    {line}"))
        if len(lines) > DECISIONS_PREVIEW_LINES:
            print(style.dim("    ..."))
    print("")
    return 0
