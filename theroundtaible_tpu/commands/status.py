"""`roundtable status` — show the latest session.

Parity with reference src/commands/status.ts:11-77.
"""

from __future__ import annotations

import os
from pathlib import Path
from typing import Optional

from ..utils.session import find_latest_session
from ..utils.ui import style

PHASE_DISPLAY = {
    "discussing": ("⚔️", "The knights are discussing", style.blue),
    "consensus_reached": ("✓", "Consensus reached", style.green),
    "escalated": ("!", "Escalated to the King", style.yellow),
    "applying": ("…", "The Lead Knight is applying the decision", style.cyan),
    "completed": ("✓", "Completed", style.green),
}

DECISIONS_PREVIEW_LINES = 10


def phase_display(status) -> tuple[str, str, object]:
    """(icon, label, color) for a SessionStatus, rejection-aware.

    The reference writes phase "consensus_reached" for unanimous rejection
    too (orchestrator.ts:616) and can't distinguish them afterward; we
    persist `unanimous_rejection` in status.json so the session lists
    don't misreport a rejected idea as an agreed decision.
    """
    if status.phase == "consensus_reached" and status.unanimous_rejection:
        return ("✗", "Unanimously rejected", style.red)
    return PHASE_DISPLAY.get(status.phase, ("?", status.phase, style.white))


def status_command(project_root: Optional[str] = None,
                   telemetry_view: bool = False) -> int:
    project_root = project_root or os.getcwd()
    session = find_latest_session(project_root)
    if session is None:
        print(style.dim("\n  No sessions yet. "
                        'Start one with "roundtable discuss".\n'))
        return 0
    if telemetry_view:
        return telemetry_status(session)

    print(style.bold(f"\n  Latest session: {session.name}"))
    if session.topic:
        print(f"  Topic: {session.topic}")
    if session.status:
        s = session.status
        icon, label, color = phase_display(s)
        print(f"  Phase: {color(f'{icon} {label}')}")
        print(f"  Round: {s.round}")
        # consensus_reached is True for unanimous rejection too (schema
        # parity with the reference) — the display must not contradict
        # the rejection phase line above it
        consensus = ("unanimous rejection" if s.unanimous_rejection
                     else "yes" if s.consensus_reached else "no")
        print(f"  Consensus: {consensus}")
        if s.current_knight:
            print(f"  Current knight: {s.current_knight}")
        if s.lead_knight:
            print(f"  Lead knight: {s.lead_knight}")
        print(style.dim(f"  Started: {s.started_at}"))
        print(style.dim(f"  Updated: {s.updated_at}"))

    decisions = Path(session.path) / "decisions.md"
    if decisions.exists():
        lines = decisions.read_text(encoding="utf-8").split("\n")
        print(style.bold("\n  Decision preview:"))
        for line in lines[:DECISIONS_PREVIEW_LINES]:
            print(style.dim(f"    {line}"))
        if len(lines) > DECISIONS_PREVIEW_LINES:
            print(style.dim("    ..."))
    print("")
    return 0


METRICS_PREVIEW_LINES = 40
SPAN_PREVIEW_LINES = 8


def telemetry_status(session) -> int:
    """`roundtable status --telemetry` — render the latest session's
    view of the unified registry (ISSUE 5): the per-round Prometheus
    snapshot metrics.json's writer drops, the span-tree summary from
    spans.jsonl, and any flight-recorder dumps. All file-based: the
    serving process owns the live registry; these files are its
    per-round export (plus this process's own registry when serving
    in-process, e.g. `roundtable serve` foreground)."""
    import json as _json

    from ..utils import telemetry

    tdir = Path(session.path) / "telemetry"
    print(style.bold(f"\n  Telemetry — session {session.name}"))
    if not tdir.exists() and not telemetry.ACTIVE:
        print(style.dim(
            "  No telemetry captured. Run with ROUNDTABLE_TELEMETRY=1 "
            "to arm span tracing and the registry snapshot.\n"))
        return 0

    prom = tdir / "metrics.prom"
    if prom.exists():
        print(style.bold("\n  Registry snapshot (metrics.prom):"))
        lines = [ln for ln in
                 prom.read_text(encoding="utf-8").splitlines()
                 if ln and not ln.startswith("#")
                 and "_bucket{" not in ln]
        for ln in lines[:METRICS_PREVIEW_LINES]:
            print(style.dim(f"    {ln}"))
        if len(lines) > METRICS_PREVIEW_LINES:
            print(style.dim(f"    ... ({len(lines)} series total)"))
    elif telemetry.ACTIVE:
        # In-process view (serve foreground / tests): the live registry.
        print(style.bold("\n  Registry (live, this process):"))
        for k, v in sorted(
                telemetry.REGISTRY.snapshot_compact().items()):
            print(style.dim(f"    {k} {v:g}"))

    spans = tdir / "spans.jsonl"
    if spans.exists():
        per_rung: dict[str, int] = {}
        total = 0
        tail: list[dict] = []
        for line in spans.read_text(encoding="utf-8").splitlines():
            try:
                rec = _json.loads(line)
            except ValueError:
                continue
            total += 1
            per_rung[rec.get("rung", "?")] = \
                per_rung.get(rec.get("rung", "?"), 0) + 1
            tail.append(rec)
        print(style.bold(f"\n  Spans ({total} in spans.jsonl):"))
        print(style.dim("    " + "  ".join(
            f"{r}:{per_rung[r]}" for r in sorted(per_rung))))
        for rec in tail[-SPAN_PREVIEW_LINES:]:
            attrs = rec.get("attrs", {})
            who = attrs.get("session") or attrs.get("engine") or ""
            print(style.dim(
                f"    {rec.get('rung', '?'):<10} "
                f"{rec.get('dur_s', 0):>9.3f}s  "
                f"{rec.get('status', '')}  {who}"))

    dumps = sorted(Path(telemetry.dump_dir()).glob("flight-*.json")) \
        if Path(telemetry.dump_dir()).exists() else []
    if dumps:
        print(style.bold(f"\n  Flight-recorder dumps ({len(dumps)}):"))
        for p in dumps[-5:]:
            print(style.dim(f"    {p}"))
    print("")
    return 0
