"""`roundtable list` — all sessions, newest first.

Parity with reference src/commands/list.ts:4-64.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.session import list_sessions
from ..utils.ui import style
from .status import phase_display


def list_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    sessions = list_sessions(project_root)
    if not sessions:
        print(style.dim("\n  No sessions yet. "
                        'Start one with "roundtable discuss".\n'))
        return 0

    print(style.bold(f"\n  {len(sessions)} session(s):\n"))
    for s in sessions:
        if s.status:
            icon, label, color = phase_display(s.status)
        else:
            icon, label, color = "?", "?", style.white
        rounds = s.status.round if s.status else 0
        topic = s.topic or "(no topic)"
        if len(topic) > 60:
            topic = topic[:57] + "..."
        print(f"  {color(icon)} {style.bold(s.name)}")
        print(f"    {topic}")
        print(style.dim(f"    {label} — {rounds} round(s)"))
        print("")
    return 0
