"""`roundtable summon` — review the current git diff.

Parity with reference src/commands/summon.ts:10-52.
"""

from __future__ import annotations

import os
import re
from concurrent.futures import ThreadPoolExecutor
from typing import Optional

from ..core.config import load_config
from ..utils.git import get_git_branch, get_git_diff, get_recent_commits
from ..utils.ui import style
from .discuss import discuss_command

DIFF_PREVIEW_CHARS = 500


def summon_command(project_root: Optional[str] = None,
                   read_code: Optional[bool] = None) -> int:
    project_root = project_root or os.getcwd()
    load_config(project_root)  # existence/validity check; errors propagate

    print(style.dim("\n  Reading the git scrolls...\n"))
    with ThreadPoolExecutor(max_workers=3) as pool:
        diff_f = pool.submit(get_git_diff, project_root)
        branch_f = pool.submit(get_git_branch, project_root)
        commits_f = pool.submit(get_recent_commits, 3, project_root)
        diff, branch, commits = diff_f.result(), branch_f.result(), \
            commits_f.result()

    if not diff:
        print(style.yellow("  Nothing to review. The code rests in peace."))
        print(style.dim("  Make some changes first, then summon again.\n"))
        return 0

    file_count = len(re.findall(r"^diff --git", diff, re.MULTILINE))
    print(style.dim(f"  Branch: {branch or 'unknown'}"))
    print(style.dim(f"  Changed files: {file_count}"))
    if commits:
        print(style.dim("  Recent commits:"))
        for line in commits.split("\n")[:3]:
            print(style.dim(f"    {line}"))

    diff_preview = " ".join(diff[:DIFF_PREVIEW_CHARS].split())
    topic = (f'Review the current changes on branch "{branch or "unknown"}". '
             f"{file_count} file(s) changed. Diff preview: {diff_preview}")

    print(style.bold("\n  The knights shall review your changes...\n"))
    return discuss_command(topic, read_code=read_code,
                           project_root=project_root)
