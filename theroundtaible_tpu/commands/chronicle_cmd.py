"""`roundtable chronicle` — pretty-print the decision chronicle.

Parity with reference src/commands/chronicle.ts:9-60 (tolerates a missing
config by falling back to the default chronicle path).
"""

from __future__ import annotations

import os
import re
from typing import Optional

from ..core.config import load_config
from ..core.errors import ConfigError
from ..utils.chronicle import read_chronicle
from ..utils.ui import style


def chronicle_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    try:
        chronicle_path = load_config(project_root).chronicle
    except ConfigError:
        chronicle_path = ".roundtable/chronicle.md"

    content = read_chronicle(project_root, chronicle_path)
    if not content.strip():
        print(style.dim("\n  The chronicle is empty. "
                        "No decisions have been recorded yet.\n"))
        return 0

    decisions = len(re.findall(r"^## ", content, re.MULTILINE))
    print(style.bold(f"\n  The Chronicle — {decisions} decision(s)\n"))
    for line in content.split("\n"):
        if line.startswith("## "):
            print(style.bold(style.cyan(f"  {line[3:]}")))
        elif line.startswith("# "):
            print(style.bold(f"  {line[2:]}"))
        elif line.startswith("**"):
            print(style.dim(f"  {line}"))
        else:
            print(f"  {line}")
    print("")
    return 0
