"""`roundtable lint` — the serving-invariant analyzer (ISSUE 15).

Runs the AST rule engine (analysis/rules, allowlist-filtered) over the
source tree and, with --jaxpr, the device-free jaxpr audit of every
registered serving program on two toy CPU engines (contiguous, and
paged + ragged + spec-tree + LoRA — together they register every
program family: prefill, decode, ragged, spec-verify, propose,
LoRA-setter). Exit code 1 on any unallowlisted finding — the CI /
tunnel-preflight contract: a statically detectable violation must
never cost a hardware window.
"""

from __future__ import annotations

import json
import os
import sys
from typing import Optional


def _source_root() -> str:
    """The tree to lint: the checkout containing this package (the
    package dir's parent), which is also where README/pyproject live."""
    import theroundtaible_tpu

    return os.path.dirname(
        os.path.dirname(os.path.abspath(theroundtaible_tpu.__file__)))


def _audit_findings() -> tuple[list, list[str]]:
    """Build the two toy CPU engines and run the jaxpr audit; returns
    (findings, audited program names). Forces the CPU platform BEFORE
    first jax import — the audit is device-free by construction and
    must never touch (or wait on) a TPU."""
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    os.environ.setdefault("ROUNDTABLE_DISABLE_TPU_DETECT", "1")
    from ..analysis.jaxpr_audit import audit_programs, collect_programs
    from ..engine.engine import InferenceEngine
    from ..engine.models.registry import get_model_config

    cfg = get_model_config("tiny-gemma", max_seq_len=512)
    engines = [
        InferenceEngine(cfg, num_slots=4, kv_layout="contiguous",
                        mesh_shape={"data": 1, "model": 1}),
        InferenceEngine(cfg, num_slots=4, kv_layout="paged",
                        mesh_shape={"data": 1, "model": 1},
                        spec_decode={"drafter": "ngram",
                                     "tree": {"branch": 2, "depth": 2}},
                        lora={"rank": 4, "max_adapters": 4}),
    ]
    findings, names = [], []
    for eng in engines:
        specs = collect_programs(eng)
        names.extend(s.name for s in specs)
        findings.extend(audit_programs(specs))
    return findings, sorted(set(names))


def lint_command(rules: Optional[list[str]] = None, jaxpr: bool = False,
                 as_json: bool = False,
                 root: Optional[str] = None) -> int:
    from ..analysis import run_lint, unallowlisted
    from ..analysis.astlint import LintConfigError

    root = root or _source_root()
    programs: list[str] = []
    audit: list = []
    extra_active = None
    if jaxpr:
        # Audit first: its findings must enter run_lint BEFORE the
        # allowlist applies, so a `<jaxpr:...>` finding suppresses
        # through the same [[allow]] mechanism as the AST half.
        from ..analysis.jaxpr_audit import JAXPR_RULE_IDS
        audit, programs = _audit_findings()
        extra_active = set(JAXPR_RULE_IDS)
    try:
        findings = run_lint(root, rule_ids=rules,
                            extra_findings=audit,
                            extra_active=extra_active)
    except (LintConfigError, ValueError) as e:
        print(f"lint configuration error: {e}", file=sys.stderr)
        return 2
    bad = unallowlisted(findings)

    if as_json:
        print(json.dumps({
            "root": root,
            "findings": [f.to_dict() for f in findings],
            "unallowlisted": len(bad),
            "allowlisted": sum(1 for f in findings if f.allowed),
            "jaxpr_programs": programs,
            "clean": not bad,
        }, indent=2))
        return 1 if bad else 0

    for f in findings:
        if not f.allowed:
            print(f.render())
    n_allowed = sum(1 for f in findings if f.allowed)
    if bad:
        print(f"\nroundtable lint: {len(bad)} finding(s) "
              f"({n_allowed} allowlisted)", file=sys.stderr)
        return 1
    suffix = (f" — jaxpr audit covered {len(programs)} program "
              "families" if jaxpr else "")
    print(f"roundtable lint: clean ({n_allowed} allowlisted "
          f"finding(s)){suffix}")
    return 0
