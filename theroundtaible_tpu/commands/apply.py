"""`roundtable apply` — Lead Knight executes the consensus decision.

The documented pipeline (reference README.md:159-207, TODO.md:87-138;
SURVEY.md §2.2): load the latest consensus session → build the apply
prompt (decision + in-scope sources + BLOCK_MAPs + editing rules) →
Lead Knight emits RTDIFF/1 → parse → validate (scope, blocks, sha256
integrity) → parley per file (default) → backup → write → manifest
auto-update → decree on scope override.

Flags: --noparley (write without per-file approval), --dry-run (full
pipeline, no writes), --override-scope (typed YES + reason, audited to the
decree log, reference README.md:206 + TODO.md:87).
"""

from __future__ import annotations

import os
import re
from pathlib import Path
from typing import Optional

from ..adapters.factory import initialize_adapters
from ..apply import (
    ParseError,
    apply_edits,
    parse_knight_output,
    validate_edits,
)
from ..apply.prompt import build_apply_prompt
from ..core.config import load_config
from ..core.errors import FileWriteError, SessionError
from ..core.orchestrator import execute_with_fallback
from ..utils.decree_log import add_decree_entry
from ..utils.manifest import (
    add_manifest_entry,
    get_feature_summary,
    topic_to_feature_id,
)
from ..core.types import ManifestEntry
from ..utils.session import (
    find_latest_session,
    now_iso,
    read_status,
    update_status,
)
from ..utils.ui import style


def _ask(prompt: str) -> str:
    try:
        return input(prompt)
    except EOFError:
        return ""


def _read_topic(session_path: str) -> str:
    topic_path = Path(session_path) / "topic.md"
    if topic_path.is_file():
        raw = topic_path.read_text(encoding="utf-8")
        m = re.search(r"^# Topic\s*\n\n(.+)", raw, re.MULTILINE)
        return (m.group(1).strip() if m else raw.strip())
    return Path(session_path).name


def _confirm_override(project_root: str, session_name: str,
                      topic: str) -> bool:
    """Typed-YES confirmation + reason, audited to the decree log
    (reference README.md:206, TODO.md:87; decree type override_scope)."""
    print(style.yellow("\n  You are about to BYPASS the agreed file "
                       "scope. The knights negotiated that scope for a "
                       "reason."))
    answer = _ask("  Type YES (all caps) to proceed: ").strip()
    if answer != "YES":
        print(style.dim("  Scope override cancelled."))
        return False
    reason = _ask("  Reason (for the audit log): ").strip()
    add_decree_entry(project_root, "override_scope", session_name, topic,
                     reason or "no reason given")
    return True


def _parley(path: str, new_text: str, state: dict) -> bool:
    """Per-file approval (reference architecture-docs.md:215-217:
    'Parley mode (default): each file shown for approval before
    writing')."""
    if state.get("all"):
        return True
    n_lines = len(new_text.splitlines())
    print(style.bold(f"\n  ── parley: {path} ({n_lines} lines) ──"))
    for line in new_text.splitlines()[:20]:
        print(style.dim(f"  {line[:100]}"))
    if n_lines > 20:
        print(style.dim(f"  … {n_lines - 20} more lines"))
    while True:
        ans = _ask("  Write this file? [y]es / [n]o / [a]ll / "
                   "[q]uit: ").strip().lower()
        if ans in ("y", "yes"):
            return True
        if ans in ("n", "no"):
            return False
        if ans in ("a", "all"):
            state["all"] = True
            return True
        if ans in ("q", "quit"):
            raise KeyboardInterrupt


def apply_command(noparley: bool = False, dry_run: bool = False,
                  override_scope: bool = False,
                  project_root: Optional[str] = None,
                  session_name: Optional[str] = None,
                  result: Optional[dict] = None) -> int:
    """`result`, when given, receives {"written": [...]} so callers (code-red
    fix-now) can distinguish a real apply from an all-skipped rc==0 run."""
    if result is not None:
        result.setdefault("written", [])
    project_root = project_root or os.getcwd()
    config = load_config(project_root)

    # --- locate the session to apply ---
    if session_name:
        session_path = str(Path(project_root) / ".roundtable" / "sessions"
                           / session_name)
        if not Path(session_path).is_dir():
            raise SessionError(f"session {session_name} not found")
        status = read_status(session_path)
    else:
        latest = find_latest_session(project_root)
        if latest is None:
            raise SessionError(
                "no sessions found — hold a discussion first",
                hint='roundtable discuss "your topic"')
        session_path, status = latest.path, latest.status
        session_name = latest.name
    if status is None or not status.consensus_reached:
        raise SessionError(
            "the latest session has no consensus to apply",
            hint="reach consensus first (roundtable discuss), or pass "
                 "--session for one that did")

    decisions_path = Path(session_path) / "decisions.md"
    if not decisions_path.is_file():
        raise SessionError("decisions.md missing from the session")
    decision = decisions_path.read_text(encoding="utf-8")
    topic = _read_topic(session_path)

    # Old sessions without scope data work normally — no enforcement
    # (reference README.md:207).
    allowed_files = status.allowed_files or None
    if allowed_files is None:
        print(style.dim("\n  No scope data in this session — scope "
                        "enforcement skipped (old session)."))

    override_active = False
    if override_scope:
        if not _confirm_override(project_root, session_name, topic):
            return 1
        override_active = True

    # --- seat the Lead Knight ---
    adapters = initialize_adapters(config)
    if not adapters:
        raise SessionError("no knights available to execute the decision")
    lead = next((k for k in config.knights
                 if k.name == status.lead_knight), None) \
        or min(config.knights, key=lambda k: k.priority)
    adapter = adapters.get(lead.adapter)
    if adapter is None:
        lead = next((k for k in config.knights if k.adapter in adapters),
                    None)
        if lead is None:
            raise SessionError("no seated adapter for any knight")
        adapter = adapters[lead.adapter]
    print(style.cyan(f"\n  Lead Knight {style.bold(lead.name)} takes up "
                     "the sword."))

    # --- build prompt, execute, parse ---
    ctx = build_apply_prompt(project_root, topic, decision,
                             allowed_files or [])
    update_status(session_path, phase="applying")
    timeout_ms = config.rules.timeout_per_turn_seconds * 1000

    from .reporter import ConsoleReporter
    response, _served_by = execute_with_fallback(
        adapter, lead, config, ctx.prompt, timeout_ms, adapters,
        ConsoleReporter())

    try:
        parsed = parse_knight_output(response)
    except ParseError as e:
        update_status(session_path, phase="consensus_reached")
        raise FileWriteError(
            f"the Lead Knight's output was not applicable: {e}",
            hint="re-run apply; knight output varies between attempts")
    if parsed.legacy:
        print(style.yellow("  ⚠ knight used the deprecated EDIT: format "
                           "— applied via search/replace"))

    # --- validate (all-or-nothing, reference TODO.md:141-144) ---
    issues = validate_edits(parsed, project_root, allowed_files,
                            ctx.source_hashes,
                            override_scope=override_active)
    fatal = [i for i in issues if i.fatal]
    if fatal:
        update_status(session_path, phase="consensus_reached")
        print(style.red(f"\n  Validation blocked the apply "
                        f"({len(fatal)} issue(s), nothing written):"))
        for i in fatal:
            print(style.red(f"    ✗ {i.path}: {i.message}"))
        return 4
    for w in parsed.warnings:
        print(style.dim(f"  note: {w}"))

    # --- parley + write ---
    state = {"all": noparley or dry_run}
    try:
        outcome = apply_edits(
            parsed.edits, project_root, session_name,
            approve=lambda p, t: _parley(p, t, state), dry_run=dry_run)
    except KeyboardInterrupt:
        update_status(session_path, phase="consensus_reached")
        print(style.dim("\n  Apply adjourned — nothing more written."))
        return 1

    if dry_run:
        print(style.green(f"\n  DRY RUN — {len(outcome.written)} file(s) "
                          "would be written:"))
        for f in outcome.written:
            print(style.dim(f"    ~ {f}"))
        update_status(session_path, phase="consensus_reached")
        return 0

    for f in outcome.written:
        print(style.green(f"    ✓ {f}"))
    for f in outcome.skipped:
        print(style.yellow(f"    − {f} (skipped at parley)"))
    if outcome.backup_dir:
        print(style.dim(f"  Backups: {outcome.backup_dir}"))

    # --- manifest auto-update (reference README.md:177-179) ---
    manifest_status = "implemented" if not outcome.skipped else "partial"
    add_manifest_entry(project_root, ManifestEntry(
        id=topic_to_feature_id(topic),
        session=session_name,
        status=manifest_status,
        files=outcome.written,
        files_skipped=outcome.skipped or None,
        summary=get_feature_summary(session_path, topic),
        applied_at=now_iso(),
        lead_knight=lead.name,
    ))
    update_status(session_path, phase="completed")
    if result is not None:
        result["written"] = list(outcome.written)
    print(style.bold(style.green(
        f"\n  The decision has been carried out — {len(outcome.written)} "
        f"file(s) written ({manifest_status}).")))
    return 0
