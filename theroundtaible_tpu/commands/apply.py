"""`roundtable apply` — Lead Knight executes the consensus decision.

Full implementation lands with the RTDIFF/1 pipeline (reference behavior
documented in README.md:159-207 / TODO.md:87-138; SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Optional

from ..utils.ui import style


def apply_command(noparley: bool = False, dry_run: bool = False,
                  override_scope: bool = False,
                  project_root: Optional[str] = None) -> int:
    print(style.yellow("\n  The apply pipeline is being forged "
                       "(RTDIFF/1 block edits, scope enforcement, parley)."))
    print(style.dim("  Until then: read decisions.md and wield the sword "
                    "yourself.\n"))
    return 1
