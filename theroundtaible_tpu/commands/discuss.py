"""`roundtable discuss` — the main command.

Parity with reference src/commands/discuss.ts:39-260: adapter seating, the
read-codebase question, the discussion loop, and the King's Choice menu on
no-consensus (pick a knight's proposal 1..N, or send them back for
unanimity, which resumes the same session). On the King's choice a decree
entry is written (the reference's storage side exists but nothing writes —
SURVEY.md §2.2 third bullet; we close that gap).
"""

from __future__ import annotations

import os
import re
from dataclasses import dataclass
from typing import Optional

from ..adapters.factory import initialize_adapters
from ..core.config import load_config
from ..core.errors import ConfigError
from ..core.orchestrator import run_discussion
from ..core.types import ContinueOptions, RoundEntry, SessionResult
from ..utils.decree_log import add_decree_entry
from ..utils.session import update_status, write_decisions
from ..utils.ui import ask, ask_yes_no, knight_color, style
from .reporter import ConsoleReporter


def ask_read_codebase() -> bool:
    """[Y/N] read-codebase question (reference discuss.ts:16-33)."""
    print(style.bold("  Shall the knights read the codebase first?\n"))
    print(f"  {style.bold('Y.')} {style.cyan('Yes')} — full codebase scan "
          "(more context, better proposals)")
    print(f"  {style.bold('N.')} {style.dim('No')} — topic only "
          "(faster, cheaper)\n")
    answer = ask_yes_no(style.bold(style.yellow("  Read codebase?")),
                        default=False)
    if answer:
        print(style.cyan(
            "\n  The knights will study the codebase before debating.\n"))
    else:
        print(style.dim("\n  Topic only. The knights go in blind.\n"))
    return answer


def continue_command(read_code: Optional[bool] = None,
                     project_root: Optional[str] = None) -> int:
    """`discuss --continue`: resume the latest unfinished session after a
    crash/interrupt (reference future work TODO.md:179). The transcript is
    rebuilt from the session's transcript.json; knights pick up at the
    next round with no King's ultimatum injected."""
    from pathlib import Path

    from ..utils.session import find_latest_session, read_transcript

    project_root = project_root or os.getcwd()
    session = find_latest_session(project_root)  # SessionInfo, not a path
    if session is None:
        print(style.dim("\n  No sessions to continue.\n"))
        return 1
    status = session.status
    if status is None or status.phase not in ("discussing", "escalated"):
        print(style.dim(
            f"\n  Latest session ({session.name}) is not resumable "
            f"(phase: {status.phase if status else 'unknown'}).\n"))
        return 1
    rounds = read_transcript(session.path)
    if not rounds:
        print(style.yellow(
            "\n  No transcript.json in the session — nothing to rebuild "
            "from (sessions from older versions can't be continued).\n"))
        return 1
    topic_file = Path(session.path) / "topic.md"
    topic = ""
    if topic_file.exists():
        for line in topic_file.read_text(encoding="utf-8").splitlines():
            if line.strip() and not line.startswith("#"):
                topic = line.strip()
                break
    last_round = max(e.round for e in rounds)
    print(style.bold(f"\n  Resuming: {session.name} "
                     f"(round {last_round} done)\n"))
    continue_from = ContinueOptions(
        session_path=session.path, all_rounds=rounds,
        start_round=last_round + 1, king_demand=False)
    return discuss_command(topic or "(resumed session)", read_code,
                           project_root, continue_from=continue_from)


def discuss_command(topic: str, read_code: Optional[bool] = None,
                    project_root: Optional[str] = None,
                    continue_from: Optional[ContinueOptions] = None) -> int:
    project_root = project_root or os.getcwd()
    config = load_config(project_root)

    print(style.bold(f'\n  Topic: "{topic}"\n'))
    print(style.dim("  Summoning the knights to the table...\n"))

    def seat_event(kind: str, message: str) -> None:
        if kind == "seated":
            print(style.dim(f"  {message}"))
        else:
            print(style.yellow(f"  {message}"))

    adapters = initialize_adapters(config, on_event=seat_event)
    if not adapters:
        raise ConfigError(
            "A roundtable with no knights is just a table.",
            hint="Install at least one AI CLI tool (claude, gemini, codex), "
                 "set an API key, or configure the tpu-llm adapter.")
    print("")

    read_codebase = read_code if read_code is not None else ask_read_codebase()

    reporter = ConsoleReporter()
    result = run_discussion(topic, config, adapters, project_root,
                            read_codebase, continue_from=continue_from,
                            reporter=reporter)

    while True:
        print(style.bold("\n" + "=" * 50))
        if result.consensus:
            if result.unanimous_rejection:
                _handle_rejection(result)
            else:
                _handle_consensus(result)
                _kings_decree(result, topic, project_root)
            break
        action = _handle_no_consensus(result, topic, project_root)
        if action != "send_back":
            break
        print(style.bold("=" * 50))
        continue_from = ContinueOptions(
            session_path=result.session_path,
            all_rounds=result.all_rounds,
            start_round=result.rounds + 1,
            resolved_files=result.resolved_files,
            resolved_commands=result.resolved_commands,
        )
        result = run_discussion(topic, config, adapters, project_root,
                                read_codebase, continue_from=continue_from,
                                reporter=reporter)
    print(style.bold("=" * 50 + "\n"))
    return 0


def _handle_consensus(result: SessionResult) -> None:
    print(style.bold(style.green(
        "  A miracle has occurred. The knights actually agree.")))
    print(style.dim(f"  Rounds: {result.rounds}"))
    print(style.dim(f"  Session: {result.session_path}"))
    print(style.bold("\n  The advice has been recorded."))
    print(style.dim(
        f"  Read the decision: {result.session_path}/decisions.md\n"))


def _kings_decree(result: SessionResult, topic: str,
                  project_root: str) -> None:
    """Post-consensus decree menu: apply now / wield the sword myself /
    decide later (reference architecture-docs.md:209 'King's Choice: apply
    now, do it yourself, or decide later'; decree writes on self/later per
    reference TODO.md:100 — the gap SURVEY.md §2.2 flags). Interactive
    only: scripted/piped runs keep the classic 'run apply yourself' hint.
    """
    import sys
    session_name = os.path.basename(result.session_path)
    if not sys.stdin.isatty():
        print(style.dim("  Execute it with: roundtable apply\n"))
        return
    print(style.bold("\n  What is your decree, Your Majesty?\n"))
    print(f"  {style.bold('1.')} {style.green('Apply now')} — the Lead "
          "Knight executes the decision")
    sword = style.cyan("I will wield the sword myself")
    print(f"  {style.bold('2.')} {sword} — no apply, the King codes it")
    print(f"  {style.bold('3.')} {style.dim('Decide later')} — "
          "adjourn; roundtable apply still works afterwards\n")
    answer = ask(style.bold(style.yellow("  Your decree? [1-3] ")),
                 default="3")
    if answer.strip() == "1":
        from .apply import apply_command
        try:
            apply_command(project_root=project_root)
        except Exception as e:  # apply failures must not unwind discuss
            print(style.red(f"  Apply failed: {e}"))
            print(style.dim("  The decision is saved — retry with "
                            "roundtable apply."))
        return
    if answer.strip() == "2":
        add_decree_entry(project_root, "rejected_no_apply", session_name,
                         topic, "King wields the sword personally")
        print(style.dim("\n  So be it. The code is yours, Your Majesty.\n"))
        return
    add_decree_entry(project_root, "deferred", session_name, topic,
                     "King will decide later")
    print(style.dim("\n  The decision rests. roundtable apply awaits "
                    "your command.\n"))


def _handle_rejection(result: SessionResult) -> None:
    print(style.bold(style.red(
        "  The knights unanimously reject this proposal.")))
    print(style.dim(f"  Rounds: {result.rounds}"))
    print(style.dim(f"  Session: {result.session_path}"))
    print(style.dim(
        "\n  Their reasoning has been recorded in decisions.md."))
    print(style.dim("  Perhaps a wiser question next time, Your Majesty.\n"))


@dataclass
class KnightProposal:
    knight: str
    score: float
    summary: str
    full_response: str


def get_last_proposals(all_rounds: list[RoundEntry]) -> list[KnightProposal]:
    """Latest turn per knight, with a one-line summary
    (reference discuss.ts:229-260)."""
    last_by_knight: dict[str, RoundEntry] = {}
    for entry in all_rounds:
        last_by_knight[entry.knight] = entry
    proposals = []
    for entry in last_by_knight.values():
        score = entry.consensus.consensus_score if entry.consensus else 0
        cleaned = re.sub(r"```json[\s\S]*?```", "", entry.response)
        cleaned = re.sub(r'\{[^{}]*"consensus_score"[^{}]*\}', "", cleaned)
        cleaned = cleaned.strip()
        lines = [l for l in cleaned.split("\n") if len(l.strip()) > 10]
        summary = lines[0].strip() if lines else "No summary available"
        if len(summary) > 80:
            summary = summary[:77] + "..."
        proposals.append(KnightProposal(
            knight=entry.knight, score=score, summary=summary,
            full_response=entry.response))
    return proposals


def _handle_no_consensus(result: SessionResult, topic: str,
                         project_root: str) -> str:
    """King's Choice menu; returns "send_back" or "done"
    (reference discuss.ts:132-217)."""
    print(style.bold(style.yellow(
        "  The knights have agreed to disagree. As usual.")))
    print(style.dim(f"  Rounds: {result.rounds}"))
    print(style.dim(f"  Session: {result.session_path}"))

    proposals = get_last_proposals(result.all_rounds)
    if not proposals:
        print(style.dim(
            "\n  No proposals to choose from. "
            "The knights were useless today."))
        return "done"

    print(style.bold("\n  But YOU are the King. The final word is yours.\n"))
    for i, p in enumerate(proposals):
        score_color = (style.green if p.score >= 9
                       else style.yellow if p.score >= 6 else style.red)
        from ..core.types import format_score
        print(f"  {style.bold(f'{i + 1}.')} "
              f"{knight_color(p.knight, p.knight)} "
              f"{score_color(f'({format_score(p.score)}/10)')} — "
              f"{style.dim(p.summary)}")
    print(f"  {style.bold(f'{len(proposals) + 1}.')} "
          f"{style.dim('Send them back — they must reach unanimity!')}")
    print("")
    answer = ask(style.bold(style.yellow(
        f"  What say you, Your Majesty? [1-{len(proposals) + 1}] ")))
    try:
        choice = int(answer.strip())
    except ValueError:
        choice = -1
    if choice < 1 or choice > len(proposals) + 1:
        print(style.dim(
            "  The King waves dismissively. Perhaps another time."))
        # King walks away without applying — record a deferred decree so the
        # knights don't re-propose blindly (SURVEY.md §2.2 decree gap).
        add_decree_entry(project_root, "deferred",
                         os.path.basename(result.session_path), topic,
                         "King adjourned without a decision")
        return "done"

    if choice == len(proposals) + 1:
        return "send_back"

    chosen = proposals[choice - 1]
    print(style.bold(
        f"\n  The King has chosen "
        f"{knight_color(chosen.knight, chosen.knight)}'s advice. "
        "So it shall be."))
    write_decisions(result.session_path, topic, chosen.full_response,
                    result.all_rounds)
    update_status(result.session_path, phase="consensus_reached",
                  consensus_reached=True)
    print(style.bold("\n  The advice has been recorded."))
    print(style.dim(
        f"  Read the decision: {result.session_path}/decisions.md\n"))
    return "done"
