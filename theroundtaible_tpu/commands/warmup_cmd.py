"""`roundtable warmup` — pre-compile the TPU serving programs.

No reference counterpart (Ollama keeps a resident server; our engine
lives in-process). First-ever serving of a config pays XLA compilation;
with the persistent compilation cache (engine.enable_compilation_cache)
that cost is paid ONCE per config — this command lets the operator pay
it up front instead of inside the first `discuss` round. Subsequent
process starts deserialize from the cache in seconds.
"""

from __future__ import annotations

import os
import time

from ..core.config import load_config
from ..utils.ui import style


def warmup_command(project_root: str | None = None) -> int:
    project_root = project_root or os.getcwd()
    config = load_config(project_root)

    # KNIGHT order, not sorted: the fleet planner assigns device groups
    # by list order, and discuss plans through the factory in knight
    # order — warming a different assignment would compile programs the
    # first discuss never hits.
    tpu_ids = list(dict.fromkeys(
        k.adapter for k in config.knights
        if k.adapter.startswith("tpu-llm")))
    if not tpu_ids:
        print(style.dim("\n  No tpu-llm knights in this config — "
                        "nothing to warm.\n"))
        return 0

    from ..adapters.factory import _plan_tpu_fleet
    from ..engine import get_engine

    # The exact planning pass discuss runs (mutates config.adapter_config
    # in place, so get_engine sees the same device assignments).
    _plan_tpu_fleet(config, None)
    configs = [config.adapter_config.get(a, {}) for a in tpu_ids]

    # Batch sizes the orchestrator will actually dispatch: 1 (serial
    # turns) and the number of knights sharing each adapter (batched
    # rounds).
    knights_per_adapter = {
        a: sum(1 for k in config.knights if k.adapter == a)
        for a in tpu_ids}

    for adapter_id, engine_cfg in zip(tpu_ids, configs):
        n = knights_per_adapter[adapter_id]
        sizes = tuple(sorted({1, n}))
        print(style.dim(f"  Warming {adapter_id} "
                        f"(batch sizes {list(sizes)})..."))
        t0 = time.monotonic()
        engine = get_engine(engine_cfg)
        secs = engine.warmup(batch_sizes=sizes)
        d = engine.describe()
        print(f"  {style.green('✓')} {d['model']} on mesh {d['mesh']}: "
              f"built in {time.monotonic() - t0 - secs:.1f}s, "
              f"warmed in {secs:.1f}s")
    print(style.dim("\n  Programs are in the persistent compilation "
                    "cache — the next discuss starts hot.\n"))
    return 0
