"""`roundtable init` — interactive setup wizard.

Parity with reference src/commands/init.ts:225-439: reinit guard, CLI tool
detection via --version, local-model detection, per-knight seat confirmation
with fallback API-key capture (masked input, saved to the chmod-600
keystore), default rules/capabilities/adapter_config, and the `.roundtable/`
scaffold. TPU addition: when JAX sees an accelerator, the wizard offers
`tpu-llm` knights served by the in-tree engine.
"""

from __future__ import annotations

import json
import os
import subprocess
from pathlib import Path
from typing import Optional

from ..core.types import RoundtableConfig
from ..utils.keys import save_key
from ..utils.local_detect import LocalModel, detect_local_models
from ..utils.session import now_iso
from ..utils.ui import ask, ask_secret, ask_yes_no, style

# Per-tool seat definitions: (adapter id, knight name, CLI command,
# API adapter id, API env key).
CLI_TOOLS = [
    ("claude-cli", "Claude", "claude", "claude-api", "ANTHROPIC_API_KEY"),
    ("gemini-cli", "Gemini", "gemini", "gemini-api", "GEMINI_API_KEY"),
    ("openai-cli", "GPT", "codex", "openai-api", "OPENAI_API_KEY"),
]

DEFAULT_CAPABILITIES = {
    "Claude": ["architecture", "code-quality", "refactoring"],
    "Gemini": ["planning", "big-picture", "research"],
    "GPT": ["implementation", "pragmatism", "shipping"],
}

DEFAULT_RULES = {
    "max_rounds": 5,
    "consensus_threshold": 9,
    "timeout_per_turn_seconds": 120,
    "escalate_to_user_after": 3,
    "auto_execute": False,
    "ignore": [".git", "node_modules", "dist", "build", ".next"],
}


def detect_tools() -> dict[str, bool]:
    """--version probes for claude/gemini/codex (reference init.ts:96-113)."""
    available = {}
    for _, _, command, _, _ in CLI_TOOLS:
        try:
            proc = subprocess.run([command, "--version"], capture_output=True,
                                  timeout=15)
            available[command] = proc.returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            available[command] = False
    return available


def _slug(model_id: str) -> str:
    import re
    return re.sub(r"[^a-z0-9]+", "-", model_id.lower()).strip("-")[:40]


def init_command(version: str, project_root: Optional[str] = None,
                 interactive: Optional[bool] = None) -> int:
    project_root = Path(project_root or os.getcwd())
    rt_dir = project_root / ".roundtable"
    if interactive is None:
        import sys
        interactive = sys.stdin.isatty()

    # Reinit guard (reference init.ts:230-239).
    if (rt_dir / "config.json").exists():
        print(style.yellow("\n  A roundtable already exists in this project."))
        if interactive and not ask_yes_no("  Re-initialize (config will be "
                                          "overwritten)?", default=False):
            print(style.dim("  Kept the existing roundtable.\n"))
            return 0
        if not interactive:
            print(style.dim("  Non-interactive: keeping existing config.\n"))
            return 0

    print(style.bold("\n  ⚔️  Welcome to TheRoundtAIble (TPU edition)\n"))
    project_name = (ask(f"  Project name [{project_root.name}]: ",
                        project_root.name)
                    if interactive else project_root.name)
    language = (ask("  Discussion language [en]: ", "en")
                if interactive else "en")

    print(style.dim("\n  Scouting for knights...\n"))
    tools = detect_tools()
    local_models = detect_local_models()

    knights: list[dict] = []
    adapter_config: dict[str, dict] = {}
    priority = 1

    # CLI/API knights (reference init.ts:296-356).
    for adapter_id, knight_name, command, api_id, env_key in CLI_TOOLS:
        if tools.get(command):
            seat = (not interactive) or ask_yes_no(
                f"  {knight_name} ({command} CLI) is available. Seat them?",
                default=True)
            if not seat:
                continue
            knights.append({
                "name": knight_name, "adapter": adapter_id,
                "capabilities": DEFAULT_CAPABILITIES.get(knight_name, []),
                "priority": priority, "fallback": api_id,
            })
            adapter_config[adapter_id] = {"command": command, "args": []}
            adapter_config.setdefault(api_id, {"env_key": env_key})
            priority += 1
        elif interactive:
            if ask_yes_no(f"  {knight_name} CLI not found. Seat them via "
                          "API key instead?", default=False):
                key = ask_secret(f"  {env_key}: ")
                if key:
                    save_key(env_key, key)
                    print(style.dim("  Key saved to the royal keystore "
                                    "(chmod 600)."))
                knights.append({
                    "name": knight_name, "adapter": api_id,
                    "capabilities": DEFAULT_CAPABILITIES.get(knight_name, []),
                    "priority": priority,
                })
                adapter_config[api_id] = {"env_key": env_key}
                priority += 1

    # Local + TPU knights (reference init.ts:359-384; TPU is our addition).
    for model in local_models:
        if model.source == "tpu":
            seat = (not interactive) or ask_yes_no(
                f"  {model.name} detected. Seat a TPU knight?", default=True)
            if not seat:
                continue
            adapter_id = "tpu-llm"
            knights.append({
                "name": "TPU Sage", "adapter": adapter_id,
                "capabilities": ["local-inference", "tpu"],
                "priority": priority,
            })
            adapter_config[adapter_id] = {
                "name": "TPU Sage",
                "model": "gemma-2b-it",
                "checkpoint": "",
                "max_seq_len": 8192,
                "dtype": "bfloat16",
                "mesh": {"data": 1, "model": 1},
            }
            priority += 1
            continue
        seat = (not interactive) or ask_yes_no(
            f"  Local model {model.name} ({model.source}) detected. "
            "Seat them?", default=True)
        if not seat:
            continue
        adapter_id = f"local-llm-{_slug(model.id)}"
        knights.append({
            "name": model.name, "adapter": adapter_id,
            "capabilities": ["local-inference"],
            "priority": priority,
        })
        adapter_config[adapter_id] = {
            "endpoint": model.endpoint, "model": model.id,
            "name": model.name, "source": model.source,
        }
        priority += 1
        if model.source == "LM Studio":
            print(style.yellow(
                "  Note: set a generous Context Length in LM Studio "
                "(Developer → Model Settings) — it cannot be detected."))

    if not knights:
        print(style.yellow(
            "\n  No knights could be seated. Install claude/gemini/codex, "
            "start Ollama/LM Studio, or run on a TPU host.\n"))
        print(style.dim("  You can edit .roundtable/config.json by hand "
                        "later — writing a config scaffold anyway.\n"))

    config = {
        "version": version,
        "project": project_name,
        "language": language,
        "knights": knights or [{
            "name": "Claude", "adapter": "claude-cli",
            "capabilities": DEFAULT_CAPABILITIES["Claude"],
            "priority": 1, "fallback": "claude-api",
        }],
        "rules": DEFAULT_RULES,
        "chronicle": ".roundtable/chronicle.md",
        "adapter_config": adapter_config or {
            "claude-cli": {"command": "claude", "args": []},
            "claude-api": {"env_key": "ANTHROPIC_API_KEY"},
        },
    }

    # Scaffold (reference init.ts:396-418).
    (rt_dir / "sessions").mkdir(parents=True, exist_ok=True)
    (rt_dir / "config.json").write_text(json.dumps(config, indent=2),
                                        encoding="utf-8")
    chronicle = rt_dir / "chronicle.md"
    if not chronicle.exists():
        chronicle.write_text(
            "# Chronicle - TheRoundtAIble\n\nBeslissingen log van dit "
            "project.\n\n---\n\n", encoding="utf-8")
    manifest = rt_dir / "manifest.json"
    if not manifest.exists():
        manifest.write_text(json.dumps(
            {"version": "1.0", "last_updated": now_iso(), "features": []},
            indent=2), encoding="utf-8")

    print(style.green(f"\n  The roundtable is ready — {len(knights)} "
                      "knight(s) seated."))
    print(style.dim(f"  Config: {rt_dir / 'config.json'}"))
    print(style.dim('  Start a discussion: roundtable discuss "your topic"\n'))
    return 0
