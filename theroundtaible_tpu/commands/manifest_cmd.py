"""`roundtable manifest list|add|deprecate|check`.

Parity with reference src/commands/manifest.ts:13-118.
"""

from __future__ import annotations

import os
from typing import Optional

from ..core.types import ManifestEntry
from ..utils.manifest import (
    add_manifest_entry,
    check_manifest,
    deprecate_feature,
    read_manifest,
)
from ..utils.session import find_latest_session, now_iso
from ..utils.ui import ask, style

STATUS_DISPLAY = {
    "implemented": ("✓", style.green),
    "partial": ("~", style.yellow),
    "deprecated": ("✗", style.dim),
}


def run(args) -> int:
    sub = getattr(args, "manifest_command", None) or "list"
    if sub == "list":
        return manifest_list_command()
    if sub == "add":
        return manifest_add_command(args.feature_id, args.files, args.status)
    if sub == "deprecate":
        return manifest_deprecate_command(args.feature_id, args.replaced_by)
    if sub == "check":
        return manifest_check_command()
    return manifest_list_command()


def manifest_list_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    manifest = read_manifest(project_root)
    if not manifest.features:
        print(style.dim("\n  The manifest is empty. Nothing has been built "
                        "(or recorded) yet.\n"))
        return 0
    print(style.bold(f"\n  Implementation manifest — "
                     f"{len(manifest.features)} feature(s)\n"))
    for f in manifest.features:
        icon, color = STATUS_DISPLAY.get(f.status, ("?", style.white))
        print(f"  {color(icon)} {style.bold(f.id)} — {f.summary}")
        files = ", ".join(f.files[:4])
        more = f" +{len(f.files) - 4} more" if len(f.files) > 4 else ""
        print(style.dim(f"    {files}{more}"))
        if f.replaced_by:
            print(style.dim(f"    replaced by: {f.replaced_by}"))
        print("")
    return 0


def manifest_add_command(feature_id: Optional[str], files_csv: str,
                         status: str,
                         project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    if not feature_id:
        feature_id = ask("  Feature id (kebab-case): ")
        if not feature_id:
            print(style.yellow("  No id given — aborted."))
            return 1
    summary = ask("  One-line summary: ") or feature_id
    files = [f.strip() for f in files_csv.split(",") if f.strip()]
    latest = find_latest_session(project_root)
    entry = ManifestEntry(
        id=feature_id,
        session=latest.name if latest else "",
        status=status if status in STATUS_DISPLAY else "implemented",
        files=files,
        summary=summary,
        applied_at=now_iso(),
        lead_knight="King",
    )
    add_manifest_entry(project_root, entry)
    print(style.green(f"  Added {feature_id} to the manifest."))
    return 0


def manifest_deprecate_command(feature_id: str,
                               replaced_by: Optional[str],
                               project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    if deprecate_feature(project_root, feature_id, replaced_by):
        print(style.green(f"  Deprecated {feature_id}."))
        return 0
    print(style.yellow(f"  No feature with id {feature_id}."))
    return 1


def manifest_check_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    warnings = check_manifest(project_root)
    if not warnings:
        print(style.green("\n  Manifest is clean — all files exist.\n"))
        return 0
    print(style.yellow(f"\n  {len(warnings)} stale manifest entr"
                       f"{'y' if len(warnings) == 1 else 'ies'}:\n"))
    for w in warnings:
        print(style.yellow(f"  ! {w}"))
    print("")
    return 0
