"""`roundtable decrees` — the King's Decree Log display.

Parity with reference src/commands/decrees.ts:8-43.
"""

from __future__ import annotations

import os
from typing import Optional

from ..utils.decree_log import read_decree_log
from ..utils.ui import style

TYPE_LABELS = {
    "rejected_no_apply": "REJECTED (not applied)",
    "deferred": "DEFERRED",
}


def decrees_command(project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    log = read_decree_log(project_root)
    if not log.entries:
        print(style.dim("\n  No decrees yet. The King has been lenient.\n"))
        return 0

    active = [e for e in log.entries if not e.revoked]
    revoked = [e for e in log.entries if e.revoked]
    print(style.bold(f"\n  King's Decree Log — {len(active)} active, "
                     f"{len(revoked)} revoked\n"))
    for e in log.entries:
        marker = style.dim("✗ revoked") if e.revoked else style.green("● active")
        label = TYPE_LABELS.get(e.type, e.type)
        print(f"  {style.bold(e.id)} {marker} — {style.yellow(label)}")
        print(f"    Topic: {e.topic}")
        print(f"    Reason: {e.reason}")
        print(style.dim(f"    Session: {e.session} — {e.date[:10]}"))
        print("")
    return 0
