"""`roundtable code-red` — diagnostic mode (triage → blind → convergence).

The reference's documented flow (architecture-docs.md:119-167,
README.md:159-175; SURVEY.md §2.2):

- **Triage round:** every doctor sees the symptoms + project context and
  gives a first assessment.
- **Blind round:** each doctor diagnoses INDEPENDENTLY — the transcript of
  the other doctors is withheld to prevent anchoring/groupthink. (In the
  TPU engine this is natural: each doctor's KV slot simply doesn't receive
  the shared-transcript delta.)
- **Convergence rounds:** doctors see everything and compare root causes;
  between rounds their `file_requests` are resolved and injected.
- **Convergence** = 2+ doctors fuzzy-matching root_cause_key with
  confidence >= 8 → outcomes: Fix now / Report only / Log for later, each
  recorded in `.roundtable/error-log.md` as CR-XXX OPEN/RESOLVED/PARKED.
"""

from __future__ import annotations

import os
import sys
from typing import Optional

from ..adapters.factory import initialize_adapters
from ..core.config import load_config
from ..core.diagnostic import (
    DiagnosticBlock,
    check_convergence,
    parse_diagnostic_from_response,
    strip_diagnostic_json,
    summarize_diagnosis,
)
from ..core.errors import ConfigError, classify_error, format_error
from ..core.orchestrator import (
    assemble_shared_context,
    execute_with_fallback,
    resolve_file_requests,
)
from ..core.prompt import load_template
from ..core.types import RoundEntry
from ..utils.context import build_context
from ..utils.error_log import add_error_entry, set_entry_status
from ..utils.session import (
    create_session,
    now_iso,
    update_status,
    write_decisions,
    write_discussion,
)
from ..utils.ui import ask, knight_color, style
from .reporter import ConsoleReporter

MAX_DIAG_ROUNDS = 4  # triage + blind + up to 2 convergence rounds

_PHASE_RULES = {
    "triage": (
        "TRIAGE — first assessment. What do the symptoms suggest? What are "
        "the candidate mechanisms? What evidence would discriminate between "
        "them? Confidence above 6 is premature in triage."),
    "blind": (
        "BLIND DIAGNOSIS — you see the symptoms and the project context but "
        "NOT the other doctors' notes this round (anti-anchoring). Commit "
        "to your own best root_cause_key and the test that would prove it."),
    "convergence": (
        "CONVERGENCE — compare your diagnosis with the other doctors'. "
        "Address disagreements head-on: either adopt a colleague's key "
        "(citing their evidence) or present the evidence that refutes it."),
}


def _build_prompt(symptoms: str, phase: str, context_text: str,
                  resolved_files: str, doctors: list[str], me: str,
                  transcript: list[RoundEntry]) -> str:
    template = load_template("code_red_prompt.md")
    others = ", ".join(d for d in doctors if d != me) or "(you consult alone)"
    if phase == "blind":
        transcript_text = "(withheld this round — diagnose independently)"
    elif transcript:
        transcript_text = "\n\n".join(
            f"### Round {e.round} — Dr. {e.knight}\n{e.response}"
            for e in transcript)
    else:
        transcript_text = "(none yet)"
    filled = template
    for key, value in (
        ("{{symptoms}}", symptoms),
        ("{{phase}}", phase.upper()),
        ("{{phase_rules}}", _PHASE_RULES[phase]),
        ("{{context}}", context_text),
        ("{{resolved_files}}", resolved_files or "(none requested)"),
        ("{{other_doctors}}", others),
        ("{{transcript}}", transcript_text),
    ):
        filled = filled.replace(key, value)
    return filled + f"\n\nYou are Dr. {me}. Your diagnosis:"


def code_red_command(description: str,
                     project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    config = load_config(project_root)

    print(style.bold(style.red("\n  ── CODE RED ──")))
    print(style.dim(f'  Incident: "{description}"\n'))

    adapters = initialize_adapters(
        config, on_event=lambda k, m: print(style.dim(f"  {m}")))
    if not adapters:
        raise ConfigError("No doctors available for the consultation.")

    context = build_context(project_root, config, read_source_code=True)
    context_text = assemble_shared_context("", context, "", "")
    session_path = create_session(project_root, f"code-red {description}")
    update_status(session_path, phase="diagnosing")

    doctors = [k.name for k in config.knights if k.adapter in adapters]
    timeout_ms = config.rules.timeout_per_turn_seconds * 1000
    reporter = ConsoleReporter()

    transcript: list[RoundEntry] = []
    resolved_files = ""
    phases = ["triage", "blind"] + \
        ["convergence"] * (MAX_DIAG_ROUNDS - 2)

    converged = None
    for round_num, phase in enumerate(phases, start=1):
        print(style.bold(f"\n  ── Round {round_num}: {phase.upper()} ──"))
        round_blocks: list[DiagnosticBlock] = []
        pending_requests: list[str] = []
        for knight in config.knights:
            if knight.adapter not in adapters:
                continue
            adapter = adapters[knight.adapter]
            prompt = _build_prompt(
                description, phase, context_text, resolved_files,
                doctors, knight.name, transcript)
            update_status(session_path, phase="diagnosing",
                          current_knight=knight.name, round=round_num)
            try:
                response, _served_by = execute_with_fallback(
                    adapter, knight, config, prompt, timeout_ms,
                    adapters, reporter)
            except Exception as e:
                print(style.red(f"  Dr. {knight.name} is unavailable "
                                f"({classify_error(e)}) — the consult "
                                "continues without them."))
                continue
            block = parse_diagnostic_from_response(
                response, knight.name, round_num)
            display = strip_diagnostic_json(response)
            print(f"\n  {knight_color(knight.name, f'Dr. {knight.name}')}"
                  f" (round {round_num}):")
            for line in display.splitlines()[:30]:
                print(style.dim(f"  {line}"))
            if block:
                conf_color = (style.green if block.confidence_score >= 8
                              else style.yellow)
                print(conf_color(
                    f"  {block.root_cause_key or '(no key)'} — confidence "
                    f"{block.confidence_score:g}/10"))
                round_blocks.append(block)
                pending_requests.extend(block.file_requests)
            transcript.append(RoundEntry(
                knight=knight.name, round=round_num, response=response,
                consensus=None, timestamp=now_iso()))

        write_discussion(session_path, transcript)

        if pending_requests:
            resolved_files = resolve_file_requests(
                pending_requests, project_root, config.rules.ignore)

        # Convergence is checked on the latest round's diagnoses — stale
        # triage guesses must not fake agreement with fresh evidence.
        if phase != "triage" and round_blocks:
            converged = check_convergence(round_blocks)
            if converged:
                break

    if converged is None:
        print(style.yellow("\n  The doctors could not agree on a root "
                           "cause. The patient lives... for now."))
        cr_id = add_error_entry(project_root, description, None,
                                status="OPEN",
                                session=os.path.basename(session_path))
        update_status(session_path, phase="escalated")
        print(style.dim(f"  Logged as {cr_id} (OPEN) in "
                        ".roundtable/error-log.md\n"))
        return 1

    key, group = converged
    diagnosis = summarize_diagnosis(key, group)
    print(style.bold(style.green(f"\n  DIAGNOSIS CONVERGED: {key}")))
    print(style.dim("  " + "\n  ".join(diagnosis.splitlines()[:12])))
    write_decisions(session_path, f"code-red: {description}", diagnosis,
                    transcript)
    # Scope for a follow-up fix = the evidence files the doctors pulled
    # (reference TODO.md:228).
    update_status(session_path, phase="consensus_reached",
                  consensus_reached=True,
                  allowed_files=sorted({
                      fr.split(":")[0] for b in group
                      for fr in b.file_requests}) or None)

    cr_id = add_error_entry(project_root, description, diagnosis,
                            status="OPEN",
                            session=os.path.basename(session_path))

    # --- outcome menu (reference README.md:174-175) ---
    if not sys.stdin.isatty():
        print(style.dim(f"\n  Logged as {cr_id}. Fix with: "
                        "roundtable apply\n"))
        return 0
    print(style.bold("\n  The diagnosis is in. Your orders?\n"))
    print(f"  {style.bold('1.')} {style.green('Fix now')} — the Lead "
          "Knight operates immediately")
    print(f"  {style.bold('2.')} {style.cyan('Report only')} — record "
          "the diagnosis, no surgery")
    print(f"  {style.bold('3.')} {style.dim('Log for later')} — park it\n")
    answer = ask(style.bold(style.yellow("  Your orders? [1-3] ")),
                 default="2").strip()
    if answer == "1":
        from .apply import apply_command
        try:
            apply_result: dict = {}
            rc = apply_command(project_root=project_root,
                               result=apply_result)
            # apply returning success with files written = resolved; a
            # 0-file apply (everything skipped at parley) must NOT flip the
            # status (reference TODO.md:227 "code-red false RESOLVED" fix).
            if rc == 0 and apply_result.get("written"):
                set_entry_status(project_root, cr_id, "RESOLVED")
                print(style.green(f"  {cr_id} RESOLVED."))
            elif rc == 0:
                print(style.yellow(
                    f"  Nothing was written — {cr_id} stays OPEN."))
        except Exception as e:
            print(style.red(f"  Surgery failed: {format_error(e)}"))
        return 0
    if answer == "3":
        set_entry_status(project_root, cr_id, "PARKED")
        print(style.dim(f"\n  {cr_id} PARKED. It will be back.\n"))
        return 0
    print(style.dim(f"\n  {cr_id} recorded (OPEN). The report is in "
                    "decisions.md.\n"))
    return 0
