"""`roundtable code-red` — diagnostic mode (triage → blind round → convergence).

Full implementation follows the documented protocol
(reference architecture-docs.md:119-167; SURVEY.md §2.2).
"""

from __future__ import annotations

from typing import Optional

from ..utils.ui import style


def code_red_command(description: str,
                     project_root: Optional[str] = None) -> int:
    print(style.yellow("\n  Code-red diagnostics are being forged "
                       "(triage → blind round → convergence)."))
    print(style.dim("  Until then: roundtable discuss "
                    f'"Diagnose: {description[:60]}"\n'))
    return 1
