"""`roundtable trace` — inspect retained request traces (ISSUE 20).

Three views over the tail-retained trace files the serving stack
appends under `tracing.trace_dir()` (one JSONL file per trace id, one
row per finished leg — a kill -9'd gateway's resume leg lands in the
SAME file, so a trace stitches across process generations):

- `trace list`           — every retained trace, newest last: outcome,
                           wall, TTFT, flags, leg/pid counts.
- `trace show <id>`      — one stitched trace: per-leg waterfall with
                           the critical-path stages as proportional
                           bars, flags, and the stage-sum-vs-wall gap.
- `trace stages`         — the aggregate critical-path table across
                           every retained trace: per-stage n / mean /
                           p95 / share of total attributed time — the
                           "where does TTFT go" answer.

File-based like `status --capacity`: works from a fresh CLI process
against whatever directory the serving process retained into
(ROUNDTABLE_TRACE_DIR or <telemetry dumps>/traces).
"""

from __future__ import annotations

from typing import Optional

from ..utils import tracing
from ..utils.ui import style

_BAR_WIDTH = 32


def trace_command(action: str, trace_id: Optional[str] = None,
                  trace_dir: Optional[str] = None) -> int:
    traces = tracing.load_traces(trace_dir)
    where = trace_dir or tracing.trace_dir()
    if action == "list":
        return _list(traces, where)
    if action == "stages":
        return _stages(traces, where)
    if action == "show":
        if not trace_id:
            print(style.red("  trace show needs a trace id "
                            "(see `roundtable trace list`)"))
            return 1
        return _show(traces, trace_id, where)
    print(style.red(f"  unknown trace action {action!r}"))
    return 1


def _empty(where) -> int:
    print(style.dim(
        f"\n  No retained traces under {where}. Serve with "
        "ROUNDTABLE_TELEMETRY=1 (head-sampling via "
        "ROUNDTABLE_TRACE_SAMPLE; shed/failed/hung/SLO-violating "
        "traces are always retained).\n"))
    return 0


def _list(traces: dict[str, list[dict]], where) -> int:
    if not traces:
        return _empty(where)
    print(style.bold(f"\n  Retained traces ({len(traces)}) — {where}"))
    print(style.dim(
        "    trace             outcome        wall_s   ttft_s  legs"
        "  flags"))
    stitched = sorted(
        ((tracing.stitch(legs), legs) for legs in traces.values()),
        key=lambda pair: pair[1][0].get("start", 0.0))
    for s, legs in stitched:
        ttft = s.get("ttft_s")
        flags = ",".join(s["flags"]) or "-"
        line = (f"    {s['trace_id']:<16}  {s['outcome']:<12} "
                f"{s['wall_s']:>8.3f} "
                f"{ttft if ttft is None else f'{ttft:8.3f}':>8}"
                f"  {len(legs):>4}  {flags}")
        print(style.red(line) if "failed" in s["outcome"]
              or "hung" in s["flags"] else style.dim(line))
    print("")
    return 0


def _show(traces: dict[str, list[dict]], trace_id: str, where) -> int:
    legs = traces.get(trace_id)
    if legs is None:
        # Prefix match — ids are long; operators paste the head.
        hits = [t for t in traces if t.startswith(trace_id)]
        if len(hits) == 1:
            trace_id, legs = hits[0], traces[hits[0]]
    if legs is None:
        print(style.red(f"  no retained trace {trace_id!r} under "
                        f"{where} (try `roundtable trace list`)"))
        return 1
    s = tracing.stitch(legs)
    print(style.bold(f"\n  Trace {trace_id}"))
    print(style.dim(
        f"    session={s.get('session', '')}  outcome={s['outcome']}  "
        f"legs={len(legs)}  pids={','.join(str(p) for p in s['pids'])}"
        + (f"  flags={','.join(s['flags'])}" if s["flags"] else "")))
    gap = s["wall_s"] - s["stage_sum_s"]
    print(style.dim(
        f"    wall={s['wall_s']:.3f}s  stage_sum={s['stage_sum_s']:.3f}s"
        f"  gap={gap:.3f}s"
        + (f"  ttft={s['ttft_s']:.3f}s"
           if s.get("ttft_s") is not None else "")))
    for i, leg in enumerate(legs):
        _waterfall(i, leg)
    print("")
    return 0


def _waterfall(i: int, leg: dict) -> None:
    stages = leg.get("stages", {})
    total = sum(stages.values()) or 1e-9
    print(style.bold(
        f"\n    leg {i} [{leg.get('kind', '?')}] pid={leg.get('pid')}"
        f"  outcome={leg.get('outcome')}  wall={leg.get('wall_s', 0):g}s"
        + (f"  reconnects={leg['reconnects']}"
           if leg.get("reconnects") else "")))
    offset = 0.0
    for stage in tracing.STAGES:
        dur = stages.get(stage)
        if dur is None:
            continue
        # Proportional waterfall: indent = time before this stage,
        # bar = this stage's share of the leg's attributed time.
        lead = int(_BAR_WIDTH * offset / total)
        width = max(int(_BAR_WIDTH * dur / total), 1)
        print(style.dim(
            f"      {stage:<14} {dur:>9.4f}s  "
            + " " * lead + "█" * width))
        offset += dur


def _stages(traces: dict[str, list[dict]], where) -> int:
    if not traces:
        return _empty(where)
    agg: dict[str, list[float]] = {}
    for legs in traces.values():
        for leg in legs:
            for stage, dur in leg.get("stages", {}).items():
                agg.setdefault(stage, []).append(dur)
    grand = sum(sum(v) for v in agg.values()) or 1e-9
    print(style.bold(
        f"\n  Critical path across {len(traces)} traces — {where}"))
    print(style.dim(
        "    stage            n       mean_s        p95_s   share"))
    for stage in tracing.STAGES:
        vals = sorted(agg.get(stage, ()))
        if not vals:
            continue
        p95 = vals[min(int(len(vals) * 0.95), len(vals) - 1)]
        share = sum(vals) / grand
        print(style.dim(
            f"    {stage:<14}{len(vals):>5}{sum(vals) / len(vals):>13.4f}"
            f"{p95:>13.4f}{share * 100:>7.1f}%"))
    print("")
    return 0
