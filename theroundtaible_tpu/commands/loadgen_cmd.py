"""`roundtable loadgen` — offered-load capacity sweep (ISSUE 19).

Thin CLI wrapper over loadgen.bench.run_capacity: builds the tiny
in-process stack (engine + scheduler + admission + gateway), ramps an
open-loop arrival process to the shed point, fits the knee, derives
admission thresholds, and (full mode) writes the CAPACITY_r19.json
record that ROUNDTABLE_GATEWAY_CAPACITY_FILE feeds back into
gateway/admission.py.
"""

from __future__ import annotations

import json
import os
import sys
import time
from typing import Optional

from ..utils.ui import style


def loadgen_command(smoke: bool = False,
                    seed: int = 7,
                    arrival: str = "poisson",
                    duration_s: Optional[float] = None,
                    rates: Optional[str] = None,
                    out: Optional[str] = None) -> int:
    from ..loadgen.bench import run_capacity

    t0 = time.monotonic()
    rate_list = ([float(r) for r in rates.split(",") if r.strip()]
                 if rates else None)
    print(style.bold("\n  Capacity sweep "
                     f"({'smoke' if smoke else 'full'}, "
                     f"{arrival} arrivals, seed {seed})"))
    record = run_capacity(
        smoke=smoke, seed=seed, arrival=arrival, rates=rate_list,
        duration_s=duration_s,
        log=lambda m: print(style.dim(f"  {m}"), file=sys.stderr))
    record["detail"]["wall_s"] = round(time.monotonic() - t0, 1)

    frontier = record["detail"]["frontier"]
    knee = frontier["knee"]
    th = frontier["derived_thresholds"]
    print(style.bold("\n  Frontier:"))
    print(style.dim("    offered_rps  admitted  shed_rate  ttft_p95_s"
                    "  accepted_tok_s"))
    for p in frontier["points"]:
        p95 = p.get("ttft_p95_s")
        print(style.dim(
            f"    {p['offered_rps']:>11.2f}  {p['admitted']:>8.0f}"
            f"  {p['shed_rate']:>9.3f}"
            f"  {p95 if p95 is None else f'{p95:.3f}':>10}"
            f"  {p['accepted_tok_s']:>14.1f}"))
    print(style.bold(
        f"\n  Knee: {knee['rate']:.2f} sessions/s ({knee['reason']})"))
    print(style.dim(
        f"  Derived thresholds: max_inflight={th['max_inflight']} "
        f"max_queue_depth={th['max_queue_depth']} "
        f"p95_slo_s={th['p95_slo_s']:.2f}"))

    meets = record["detail"]["acceptance"]["meets"]
    if smoke:
        print(style.dim("\n  (smoke mode: no artifact written)\n"))
        return 0 if meets else 1
    path = out or os.path.join(os.getcwd(), "CAPACITY_r19.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(style.dim(f"\n  wrote {path}"))
    print(style.dim(
        "  feed it back: ROUNDTABLE_GATEWAY_CAPACITY_FILE="
        f"{path} roundtable gateway\n"))
    return 0 if meets else 1
