"""`roundtable serve` — K concurrent discussions on one shared fleet.

The discuss command serves exactly one session; this command is the
ISSUE 4 entry point that drives MANY: each topic gets its own discussion
thread with its own session directory, metrics file and adapter
instances, while every tpu-llm adapter routes its rounds through the
per-engine continuous-batching SessionScheduler — so the sessions'
decode work genuinely interleaves on the shared engines instead of
serializing behind one serve lock.

Programmatic surface: `serve_discussions(topics, config, project_root)`
returns per-session results plus each scheduler's decision provenance;
bench_discuss's offered-load mode and the scheduler test-suite drive it
directly.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Any, Callable, Optional

from ..adapters.factory import initialize_adapters
from ..core.config import load_config
from ..core.errors import ConfigError
from ..core.orchestrator import run_discussion
from ..utils.ui import style


def _dedupe_topics(topics: list[str]) -> list[str]:
    """Session dirs are named date-HHMM-slug(topic): concurrent sessions
    whose topics slug identically would share (and corrupt) one session
    directory. Duplicates get a "(session N)" PREFIX — slugify truncates
    at 50 chars, so a suffix on any sentence-length topic would land
    past the cut and the slugs would still collide."""
    from ..utils.session import slugify
    seen: set = set()
    out = []
    for t in topics:
        cand, n = t, 1
        while slugify(cand) in seen:
            n += 1
            cand = f"(session {n}) {t}"
        seen.add(slugify(cand))
        out.append(cand)
    return out


def _attach_schedulers(adapters: dict, session_id: str,
                       admit_hold_s: float,
                       journal=None, router=None) -> tuple[list, list]:
    """Bind every tpu-llm adapter in this session's seat map to its
    session id and to the SHARED per-engine scheduler (scheduler_for:
    one scheduler per resident engine, however many sessions share it)
    — or, under a session router, to the scheduler of the REPLICA the
    router placed this session on (affinity + load score; ISSUE 17).
    Returns (schedulers touched, schedulers CREATED here) — the caller
    must only close the latter: a scheduler that pre-existed this serve
    call belongs to someone else's sessions too, and closing it would
    kill their in-flight rounds with SchedulerClosed."""
    scheds, owned = [], []
    for adapter in adapters.values():
        attach = getattr(adapter, "attach_scheduler", None)
        if attach is None:
            continue
        if router is not None:
            # The router owns replica schedulers' lifecycles; serving
            # goes through the scheduler, so the adapter's own engine
            # handle is only a tokenizer/config source.
            sched = router.scheduler_for(session_id)
            attach(sched, session=session_id)
            if sched not in scheds:
                scheds.append(sched)
            continue
        try:
            engine = adapter._get_engine()
        except Exception:  # noqa: BLE001 — seat probes already warned
            # The engine may still come up later (execute_round retries
            # construction on the breaker's probe) — the session
            # NAMESPACE must be bound regardless, or two sessions'
            # same-named knights would collide on the recovered engine.
            adapter.session = session_id
            continue
        # PPEngine has no segment seam to schedule at — sessions on a
        # pipe mesh still get namespace isolation via adapter.session.
        from ..engine.scheduler import acquire_scheduler
        try:
            sched, created = acquire_scheduler(
                engine, admit_hold_s=admit_hold_s)
        except TypeError:
            adapter.session = session_id
            continue
        if journal is not None and sched.journal is not journal:
            # Durable turn journal (ISSUE 12): one journal per serve
            # root, shared by every scheduler — committed turns fsync
            # at retire so `serve --resume` survives a kill -9. A
            # different already-attached journal is REPLACED: `--resume
            # DIR1 --journal DIR2` must journal new turns into DIR2,
            # not keep the replay-attached DIR1 (the full-disk
            # migration case).
            sched.attach_journal(journal)
        attach(sched, session=session_id)
        if sched not in scheds:
            scheds.append(sched)
        if created and sched not in owned:
            owned.append(sched)
    return scheds, owned


def serve_discussions(
    topics: list[str],
    config,
    project_root: str,
    *,
    read_source_code: bool = False,
    admit_hold_s: float = 0.25,
    reporter_factory: Optional[Callable[[str], Any]] = None,
    close_schedulers: bool = True,
    journal_dir: Optional[str] = None,
    replicas: int = 1,
) -> dict[str, Any]:
    """Run one discussion per topic, all concurrently, on shared engines.

    Each session gets its OWN adapter instances (adapter state —
    last_stats, degradation markers, the fallback cache — is per
    session) seated from the same config; the engine cache underneath
    dedups the resident models, and scheduler_for dedups the scheduler
    per engine, so N sessions share one model + one continuous batch.

    Returns {"sessions": [{topic, session_id, ok, result|error,
    wall_s, session_path}], "schedulers": [describe()...],
    "wall_s": total}.
    """
    topics = _dedupe_topics(list(topics))
    journal = None
    if journal_dir is not None:
        from ..engine.session_journal import SessionJournal
        journal = SessionJournal(journal_dir)
    router = None
    if replicas > 1:
        # N-replica fleet (ISSUE 17): one engine per replica behind a
        # session router — sessions place by affinity/load and every
        # scheduler shares the one journal. `--replicas 1` (and every
        # caller that doesn't pass it) takes the classic path below,
        # byte-identical to single-engine serving.
        from ..router import SessionRouter, build_replicas, \
            set_active_router
        probe = initialize_adapters(config)
        engine = None
        for adapter in probe.values():
            if hasattr(adapter, "attach_scheduler"):
                try:
                    engine = adapter._get_engine()
                    break
                except Exception:  # noqa: BLE001 — try the next seat
                    continue
        if engine is None:
            raise ConfigError(
                "--replicas needs at least one tpu-llm knight whose "
                "engine can be built")
        reps = build_replicas(engine, replicas, journal=journal,
                              admit_hold_s=admit_hold_s)
        router = SessionRouter(reps, journal=journal)
        set_active_router(router)
    all_scheds: list = []
    owned_scheds: list = []
    # Session ids carry a per-CALL unique component: two concurrent
    # serve_discussions calls share the resident engine (by design), so
    # plain "s0"/"s1" ids would merge unrelated discussions into one
    # KV isolation domain.
    import uuid
    call_tag = uuid.uuid4().hex[:6]
    session_entries: list[dict[str, Any]] = [
        {"topic": t, "session_id": f"{call_tag}-s{i}"}
        for i, t in enumerate(topics)]
    threads = []
    t0 = time.monotonic()

    def run_one(entry: dict[str, Any]) -> None:
        ts = time.monotonic()
        try:
            adapters = initialize_adapters(config)
            if not adapters:
                raise ConfigError(
                    "A roundtable with no knights is just a table.")
            # Plain appends from session threads; deduped by identity
            # when the report is built.
            scheds, owned = _attach_schedulers(
                adapters, entry["session_id"], admit_hold_s,
                journal=journal, router=router)
            all_scheds.extend(scheds)
            owned_scheds.extend(owned)
            reporter = (reporter_factory(entry["session_id"])
                        if reporter_factory else None)
            result = run_discussion(
                entry["topic"], config, adapters, project_root,
                read_source_code=read_source_code, reporter=reporter)
            entry["ok"] = True
            entry["result"] = result
            entry["session_path"] = result.session_path
        except Exception as e:  # noqa: BLE001 — per-session containment
            entry["ok"] = False
            entry["error"] = e
        entry["wall_s"] = round(time.monotonic() - ts, 3)

    for entry in session_entries:
        th = threading.Thread(target=run_one, args=(entry,),
                              name=f"serve-{entry['session_id']}",
                              daemon=True)
        threads.append(th)
        th.start()
    for th in threads:
        th.join()
    uniq = list({id(s): s for s in all_scheds}.values())
    report = {
        "sessions": session_entries,
        "schedulers": [s.describe() for s in uniq],
        "wall_s": round(time.monotonic() - t0, 3),
    }
    if router is not None:
        report["router"] = router.describe()
    if close_schedulers:
        # Only schedulers CREATED by this call — a pre-existing one is
        # shared with sessions outside this call and must keep running.
        for s in {id(s): s for s in owned_scheds}.values():
            s.close()
        if router is not None:
            router.close()
            for rep in router.replicas:
                if getattr(rep, "owned_scheduler", False):
                    rep.scheduler.close()
    return report


# Factored into the engine layer (ISSUE 16): the gateway restores
# committed sessions on boot through the same seam the CLI uses. The
# re-export keeps `commands.serve.resume_from_journal` — and the
# `serve --resume` behavior behind it — byte-identical.
from ..engine.recovery import resume_from_journal  # noqa: E402,F401


def serve_command(topics: list[str], sessions: Optional[int] = None,
                  read_code: Optional[bool] = None,
                  project_root: Optional[str] = None,
                  journal_dir: Optional[str] = None,
                  resume_dir: Optional[str] = None,
                  replicas: int = 1) -> int:
    """CLI: `roundtable serve "topic" --sessions 4` (one topic fanned
    into K concurrent discussions), `roundtable serve "t1" "t2" "t3"`
    (one discussion each), `--journal DIR` for crash-durable turn
    records, `--resume DIR` to replay a crashed process's journal."""
    project_root = project_root or os.getcwd()
    config = load_config(project_root)
    if not topics and not resume_dir:
        raise ConfigError(
            "serve needs topics to discuss (or --resume DIR)")
    if resume_dir:
        print(style.bold(f"\n  Resuming sessions from journal "
                         f"{resume_dir}..."))
        r = resume_from_journal(resume_dir, config=config,
                                project_root=project_root)
        print(style.dim(
            f"  replayed {r['turns']} committed turn(s) across "
            f"{r['sessions']} session(s) — KV restored at the last "
            "committed turn"))
        # A resumed serve keeps journaling into the same directory
        # unless the operator pointed --journal elsewhere.
        journal_dir = journal_dir or resume_dir
        if not topics:
            # Nothing to serve: the replay above VALIDATED the journal
            # (every committed turn re-prefilled cleanly), but the
            # restored KV lives only in this process — continuing the
            # work needs topics in the same invocation.
            from ..engine.session_journal import SessionJournal
            j = SessionJournal(resume_dir)
            for session in j.sessions():
                last = j.last_turn(session)
                print(style.dim(
                    f"    {session}: resumed at committed turn {last}"))
            print(style.dim(
                "\n  journal validated — no topics given, so this "
                "process exits. To continue serving after a crash, "
                "pass the next topics in the same invocation:\n"
                "    roundtable serve --resume DIR \"next topic\"\n"))
            return 0
    if sessions and len(topics) == 1:
        topics = topics * sessions
    elif sessions and len(topics) != sessions:
        raise ConfigError(
            f"--sessions {sessions} with {len(topics)} topics — give ONE "
            "topic to replicate, or one topic per session")

    print(style.bold(f"\n  Serving {len(topics)} concurrent "
                     "discussion(s) on the shared fleet...\n"))
    report = serve_discussions(topics, config, project_root,
                               read_source_code=bool(read_code),
                               journal_dir=journal_dir,
                               replicas=replicas)

    failed = 0
    for entry in report["sessions"]:
        if entry.get("ok"):
            r = entry["result"]
            verdict = ("consensus" if r.consensus
                       and not r.unanimous_rejection
                       else "rejection" if r.consensus else "escalated")
            print(f"  {style.green(entry['session_id'])} "
                  f"{verdict} in {r.rounds} round(s), "
                  f"{entry['wall_s']:.1f}s — {entry['session_path']}")
        else:
            failed += 1
            print(f"  {style.red(entry['session_id'])} failed: "
                  f"{entry.get('error')}")
    for sched in report["schedulers"]:
        print(style.dim(
            f"\n  scheduler: admitted {sched['admitted']}, "
            f"completed {sched['completed']}, "
            f"max occupancy {sched['max_occupancy']} rows, "
            f"mean {sched['occupancy_mean']} over "
            f"{sched['segments']} segment(s), "
            f"queue peak {sched['queued_peak']}"))
    if report.get("router"):
        rt = report["router"]
        print(style.dim(
            f"  router: {len(rt['replicas'])} replica(s), "
            f"{rt['sessions']} session(s) placed, "
            f"{rt['migrations']} migration(s), "
            f"{rt['failovers']} failover(s)"))
    print(style.dim(f"  total wall: {report['wall_s']:.1f}s\n"))
    return 1 if failed else 0
