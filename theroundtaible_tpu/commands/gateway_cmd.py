"""`roundtable gateway` — serve the streaming HTTP/SSE front door.

Seats the configured adapters, acquires the first tpu-llm engine's
shared SessionScheduler (the same seam `serve --resume` uses), wires
the durable journals, optionally replays a crashed process's committed
turns, and blocks serving HTTP until interrupted.
"""

from __future__ import annotations

import os
from typing import Optional

from ..core.config import load_config
from ..core.errors import ConfigError
from ..utils.ui import style


def _build_scheduler(config, journal_dir: Optional[str]):
    """First tpu-llm engine's shared scheduler (+ attached journal)."""
    from ..adapters.factory import initialize_adapters
    from ..engine.scheduler import acquire_scheduler

    adapters = initialize_adapters(config)
    sched = None
    for adapter in adapters.values():
        if not hasattr(adapter, "attach_scheduler"):
            continue
        try:
            engine = adapter._get_engine()
            sched, _created = acquire_scheduler(engine)
            break
        except Exception:  # noqa: BLE001 — try the next seat
            continue
    if sched is None:
        raise ConfigError(
            "gateway needs at least one tpu-llm knight whose engine "
            "can be built — no scheduler available to serve")
    if journal_dir is not None and sched.journal is None:
        from ..engine.session_journal import SessionJournal
        sched.attach_journal(SessionJournal(journal_dir))
    return sched


def _build_router(sched, replicas: int):
    """N-replica fleet around the scheduler's engine (ISSUE 17): the
    router owns session placement, migration, rolls, and failover;
    admission reads fleet-wide signals through router.signals()."""
    from ..router import SessionRouter, build_replicas, \
        set_active_router
    reps = build_replicas(sched.engine, replicas,
                          journal=sched.journal)
    router = SessionRouter(reps, journal=sched.journal)
    set_active_router(router)
    return router


def gateway_command(host: Optional[str] = None,
                    port: Optional[int] = None,
                    journal_dir: Optional[str] = None,
                    resume_dir: Optional[str] = None,
                    replicas: int = 1,
                    project_root: Optional[str] = None) -> int:
    project_root = project_root or os.getcwd()
    config = load_config(project_root)
    from ..gateway import Gateway

    if resume_dir is not None:
        # Boot-time recovery through the library seam
        # (engine/recovery.py — the factored `serve --resume` path):
        # committed turns replay into KV BEFORE the socket opens, so
        # the first Last-Event-ID reconnect finds its session restored.
        print(style.bold(f"\n  Resuming sessions from journal "
                         f"{resume_dir}..."))
        from ..engine.recovery import resume_from_journal
        r = resume_from_journal(resume_dir, config=config,
                                project_root=project_root)
        sched = r["scheduler"]
        print(style.dim(
            f"  replayed {r['turns']} committed turn(s) across "
            f"{r['sessions']} session(s)"))
        journal_dir = journal_dir or resume_dir
        if journal_dir != str(sched.journal.root):
            from ..engine.session_journal import SessionJournal
            sched.attach_journal(SessionJournal(journal_dir))
    else:
        sched = _build_scheduler(config, journal_dir)

    router = _build_router(sched, replicas) if replicas > 1 else None
    gw = Gateway(sched, host=host, port=port, intent_dir=journal_dir,
                 router=router)
    if router is not None:
        print(style.dim(f"  serving across {replicas} replicas "
                        f"({', '.join(r.name for r in router.replicas)})"))
    print(style.bold(f"\n  Gateway listening on "
                     f"http://{gw.host}:{gw.port}"))
    print(style.dim(
        "    POST /v1/chat/completions   (OpenAI-compatible, SSE)\n"
        "    POST /v1/discussions        (native multi-knight, SSE)\n"
        "    GET  /v1/streams/<id>       (Last-Event-ID reconnect)\n"
        "    POST /v1/admin/roll         (rolling restart, fleets)\n"
        "    GET  /healthz · GET /metrics\n"))
    gw.run()
    gw.stop()
    if router is not None:
        router.close()
    return 0
