"""Adapter factory + initialization.

Parity with reference src/utils/adapters.ts:15-106: `create_adapter` switches
over the static adapter ids plus dynamic prefix ids; `initialize_adapters`
probes availability per knight, substitutes the API adapter when a CLI is
missing (init-time fallback), and runs context-window detection for local
adapters. The map is keyed by **adapter id**, not knight name.

TPU-build additions: the `tpu-llm` / `tpu-llm-<model>` dynamic id family
(in-tree JAX engine) and the `fake` id (hermetic tests).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

from ..core.types import RoundtableConfig
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS

# CLI id → API id used for init-time fallback (reference adapters.ts:89-100).
_CLI_TO_API = {
    "claude-cli": "claude-api",
    "gemini-cli": "gemini-api",
    "openai-cli": "openai-api",
}


def create_adapter(adapter_id: str, config: RoundtableConfig,
                   timeout_ms: int = DEFAULT_TIMEOUT_MS
                   ) -> Optional[BaseAdapter]:
    """Instantiate one adapter by id (reference adapters.ts:15-56)."""
    cfg: dict[str, Any] = config.adapter_config.get(adapter_id, {})

    if adapter_id == "claude-cli":
        from .cli_adapters import ClaudeCliAdapter
        return ClaudeCliAdapter(cfg.get("command", "claude"), timeout_ms)
    if adapter_id == "gemini-cli":
        from .cli_adapters import GeminiCliAdapter
        return GeminiCliAdapter(cfg.get("command", "gemini"),
                                cfg.get("model"), timeout_ms)
    if adapter_id == "openai-cli":
        from .cli_adapters import OpenAICliAdapter
        return OpenAICliAdapter(cfg.get("command", "codex"), timeout_ms)
    if adapter_id == "claude-api":
        from .api_adapters import ClaudeApiAdapter
        return ClaudeApiAdapter(cfg.get("model", "claude-sonnet-4-6"),
                                cfg.get("env_key", "ANTHROPIC_API_KEY"),
                                timeout_ms)
    if adapter_id == "gemini-api":
        from .api_adapters import GeminiApiAdapter
        return GeminiApiAdapter(cfg.get("model", "gemini-2.5-flash"),
                                cfg.get("env_key", "GEMINI_API_KEY"),
                                timeout_ms)
    if adapter_id == "openai-api":
        from .api_adapters import OpenAIApiAdapter
        return OpenAIApiAdapter(cfg.get("model", "gpt-5.2"),
                                cfg.get("env_key", "OPENAI_API_KEY"),
                                timeout_ms)
    if adapter_id.startswith("local-llm"):
        from .local_llm import LocalLlmAdapter
        if not cfg.get("endpoint") or not cfg.get("model"):
            return None
        return LocalLlmAdapter(
            endpoint=cfg["endpoint"], model=cfg["model"],
            name=cfg.get("name", adapter_id), source=cfg.get("source"),
            timeout_ms=timeout_ms)
    if adapter_id.startswith("tpu-llm"):
        from .tpu_llm import TpuLlmAdapter
        return TpuLlmAdapter.from_config(adapter_id, cfg, timeout_ms)
    if adapter_id == "fake":
        from .fake import FakeAdapter
        return FakeAdapter(name=cfg.get("name", "Fake"))
    return None


def initialize_adapters(
    config: RoundtableConfig,
    on_event: Optional[Callable[[str, str], None]] = None,
) -> dict[str, BaseAdapter]:
    """Probe + seat every knight's adapter (reference adapters.ts:62-106).

    on_event(kind, message): "seated" | "fallback" | "unavailable" notices
    for the command layer to display.
    """
    timeout_ms = config.rules.timeout_per_turn_seconds * 1000
    adapters: dict[str, BaseAdapter] = {}

    _plan_tpu_fleet(config, on_event)

    for knight in config.knights:
        adapter_id = knight.adapter
        if adapter_id in adapters:
            continue
        adapter = create_adapter(adapter_id, config, timeout_ms)
        if adapter is not None and adapter.is_available():
            _post_init(adapter)
            adapters[adapter_id] = adapter
            if on_event:
                on_event("seated", f"{knight.name} ({adapter_id}) is at the table")
            continue

        # Init-time CLI→API fallback (reference adapters.ts:89-100).
        api_id = _CLI_TO_API.get(adapter_id)
        if api_id:
            api_adapter = create_adapter(api_id, config, timeout_ms)
            if api_adapter is not None and api_adapter.is_available():
                adapters[adapter_id] = api_adapter
                if on_event:
                    on_event("fallback",
                             f"{knight.name}: {adapter_id} unavailable, "
                             f"seated via {api_id}")
                continue
        if on_event:
            on_event("unavailable",
                     f"{knight.name} ({adapter_id}) is unavailable")
    return adapters


def _plan_tpu_fleet(config: RoundtableConfig,
                    on_event: Optional[Callable[[str, str], None]]) -> None:
    """Heterogeneous serving: when several knights use DIFFERENT tpu-llm
    models, partition the chips into per-model submeshes before any engine
    is built (engine/fleet.py; SURVEY.md §2.3). Homogeneous setups and
    configs with explicit mesh/devices are untouched."""
    tpu_cfgs = []
    for knight in config.knights:
        if knight.adapter.startswith("tpu-llm"):
            # Unconfigured tpu-llm ids get a dict INSERTED into the config
            # map so the planner's device assignment reaches the adapter —
            # leaving one engine on the full default mesh would overlap the
            # submeshes planned for the others and double-book HBM.
            cfg = config.adapter_config.setdefault(knight.adapter, {})
            if isinstance(cfg, dict):
                tpu_cfgs.append(cfg)
    if len(tpu_cfgs) < 2:
        return
    try:
        from ..engine.fleet import plan_fleet
        plan_fleet(tpu_cfgs)
    except Exception as e:  # noqa: BLE001 — engines still run (sharing the
        # full default mesh), but the operator must hear planning failed:
        # the symptom otherwise is an unexplained HBM OOM at weight load.
        if on_event:
            on_event("unavailable",
                     f"fleet planning failed ({e}); engines will share "
                     f"the full device mesh")


def _post_init(adapter: BaseAdapter) -> None:
    """Context-window detection for adapters that support it
    (reference adapters.ts:78-83)."""
    detect = getattr(adapter, "detect_context_window", None)
    if callable(detect):
        try:
            detect()
        except Exception:
            pass
