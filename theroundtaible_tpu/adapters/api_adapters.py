"""Cloud HTTPS API adapters: Anthropic, Google, OpenAI.

Parity with reference src/adapters/{claude-api,gemini-api,openai-api}.ts:
key lookup via env-var-then-keystore, 16384 max output tokens, per-turn
timeout, availability = key presence.
"""

from __future__ import annotations

from typing import Optional

from ..core.errors import AdapterError, classify_error
from ..utils.keys import get_key
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS
from .httpx import HttpError, post_json

MAX_OUTPUT_TOKENS = 16384


class _ApiAdapter(BaseAdapter):
    def __init__(self, name: str, model: str, env_key: str,
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__(name)
        self.model = model
        self.env_key = env_key
        self.default_timeout = timeout_ms

    def is_available(self) -> bool:
        key = get_key(self.env_key)
        return bool(key)

    def _require_key(self) -> str:
        key = get_key(self.env_key)
        if not key:
            raise AdapterError(
                f"{self.name} API key not set. Set {self.env_key} or run "
                f"'roundtable init'.", kind="auth")
        return key

    def _request(self, prompt: str, timeout_ms: int) -> str:
        raise NotImplementedError

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        try:
            return self._request(prompt, timeout_ms or self.default_timeout)
        except AdapterError:
            raise
        except Exception as e:
            raise AdapterError(str(e), kind=classify_error(e), cause=e)


class ClaudeApiAdapter(_ApiAdapter):
    """POST api.anthropic.com/v1/messages (reference claude-api.ts:5-74)."""

    def __init__(self, model: str = "claude-sonnet-4-6",
                 env_key: str = "ANTHROPIC_API_KEY",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("Claude", model, env_key, timeout_ms)

    def _request(self, prompt: str, timeout_ms: int) -> str:
        key = self._require_key()
        try:
            data = post_json(
                "https://api.anthropic.com/v1/messages",
                {
                    "model": self.model,
                    "max_tokens": MAX_OUTPUT_TOKENS,
                    "messages": [{"role": "user", "content": prompt}],
                },
                headers={"x-api-key": key,
                         "anthropic-version": "2023-06-01"},
                timeout_s=timeout_ms / 1000)
        except HttpError as e:
            raise AdapterError(f"Anthropic API error ({e.status}): {e.body}",
                               kind=classify_error(e))
        for part in data.get("content", []):
            if part.get("type") == "text" and part.get("text"):
                return part["text"]
        raise AdapterError("Anthropic API returned empty response", kind="api")


class GeminiApiAdapter(_ApiAdapter):
    """POST generativelanguage.googleapis.com generateContent
    (reference gemini-api.ts:5-70)."""

    def __init__(self, model: str = "gemini-2.5-flash",
                 env_key: str = "GEMINI_API_KEY",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("Gemini", model, env_key, timeout_ms)

    def _request(self, prompt: str, timeout_ms: int) -> str:
        key = self._require_key()
        url = ("https://generativelanguage.googleapis.com/v1beta/models/"
               f"{self.model}:generateContent?key={key}")
        try:
            data = post_json(url, {
                "contents": [{"parts": [{"text": prompt}]}],
                "generationConfig": {"maxOutputTokens": MAX_OUTPUT_TOKENS},
            }, timeout_s=timeout_ms / 1000)
        except HttpError as e:
            raise AdapterError(f"Gemini API error ({e.status}): {e.body}",
                               kind=classify_error(e))
        try:
            text = data["candidates"][0]["content"]["parts"][0]["text"]
        except (KeyError, IndexError, TypeError):
            text = None
        if not text:
            raise AdapterError("Gemini API returned empty response", kind="api")
        return text


class OpenAIApiAdapter(_ApiAdapter):
    """POST api.openai.com/v1/chat/completions (reference openai-api.ts:5-73)."""

    def __init__(self, model: str = "gpt-5.2",
                 env_key: str = "OPENAI_API_KEY",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("GPT", model, env_key, timeout_ms)

    def _request(self, prompt: str, timeout_ms: int) -> str:
        key = self._require_key()
        try:
            data = post_json(
                "https://api.openai.com/v1/chat/completions",
                {
                    "model": self.model,
                    "max_completion_tokens": MAX_OUTPUT_TOKENS,
                    "messages": [{"role": "user", "content": prompt}],
                },
                headers={"Authorization": f"Bearer {key}"},
                timeout_s=timeout_ms / 1000)
        except HttpError as e:
            raise AdapterError(f"OpenAI API error ({e.status}): {e.body}",
                               kind=classify_error(e))
        try:
            text = data["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            text = None
        if not text:
            raise AdapterError("OpenAI API returned empty response", kind="api")
        return text
