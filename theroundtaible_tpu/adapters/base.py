"""The knight boundary — abstract adapter contract.

Parity with reference src/adapters/base.ts:10-29 plus the one TPU-build
extension from SURVEY.md §7.1: a batched ``execute_round`` entry point that
lets the in-tree engine collapse a round's N-knight fan-out into a single
device program. Serial ``execute`` stays the contract for cloud/CLI adapters.
"""

from __future__ import annotations

from abc import ABC, abstractmethod
from dataclasses import dataclass
from typing import Optional

from ..core.consensus import parse_consensus_from_response
from ..core.types import ConsensusBlock

DEFAULT_TIMEOUT_MS = 120_000


@dataclass
class KnightTurn:
    """One prompt in a batched round dispatch."""

    knight_name: str
    prompt: str


class BaseAdapter(ABC):
    """4-method contract (reference base.ts:10-29)."""

    # True when execute_round/execute_for accept a `budget` keyword (an
    # engine/deadlines.Budget node). The orchestrator only passes one to
    # adapters that opt in, so third-party/test subclasses overriding
    # execute_round with the legacy (turns, timeout_ms) signature keep
    # working unchanged.
    accepts_budget = False

    def __init__(self, name: str):
        self.name = name

    @abstractmethod
    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        """Run one prompt to completion and return the raw response text."""

    def execute_for(self, knight_name: str, prompt: str,
                    timeout_ms: int = DEFAULT_TIMEOUT_MS,
                    budget=None) -> str:
        """Execute one turn attributed to `knight_name`. Cloud/CLI
        adapters ignore the name (and the budget — their own process
        timeouts bound the turn); engine-backed adapters override so the
        knight keeps its own KV slot and per-knight sampling even when a
        round degrades from the batched path to serial turns."""
        return self.execute(prompt, timeout_ms)

    @abstractmethod
    def is_available(self) -> bool:
        """Probe whether this backend can serve requests right now."""

    def get_max_source_chars(self) -> Optional[int]:
        """Context-budget hook: max source chars this knight can carry.

        None means "no special limit" → orchestrator default 200KB
        (reference base.ts:22-24, orchestrator.ts:281-292).
        """
        return None

    def parse_consensus(self, response: str, round_num: int
                        ) -> Optional[ConsensusBlock]:
        """Default delegates to the consensus engine (reference base.ts:26-28)."""
        return parse_consensus_from_response(response, self.name, round_num)

    # --- TPU-build extension ---

    def supports_batched_rounds(self) -> bool:
        """True when execute_round is a genuine batched dispatch."""
        return False

    def known_unhealthy(self) -> bool:
        """Cheap, NON-constructive health check: True only when this
        adapter already knows it cannot serve (open circuit breaker,
        memoized dead engine). Unlike is_available() it must never
        trigger lazy engine construction — the orchestrator calls it
        synchronously while forming batch groups."""
        return False

    def last_stats(self) -> Optional[dict]:
        """Engine-side numbers for the most recent execute/execute_round
        (token counts, prefill/decode tok/s) — None for backends that
        don't measure. Consumed by the session metrics (utils/metrics.py)."""
        return None

    def execute_round(self, turns: list[KnightTurn],
                      timeout_ms: int = DEFAULT_TIMEOUT_MS,
                      budget=None) -> list[str]:
        """Execute N same-round prompts. Default: serial loop over execute().

        The tpu-llm adapter overrides this with one batched forward pass over
        N persistent KV slots (SURVEY.md §2.3 parallelism table) and
        splits `budget` (a round-rung deadlines.Budget) across the
        batched attempt and any serial retries.
        """
        return [self.execute_for(t.knight_name, t.prompt, timeout_ms)
                for t in turns]
