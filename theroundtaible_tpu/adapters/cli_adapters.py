"""CLI-spawn adapters: Claude, Gemini, Codex (OpenAI).

Parity with reference src/adapters/{claude-cli,gemini-cli,openai-cli}.ts.
Each spawns the vendor CLI with the prompt on stdin, read-only tool settings,
and a per-turn timeout; availability is a `--version` probe.
"""

from __future__ import annotations

import json
import os
import subprocess
from typing import Optional

from ..core.errors import AdapterError, classify_error
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS


def _probe_version(command: str) -> bool:
    try:
        proc = subprocess.run([command, "--version"], capture_output=True,
                              timeout=15)
        return proc.returncode == 0
    except (OSError, subprocess.TimeoutExpired):
        return False


def _spawn(command: str, args: list[str], prompt: str, timeout_ms: int,
           env: Optional[dict[str, str]] = None) -> subprocess.CompletedProcess:
    try:
        return subprocess.run(
            [command, *args], input=prompt, capture_output=True, text=True,
            timeout=timeout_ms / 1000, env=env, errors="replace",
        )
    except subprocess.TimeoutExpired as e:
        raise AdapterError(f"{command} timed out after {timeout_ms // 1000}s",
                           kind="timeout", cause=e)
    except OSError as e:
        raise AdapterError(f"{command} not found: {e}", kind="not_installed",
                           cause=e)


class ClaudeCliAdapter(BaseAdapter):
    """`claude --print` with write tools disabled (reference claude-cli.ts:5-58)."""

    DISALLOWED_TOOLS = ("Edit,Write,Bash,Read,Glob,Grep,NotebookEdit,"
                        "WebFetch,WebSearch,Task")

    def __init__(self, command: str = "claude",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("Claude")
        self.command = command
        self.default_timeout = timeout_ms

    def is_available(self) -> bool:
        return _probe_version(self.command)

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        # Drop CLAUDECODE so nested invocation from inside Claude Code works
        # (reference claude-cli.ts:33-34 — empty string is not enough).
        env = dict(os.environ)
        env.pop("CLAUDECODE", None)
        result = _spawn(self.command, [
            "--print", "--output-format", "text",
            "--disallowedTools", self.DISALLOWED_TOOLS,
        ], prompt, timeout_ms or self.default_timeout, env=env)
        if result.returncode != 0:
            msg = result.stderr or result.stdout or "Unknown error"
            raise AdapterError(
                f"Claude CLI failed (exit {result.returncode}): {msg}",
                kind=classify_error(RuntimeError(msg)))
        return result.stdout


class GeminiCliAdapter(BaseAdapter):
    """`gemini -p "" --approval-mode plan` (reference gemini-cli.ts:5-77)."""

    # The CLI's own default model often 429s for free accounts; pin a stable
    # one unless config overrides (reference gemini-cli.ts:8-11).
    DEFAULT_MODEL = "gemini-2.5-pro"

    def __init__(self, command: str = "gemini", model: Optional[str] = None,
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("Gemini")
        self.command = command
        self.model = model or self.DEFAULT_MODEL
        self.default_timeout = timeout_ms

    def is_available(self) -> bool:
        return _probe_version(self.command)

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        timeout = timeout_ms or self.default_timeout
        base_args = ["-p", "", "--approval-mode", "plan", "-o", "text",
                     "-m", self.model]
        result = _spawn(self.command, base_args, prompt, timeout)
        # plan mode needs experimental.plan in gemini config — retry without
        # (reference gemini-cli.ts:53-59).
        if result.returncode != 0 and "approval-mode" in (result.stderr or ""):
            result = _spawn(self.command,
                            ["-p", "", "-o", "text", "-m", self.model],
                            prompt, timeout)
        # Non-zero exits with usable stdout happen on tool denials in plan
        # mode; accept output > 50 chars (reference gemini-cli.ts:62-65).
        if result.stdout and len(result.stdout.strip()) > 50:
            return result.stdout
        if result.returncode != 0:
            msg = result.stderr or result.stdout or "Unknown error"
            raise AdapterError(
                f"Gemini CLI failed (exit {result.returncode}): {msg}",
                kind=classify_error(RuntimeError(msg)))
        return result.stdout


class OpenAICliAdapter(BaseAdapter):
    """`codex exec - --json` JSONL stream parsing (reference openai-cli.ts:5-94)."""

    def __init__(self, command: str = "codex",
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__("GPT")
        self.command = command
        self.default_timeout = timeout_ms

    def is_available(self) -> bool:
        return _probe_version(self.command)

    @staticmethod
    def _inside_git_repo() -> bool:
        try:
            proc = subprocess.run(
                ["git", "rev-parse", "--is-inside-work-tree"],
                capture_output=True, timeout=10)
            return proc.returncode == 0
        except (OSError, subprocess.TimeoutExpired):
            return False

    @staticmethod
    def extract_agent_message(jsonl: str) -> str:
        """Collect text from item.completed/agent_message events; ignore
        non-JSON log lines (reference openai-cli.ts:41-56)."""
        parts: list[str] = []
        for line in jsonl.splitlines():
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                evt = json.loads(line)
            except json.JSONDecodeError:
                continue
            item = evt.get("item") or {}
            if (evt.get("type") == "item.completed"
                    and item.get("type") == "agent_message"
                    and isinstance(item.get("text"), str)):
                parts.append(item["text"])
        return "\n".join(parts).strip()

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        args = ["exec", "-", "--sandbox", "read-only", "--json",
                "--color", "never"]
        if not self._inside_git_repo():
            args.append("--skip-git-repo-check")
        result = _spawn(self.command, args, prompt,
                        timeout_ms or self.default_timeout)
        if result.returncode != 0:
            msg = result.stderr or result.stdout or "Unknown error"
            raise AdapterError(
                f"Codex CLI failed (exit {result.returncode}): {msg}",
                kind=classify_error(RuntimeError(msg)))
        message = self.extract_agent_message(result.stdout)
        if not message:
            raise AdapterError("Codex CLI returned no agent_message events",
                               kind="api")
        return message
