"""Scripted FakeAdapter — the hermetic test seam.

No reference counterpart (the reference has no fakes, SURVEY.md §4); this is
the harness its BaseAdapter seam was designed to enable: a deterministic
knight whose responses are scripted per call, driving full discuss flows
without any external process.
"""

from __future__ import annotations

from typing import Callable, Optional, Union

from .base import BaseAdapter, DEFAULT_TIMEOUT_MS, KnightTurn

ScriptItem = Union[str, Exception]


class FakeAdapter(BaseAdapter):
    """Returns scripted responses in order; repeats the last one when the
    script runs out. An Exception in the script is raised instead."""

    def __init__(self, name: str = "Fake",
                 script: Optional[list[ScriptItem]] = None,
                 available: bool = True,
                 max_source_chars: Optional[int] = None,
                 on_execute: Optional[Callable[[str], None]] = None):
        super().__init__(name)
        self.script = list(script or [])
        self.available = available
        self.max_source_chars = max_source_chars
        self.on_execute = on_execute
        self.calls: list[str] = []
        self.batched_calls: list[list[str]] = []

    def is_available(self) -> bool:
        return self.available

    def get_max_source_chars(self) -> Optional[int]:
        return self.max_source_chars

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        self.calls.append(prompt)
        if self.on_execute:
            self.on_execute(prompt)
        if not self.script:
            return self._consensus_response(9)
        idx = min(len(self.calls) - 1, len(self.script) - 1)
        item = self.script[idx]
        if isinstance(item, Exception):
            raise item
        return item

    def execute_round(self, turns: list[KnightTurn],
                      timeout_ms: int = DEFAULT_TIMEOUT_MS) -> list[str]:
        self.batched_calls.append([t.prompt for t in turns])
        return super().execute_round(turns, timeout_ms)

    @staticmethod
    def _consensus_response(score: int, files: Optional[list[str]] = None,
                            text: str = "Sounds good.") -> str:
        import json
        block = {"consensus_score": score, "agrees_with": [],
                 "pending_issues": []}
        if files:
            block["files_to_modify"] = files
        return f"{text}\n```json\n{json.dumps(block)}\n```"


def scripted_response(score: int, text: str = "My analysis.",
                      files: Optional[list[str]] = None,
                      file_requests: Optional[list[str]] = None,
                      verify_commands: Optional[list[str]] = None,
                      pending: Optional[list[str]] = None,
                      proposal: Optional[str] = None) -> str:
    """Build a well-formed knight response for scripting tests."""
    import json
    block: dict = {"consensus_score": score, "agrees_with": [],
                   "pending_issues": pending or []}
    if files is not None:
        block["files_to_modify"] = files
    if file_requests is not None:
        block["file_requests"] = file_requests
    if verify_commands is not None:
        block["verify_commands"] = verify_commands
    if proposal is not None:
        block["proposal"] = proposal
    return f"{text}\n```json\n{json.dumps(block)}\n```"
