"""`tpu-llm` adapter — knights served by the in-tree JAX/XLA engine.

This is the component that replaces the reference's local-llm → Ollama/
LM Studio → CUDA llama.cpp stack (reference src/adapters/local-llm.ts;
SURVEY.md §2.3). The adapter is a thin host-side shim: tokenize → dispatch to
the engine's sharded prefill+decode → detokenize. Engine construction is lazy
and cached per checkpoint so several knights (or several adapters) share one
resident model.
"""

from __future__ import annotations

from typing import Any, Optional

from ..core.errors import AdapterError, classify_error
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS, KnightTurn

# Reserves mirror the local-llm budget contract (reference local-llm.ts:58-70),
# but get_max_source_chars answers from REAL tokenizer counts downstream.
RESPONSE_RESERVE_TOKENS = 4096
OVERHEAD_RESERVE_TOKENS = 3000
MIN_AVAILABLE_TOKENS = 2000


class TpuLlmAdapter(BaseAdapter):
    """BaseAdapter over an EngineHandle (theroundtaible_tpu.engine)."""

    def __init__(self, name: str, engine_config: dict[str, Any],
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__(name)
        self.engine_config = dict(engine_config)
        self.default_timeout = timeout_ms
        self._engine = None
        self._engine_error: Optional[str] = None
        self._last_stats: Optional[dict] = None

    @classmethod
    def from_config(cls, adapter_id: str, cfg: dict[str, Any],
                    timeout_ms: int = DEFAULT_TIMEOUT_MS) -> "TpuLlmAdapter":
        return cls(name=cfg.get("name", adapter_id), engine_config=cfg,
                   timeout_ms=timeout_ms)

    # --- engine lifecycle ---

    def _get_engine(self):
        if self._engine is None and self._engine_error is None:
            try:
                from ..engine import get_engine
                self._engine = get_engine(self.engine_config)
            except Exception as e:  # noqa: BLE001 — surfaced via is_available
                self._engine_error = str(e)
        if self._engine is None:
            raise AdapterError(
                f"TPU engine unavailable: {self._engine_error}",
                kind=classify_error(RuntimeError(self._engine_error or "")))
        return self._engine

    def is_available(self) -> bool:
        try:
            self._get_engine()
            return True
        except AdapterError:
            return False

    # --- serving ---

    def get_max_source_chars(self) -> Optional[int]:
        """Budget from the engine's real max_seq_len and tokenizer
        chars-per-token ratio (replaces the 4-chars/token estimate)."""
        try:
            engine = self._get_engine()
        except AdapterError:
            return None
        ctx = engine.max_seq_len
        available = max(ctx - RESPONSE_RESERVE_TOKENS - OVERHEAD_RESERVE_TOKENS,
                        MIN_AVAILABLE_TOKENS)
        return int(available * engine.chars_per_token())

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        return self.execute_round(
            [KnightTurn(knight_name=self.name, prompt=prompt)], timeout_ms)[0]

    def supports_batched_rounds(self) -> bool:
        return True

    def _sampling_for(self, knight_name: str):
        """Per-knight SamplingParams: `knight_sampling: {name: {...}}` in
        the adapter config overrides the engine default per seat —
        heterogeneous personas (a hotter skeptic, a greedy pragmatist)
        sample correctly inside the same batched program."""
        overrides = self.engine_config.get("knight_sampling", {})
        cfg = overrides.get(knight_name)
        if not cfg:
            return None
        from ..engine.sampling import SamplingParams
        base = self._get_engine().sampling
        return SamplingParams(
            temperature=float(cfg.get("temperature", base.temperature)),
            top_k=int(cfg.get("top_k", base.top_k)),
            top_p=float(cfg.get("top_p", base.top_p)),
            # per-row decode budgets: a terse knight stops at its own
            # cap while the batch keeps decoding (engine decode_while)
            max_new_tokens=int(cfg.get("max_new_tokens",
                                       base.max_new_tokens)))

    def execute_round(self, turns: list[KnightTurn],
                      timeout_ms: int = DEFAULT_TIMEOUT_MS) -> list[str]:
        """One batched forward pass over N persistent per-knight KV slots."""
        engine = self._get_engine()
        self._last_stats = None  # a failed call must not leave stale stats
        per_turn = None
        if self.engine_config.get("knight_sampling"):
            per_turn = [self._sampling_for(t.knight_name)
                        or engine.sampling for t in turns]
        try:
            kwargs = {"timeout_s": (timeout_ms or self.default_timeout)
                      / 1000}
            if per_turn is not None:
                kwargs["sampling_per_turn"] = per_turn
                # call-level cap = the LARGEST per-knight budget, so a
                # knight configured above the engine default isn't
                # silently clamped (row budgets bound each row below it)
                kwargs["max_new_tokens"] = max(
                    p.max_new_tokens for p in per_turn)
            responses, stats = engine.generate_batch_with_stats(
                [(t.knight_name, t.prompt) for t in turns], **kwargs)
        except Exception as e:  # noqa: BLE001
            raise AdapterError(str(e), kind=classify_error(e), cause=e)
        # per-call snapshot, NOT engine.last_stats — adapters sharing one
        # cached engine would otherwise read each other's numbers
        self._last_stats = {
            "model": engine.cfg.name,
            "prefill_tokens": stats.prefill_tokens,
            "reused_tokens": stats.reused_tokens,
            "decode_tokens": stats.decode_tokens,
            "prefill_seconds": round(stats.prefill_seconds, 3),
            "decode_seconds": round(stats.decode_seconds, 3),
            "prefill_tps": round(stats.prefill_tps, 1),
            "decode_tps": round(stats.decode_tps, 1),
        }
        return responses

    def last_stats(self) -> Optional[dict]:
        return self._last_stats
