"""`tpu-llm` adapter — knights served by the in-tree JAX/XLA engine.

This is the component that replaces the reference's local-llm → Ollama/
LM Studio → CUDA llama.cpp stack (reference src/adapters/local-llm.ts;
SURVEY.md §2.3). The adapter is a thin host-side shim: tokenize → dispatch to
the engine's sharded prefill+decode → detokenize. Engine construction is lazy
and cached per checkpoint so several knights (or several adapters) share one
resident model.

Fault tolerance (ISSUE 1, ARCHITECTURE.md "Fault tolerance"): this is the
adapter rung of the degradation ladder. A failed BATCHED round invalidates
the batch's KV slots and retries the knights serially (smaller programs,
per-knight isolation) before giving up; every final failure feeds the
engine's shared circuit breaker (engine.get_breaker — keyed like the engine
cache, so adapters sharing a resident engine share its health), and once the
breaker opens `is_available()` reports False with the breaker's reason so
the orchestrator's runtime-fallback path seats the knight elsewhere instead
of feeding more turns into a sick engine.
"""

from __future__ import annotations

import warnings
from typing import Any, Optional

from ..core.errors import AdapterError, classify_error
from ..engine import deadlines
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS, KnightTurn

# Reserves mirror the local-llm budget contract (reference local-llm.ts:58-70),
# but get_max_source_chars answers from REAL tokenizer counts downstream.
RESPONSE_RESERVE_TOKENS = 4096
OVERHEAD_RESERVE_TOKENS = 3000
MIN_AVAILABLE_TOKENS = 2000

# Fraction of a multi-knight round's budget the BATCHED attempt may
# consume (ISSUE 2: the round budget SPLITS across batched/serial
# attempts instead of one shared ad-hoc deadline): a hung/wedged batch
# must leave the serial-retry rung real time to serve the knights.
# Config key "batch_budget_fraction" overrides. Single-turn rounds have
# no serial rung and get the whole budget.
BATCH_BUDGET_FRACTION = 0.6


def _engine_serves_lora(engine) -> bool:
    """True when this engine resolved an adapter store (ISSUE 10).
    Gates the adapters_per_turn kwarg: the PP engine's
    generate_batch_with_stats has no such parameter, and a lora-off
    engine serves base regardless — both must decline gracefully."""
    return getattr(engine, "lora", None) is not None


class TpuLlmAdapter(BaseAdapter):
    """BaseAdapter over an EngineHandle (theroundtaible_tpu.engine)."""

    def __init__(self, name: str, engine_config: dict[str, Any],
                 timeout_ms: int = DEFAULT_TIMEOUT_MS,
                 session: Optional[str] = None):
        super().__init__(name)
        self.engine_config = dict(engine_config)
        self.default_timeout = timeout_ms
        # Session identity (ISSUE 4): namespaces this adapter's KV slot
        # names (kvcache.scoped_slot) so concurrent discussions sharing
        # one resident engine never collide — and routes rounds through
        # the attached continuous-batching scheduler when one is set.
        self.session = session
        # Persona adapter id (ISSUE 10): the LoRA adapter this knight
        # speaks through on a shared-base engine — `lora_adapter` is
        # the adapter-level default, `knight_adapters: {name: id}`
        # overrides per seat (the knight_sampling pattern). None (or a
        # lora-off engine) serves the base model.
        self.persona_adapter = engine_config.get("lora_adapter")
        self._scheduler = None
        self._engine = None
        self._engine_error: Optional[str] = None
        self._last_stats: Optional[dict] = None
        # Which degradation rung served the last round, if any
        # ("serial_retry"); chaos tests and metrics read it.
        self.last_degradation: Optional[str] = None
        # Classified kind of the failure the last round RECOVERED from
        # ("hang", "oom", ...); None when the round served clean. The
        # hang acceptance check and status surfaces read it.
        self.last_recovered_kind: Optional[str] = None

    @classmethod
    def from_config(cls, adapter_id: str, cfg: dict[str, Any],
                    timeout_ms: int = DEFAULT_TIMEOUT_MS) -> "TpuLlmAdapter":
        return cls(name=cfg.get("name", adapter_id), engine_config=cfg,
                   timeout_ms=timeout_ms)

    # --- engine lifecycle + health ---

    def breaker(self):
        """The engine-cache-shared CircuitBreaker for this config."""
        from ..engine import get_breaker
        return get_breaker(self.engine_config)

    def _get_engine(self, retry_construction: bool = False):
        if (retry_construction and self._engine is None
                and self._engine_error is not None):
            # The caller was admitted by the breaker (closed, or its
            # half-open probe), so a memoized construction failure gets a
            # fresh attempt: a checkpoint fixed after startup (or freed
            # HBM) closes the breaker in-process on the SAME admitted
            # call instead of staying memoized-dead. Passive callers
            # (is_available, get_max_source_chars) keep the memo.
            self._engine_error = None
        if self._engine is None and self._engine_error is None:
            try:
                from ..engine import get_engine
                self._engine = get_engine(self.engine_config)
            except Exception as e:  # noqa: BLE001 — surfaced via is_available
                self._engine_error = str(e)
                # A construction failure is permanent, not transient (and
                # memoized — it would only ever count once), so it OPENS
                # the breaker outright: fleet_health must report a dead
                # engine as open, not eternally 'degraded'.
                self.breaker().trip(e)
        if self._engine is None:
            raise AdapterError(
                f"TPU engine unavailable: {self._engine_error}",
                kind=classify_error(RuntimeError(self._engine_error or "")))
        return self._engine

    def attach_scheduler(self, scheduler,
                         session: Optional[str] = None) -> None:
        """Route this adapter's rounds through a shared continuous-
        batching SessionScheduler (engine/scheduler.py). Every rung of
        the degradation ladder — the batched attempt AND the per-knight
        serial retries — then goes through the scheduler's queue, so a
        degraded session keeps co-scheduling with healthy ones instead
        of seizing the engine serially.

        A scheduled adapter ALWAYS has a session id: with none given
        (and none set), a unique one is generated — the adapter NAME is
        not unique (the factory names every instance by adapter id), and
        two adapters falling back to one shared name would share an
        isolation domain, re-creating exactly the cross-session slot
        collision the namespace exists to prevent."""
        self._scheduler = scheduler
        if session is not None:
            self.session = session
        elif not self.session:
            import uuid
            self.session = f"{self.name}-{uuid.uuid4().hex[:8]}"

    def _effective_session(self) -> Optional[str]:
        """The session namespace the engine-side slots actually live
        under. _serve and _slot_name MUST agree, or serial-retry slot
        invalidation would release a name the scheduler never allocated;
        attach_scheduler guarantees a session id whenever a scheduler
        is attached."""
        return self.session

    def _serve(self, engine, turn_pairs, **kwargs):
        """The one engine-call seam: scheduled sessions submit to the
        shared batch; unscheduled calls hit the engine directly with the
        session namespace applied."""
        if self._scheduler is not None:
            return self._scheduler.submit(
                self._effective_session(), turn_pairs, **kwargs)
        return engine.generate_batch_with_stats(
            turn_pairs, session=self.session, **kwargs)

    def _slot_name(self, knight_name: str) -> str:
        """The engine-side slot name for a knight of THIS session."""
        from ..engine.kvcache import scoped_slot
        return scoped_slot(self._effective_session(), knight_name)

    def known_unhealthy(self) -> bool:
        # No construction here (contract): just the breaker verdict and
        # the memoized construction failure.
        return self.breaker().is_open or self._engine_error is not None

    def is_available(self) -> bool:
        if self.breaker().is_open:
            return False
        try:
            self._get_engine()
            return True
        except AdapterError:
            return False

    def unavailable_reason(self) -> Optional[str]:
        """Why is_available() is False (None when it isn't): the open
        breaker's reason, or the engine construction error."""
        reason = self.breaker().reason
        return reason if reason else self._engine_error

    # --- serving ---

    def get_max_source_chars(self) -> Optional[int]:
        """Budget from the engine's real max_seq_len and tokenizer
        chars-per-token ratio (replaces the 4-chars/token estimate)."""
        try:
            engine = self._get_engine()
        except AdapterError:
            return None
        ctx = engine.max_seq_len
        available = max(ctx - RESPONSE_RESERVE_TOKENS - OVERHEAD_RESERVE_TOKENS,
                        MIN_AVAILABLE_TOKENS)
        return int(available * engine.chars_per_token())

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        return self.execute_for(self.name, prompt, timeout_ms)

    def execute_for(self, knight_name: str, prompt: str,
                    timeout_ms: int = DEFAULT_TIMEOUT_MS,
                    budget=None) -> str:
        # Keyed by the KNIGHT, not the adapter: a knight degraded off the
        # batched path onto serial turns keeps its own KV slot and
        # per-knight sampling instead of colliding on the adapter's name.
        return self.execute_round(
            [KnightTurn(knight_name=knight_name, prompt=prompt)],
            timeout_ms, budget=budget)[0]

    accepts_budget = True

    def supports_batched_rounds(self) -> bool:
        return True

    def _adapter_for(self, knight_name: str) -> Optional[str]:
        """The LoRA persona adapter id for a seat: per-knight
        `knight_adapters` map first, then the adapter-level
        `lora_adapter` default."""
        overrides = self.engine_config.get("knight_adapters", {})
        return overrides.get(knight_name, self.persona_adapter)

    def _adapters_for(self, turns) -> Optional[list]:
        """Per-turn adapter ids for one round, or None when every
        seat serves the base model (the common non-persona fleet keeps
        its exact pre-LoRA call signature)."""
        ads = [self._adapter_for(t.knight_name) for t in turns]
        return ads if any(a is not None for a in ads) else None

    def _sampling_for(self, knight_name: str):
        """Per-knight SamplingParams: `knight_sampling: {name: {...}}` in
        the adapter config overrides the engine default per seat —
        heterogeneous personas (a hotter skeptic, a greedy pragmatist)
        sample correctly inside the same batched program."""
        overrides = self.engine_config.get("knight_sampling", {})
        cfg = overrides.get(knight_name)
        if not cfg:
            return None
        from ..engine.sampling import SamplingParams
        base = self._get_engine().sampling
        return SamplingParams(
            temperature=float(cfg.get("temperature", base.temperature)),
            top_k=int(cfg.get("top_k", base.top_k)),
            top_p=float(cfg.get("top_p", base.top_p)),
            # per-row decode budgets: a terse knight stops at its own
            # cap while the batch keeps decoding (engine decode_while)
            max_new_tokens=int(cfg.get("max_new_tokens",
                                       base.max_new_tokens)))

    def execute_round(self, turns: list[KnightTurn],
                      timeout_ms: int = DEFAULT_TIMEOUT_MS,
                      budget=None) -> list[str]:
        """One batched forward pass over N persistent per-knight KV slots.

        Failure handling: a failed batched dispatch degrades to serial
        per-knight retry (_serial_retry); the final outcome — success or
        AdapterError — is recorded on the engine's circuit breaker.

        Time ladder (ISSUE 2): `budget` is the round-rung Budget the
        orchestrator threads down (None builds a local root from
        timeout_ms). The round budget is SPLIT across the degradation
        attempts — the batched dispatch gets BATCH_BUDGET_FRACTION of it
        when a serial rung exists to fall back to, and each serial
        retry gets a fair share of whatever remains — so a hung batch
        can never consume the time its recovery path needs, and
        execute_round's timeout contract never multiplies into (N+1)x
        under degradation."""
        breaker = self.breaker()
        # Clear BEFORE the fail-fast below: a failed call — including one
        # that never dispatched — must not leave stale stats.
        self._last_stats = None
        self.last_degradation = None
        self.last_recovered_kind = None
        if not breaker.should_attempt():
            # Fail fast with the health verdict instead of dispatching
            # into a sick engine (should_attempt still admits periodic
            # half-open probes, so a recovered engine closes the breaker
            # again); the orchestrator's fallback path reads this as any
            # other adapter failure. The kind comes from the breaker's
            # underlying error so the operator sees the oom/timeout hint
            # that actually applies, not a generic backend-error one.
            reason = breaker.reason or ""
            raise AdapterError(f"TPU engine unavailable: {reason}",
                               kind=classify_error(RuntimeError(reason)))
        # AFTER the breaker gate: this call was admitted (closed breaker
        # or half-open probe), so a memoized construction failure gets
        # one fresh attempt — and on success the same call dispatches
        # and closes the breaker, re-seating the knights in one probe.
        engine = self._get_engine(retry_construction=True)
        per_turn = None
        if self.engine_config.get("knight_sampling"):
            per_turn = [self._sampling_for(t.knight_name)
                        or engine.sampling for t in turns]
        # ONE round budget bounds the batched attempt and every serial
        # retry (its deadline is the old shared float); the splits
        # happen inside _dispatch_round/_serial_retry.
        timeout_s = (timeout_ms or self.default_timeout) / 1000
        round_budget = (budget.child("round", timeout_s=timeout_s)
                        if budget is not None
                        else deadlines.Budget.root(timeout_s, rung="round"))
        try:
            responses, stats = self._dispatch_round(engine, turns, per_turn,
                                                    round_budget)
        except Exception as e:  # noqa: BLE001
            breaker.record_failure(e)
            # A failure after donation consumed the KV buffers must not
            # brick the engine: single-turn rounds re-raise before
            # _serial_retry's revive, so without this the breaker's
            # half-open probes would die on 'Array has been deleted'
            # for the process lifetime.
            self._revive_best_effort(engine)
            if isinstance(e, AdapterError):
                raise
            raise AdapterError(str(e), kind=classify_error(e), cause=e)
        breaker.record_success()
        # per-call snapshot, NOT engine.last_stats — adapters sharing one
        # cached engine would otherwise read each other's numbers
        self._last_stats = {
            "model": engine.cfg.name,
            "prefill_tokens": stats.prefill_tokens,
            "reused_tokens": stats.reused_tokens,
            # Of which the CROSS-SESSION prefix cache served (ISSUE 7) —
            # 0 on contiguous / cache-off engines.
            "prefix_reused_tokens": stats.prefix_reused_tokens,
            "decode_tokens": stats.decode_tokens,
            "prefill_seconds": round(stats.prefill_seconds, 3),
            "decode_seconds": round(stats.decode_seconds, 3),
            "prefill_tps": round(stats.prefill_tps, 1),
            "decode_tps": round(stats.decode_tps, 1),
        }
        if stats.int4_paths is not None:
            # Path provenance (ISSUE 3): which einsum dispatches ran the
            # fused w4a16 kernels vs the XLA dequant fallback — rides the
            # per-turn engine stats into metrics.json so a window's int4
            # numbers are attributable.
            self._last_stats["int4_paths"] = stats.int4_paths
        if stats.sched is not None:
            # Scheduler provenance (ISSUE 4): queue wait + decode-batch
            # occupancy ride the per-turn stats into metrics.json, same
            # pattern as int4_paths.
            self._last_stats["sched"] = stats.sched
        if self.last_degradation:
            self._last_stats["degraded"] = self.last_degradation
        if self.last_recovered_kind:
            self._last_stats["recovered_from"] = self.last_recovered_kind
        return responses

    def _dispatch_round(self, engine, turns, per_turn, round_budget):
        # Budget split, batched rung: a multi-knight batch gets a
        # FRACTION of the round (the serial rung must still have room
        # behind it); a single-turn round has no fallback and gets all.
        if len(turns) > 1:
            frac = float(self.engine_config.get(
                "batch_budget_fraction", BATCH_BUDGET_FRACTION))
            batch_budget = round_budget.child(
                "turn", timeout_s=round_budget.remaining() * frac)
        else:
            batch_budget = round_budget.child("turn")
        kwargs: dict[str, Any] = {
            "timeout_s": max(batch_budget.remaining(), 0.0),
            "budget": batch_budget}
        ads = self._adapters_for(turns)
        if ads is not None and _engine_serves_lora(engine):
            # Persona adapters ride the round into the engine /
            # scheduler (ISSUE 10); co-batched knights with DIFFERENT
            # personas decode in one mixed-adapter segment. Engines
            # without a lora store — the PP engine, a kill-switched or
            # config-less InferenceEngine — serve the base model
            # instead of choking on an unknown kwarg (the
            # ROUNDTABLE_LORA=0 byte-identity contract).
            kwargs["adapters_per_turn"] = ads
        if per_turn is not None:
            kwargs["sampling_per_turn"] = per_turn
            # call-level cap = the LARGEST per-knight budget, so a
            # knight configured above the engine default isn't
            # silently clamped (row budgets bound each row below it)
            kwargs["max_new_tokens"] = max(
                p.max_new_tokens for p in per_turn)
        try:
            return self._serve(
                engine, [(t.knight_name, t.prompt) for t in turns],
                **kwargs)
        except Exception as batch_err:  # noqa: BLE001
            if len(turns) < 2:
                raise
            return self._serial_retry(engine, turns, per_turn,
                                      round_budget, batch_err)

    def _serial_retry(self, engine, turns, per_turn, round_budget,
                      batch_err):
        """Batched-round degradation rung: the fan-out failed, so the
        round becomes best-effort — invalidate the batch's KV slots (a
        mid-flight failure may have left partial scatter writes) and
        serve each knight as its own single-row program. Smaller
        programs, per-knight isolation: one knight's pathology no longer
        dooms the whole round. Every serial attempt runs inside the
        ROUND's remaining budget — a timed-out batch does not buy N
        fresh timeouts — and each knight gets a FAIR SHARE of what is
        left (remaining / knights-still-waiting, so early finishers
        donate their surplus to later knights but a single wedged
        knight can never starve the rest)."""
        if round_budget.remaining() <= 0:
            # No time left to retry anything: surface the timeout BEFORE
            # the destructive slot invalidation below, so the knights'
            # cached conversation KV survives for the next round instead
            # of being wiped for zero benefit.
            raise AdapterError(
                f"batched round failed ({batch_err}) and the round's "
                "deadline passed before serial retry could start",
                kind="timeout")
        warnings.warn(
            f"batched round failed ({batch_err}); invalidating the "
            f"batch's KV slots and retrying {len(turns)} knight(s) "
            "serially", stacklevel=3)
        # Ladder escalation ships its own postmortem (ISSUE 5): the
        # flight ring at this moment holds the failed batch's spans and
        # whatever the hang/fault machinery recorded before it.
        from ..utils import telemetry
        telemetry.inc("roundtable_degradations_total",
                      rung="serial_retry")
        telemetry.recorder().record(
            "ladder_escalation", rung="serial_retry",
            adapter=self.name, error=str(batch_err)[:200])
        telemetry.flight_dump(
            "ladder_escalation",
            extra={"rung": "serial_retry", "adapter": self.name,
                   "error": str(batch_err)[:500]})
        # A failure that surfaced AFTER donation consumed the KV cache
        # (jit programs donate the cache buffers) left the engine holding
        # deleted arrays — reallocate fresh buffers first, else every
        # serial retry dies on the secondary 'Array has been deleted'
        # error instead of re-prefilling.
        if self._revive_best_effort(engine):
            warnings.warn(
                "KV buffers were consumed by the failed dispatch; "
                "reallocated fresh pools (all cached slots lost)",
                stacklevel=3)
        if self._scheduler is None:
            # Release the SESSION-SCOPED slots (the names the engine
            # actually allocated). Scheduled sessions skip this: the
            # scheduler's _fail_request already released the failed
            # round's slots ON ITS OWN THREAD — releasing here would
            # mutate shared SlotBook/PagedKVCache host state from the
            # session thread while the scheduler thread iterates it
            # (dict-changed-during-iteration crashes the loop and fails
            # every other session).
            for t in turns:
                engine.kv.release(self._slot_name(t.knight_name))
        from ..engine.engine import GenStats
        total = GenStats()
        responses = []
        failures: list[tuple[str, Exception]] = []
        for i, t in enumerate(turns):
            remaining = round_budget.remaining()
            if remaining <= 0:
                raise AdapterError(
                    f"batched round failed ({batch_err}) and the round's "
                    f"deadline passed during serial retry at knight "
                    f"{t.knight_name}", kind="timeout")
            # Fair share of the remaining round budget: knights still
            # waiting split it evenly, recomputed per knight so early
            # finishers' surplus flows to later ones.
            knight_budget = round_budget.child(
                "turn", timeout_s=remaining / (len(turns) - i))
            kwargs: dict[str, Any] = {
                "timeout_s": max(knight_budget.remaining(), 0.0),
                "budget": knight_budget}
            ad = self._adapter_for(t.knight_name)
            if ad is not None and _engine_serves_lora(engine):
                kwargs["adapters_per_turn"] = [ad]
            if per_turn is not None:
                kwargs["sampling_per_turn"] = [per_turn[i]]
                kwargs["max_new_tokens"] = per_turn[i].max_new_tokens
            try:
                # Through the scheduler when attached: the degraded
                # session's serial turns co-schedule with OTHER sessions'
                # healthy rows instead of seizing the engine.
                out, stats = self._serve(
                    engine, [(t.knight_name, t.prompt)], **kwargs)
            except Exception as serial_err:  # noqa: BLE001
                # Best-effort really means it: one knight's pathology
                # must not abandon the rest of the round. Keep serving
                # the remaining knights (revive first, in case THIS
                # failure consumed the buffers); the succeeded knights'
                # committed KV makes the orchestrator's per-knight
                # re-run cheap via prefix reuse.
                failures.append((t.knight_name, serial_err))
                self._revive_best_effort(engine)
                continue
            responses.append(out[0])
            total.int4_paths = stats.int4_paths
            total.sched = stats.sched
            total.prefill_tokens += stats.prefill_tokens
            total.reused_tokens += stats.reused_tokens
            total.prefix_reused_tokens += stats.prefix_reused_tokens
            total.decode_tokens += stats.decode_tokens
            total.prefill_seconds += stats.prefill_seconds
            total.decode_seconds += stats.decode_seconds
        if failures:
            names = ", ".join(n for n, _ in failures)
            first = failures[0][1]
            raise AdapterError(
                f"batched round failed ({batch_err}) and serial retry "
                f"failed for knight(s) {names}: {first}",
                kind=classify_error(first), cause=first)
        self.last_degradation = "serial_retry"
        # What the round recovered FROM — a watchdog-detected hang is
        # recorded distinctly from a crash (ISSUE 2 acceptance).
        self.last_recovered_kind = classify_error(batch_err)
        return responses, total

    def _revive_best_effort(self, engine) -> bool:
        """revive_kv_if_dead that never raises: a broken revive must not
        mask the dispatch error the operator actually needs to see.
        Scheduled sessions never revive from here — the scheduler's
        _after_engine_failure owns donation-death recovery on its own
        thread (a session-thread revive would swap the pools out from
        under a concurrently-dispatching scheduler)."""
        if self._scheduler is not None:
            return False
        try:
            return getattr(engine, "revive_kv_if_dead", lambda: False)()
        except Exception:  # noqa: BLE001 — the dispatch error wins
            return False

    def last_stats(self) -> Optional[dict]:
        return self._last_stats
