"""Tiny HTTP helper shared by API/local adapters (stdlib-only)."""

from __future__ import annotations

import json
import urllib.error
import urllib.request
from typing import Any, Optional


class HttpError(Exception):
    def __init__(self, status: int, body: str, url: str):
        super().__init__(f"HTTP {status} from {url}: {body[:500]}")
        self.status = status
        self.body = body


def post_json(url: str, payload: dict[str, Any],
              headers: Optional[dict[str, str]] = None,
              timeout_s: float = 120.0) -> dict[str, Any]:
    data = json.dumps(payload).encode("utf-8")
    req = urllib.request.Request(url, data=data, method="POST")
    req.add_header("Content-Type", "application/json")
    for k, v in (headers or {}).items():
        req.add_header(k, v)
    try:
        with urllib.request.urlopen(req, timeout=timeout_s) as resp:
            return json.loads(resp.read().decode("utf-8"))
    except urllib.error.HTTPError as e:
        body = e.read().decode("utf-8", errors="replace")
        raise HttpError(e.code, body, url) from e


def get_ok(url: str, timeout_s: float = 3.0) -> bool:
    try:
        with urllib.request.urlopen(url, timeout=timeout_s) as resp:
            return 200 <= resp.status < 300
    except Exception:
        return False
