"""Local inference-server adapter (Ollama / LM Studio).

Parity with reference src/adapters/local-llm.ts:1-249 — kept so existing
local-GPU users can run unchanged next to `tpu-llm` knights:

- Ollama native /api/chat with dynamic num_ctx = est. prompt tokens + 4096
  response + 512 margin, clamped to the detected max (:95-144)
- OpenAI-compat /v1/chat/completions for LM Studio, deliberately without
  max_tokens (:150-199)
- context detection via Ollama /api/show → "*.context_length" (:205-235)
- source budget = (ctx − 4096 − 3000) × 4 chars/token, floor 2000 tokens;
  LM Studio assumed 16384 (:58-70)
- one retry after 3s on "Model reloaded" (:79-88)
- LM Studio context-overflow detection with an actionable message (:170-180)
"""

from __future__ import annotations

import math
import time
from typing import Optional

from ..core.errors import AdapterError, classify_error
from .base import BaseAdapter, DEFAULT_TIMEOUT_MS
from .httpx import HttpError, get_ok, post_json

RESPONSE_RESERVE_TOKENS = 4096
OVERHEAD_RESERVE_TOKENS = 3000
SAFETY_MARGIN_TOKENS = 512
MIN_AVAILABLE_TOKENS = 2000
LM_STUDIO_ASSUMED_CTX = 16384
CHARS_PER_TOKEN_ESTIMATE = 4


def _is_context_window_error(body: str) -> bool:
    lower = body.lower()
    return (("n_keep" in lower and "n_ctx" in lower)
            or "context length exceeded" in lower
            or "maximum context length" in lower
            or "too many tokens" in lower)


class LocalLlmAdapter(BaseAdapter):
    def __init__(self, endpoint: str, model: str, name: str,
                 source: Optional[str] = None,
                 timeout_ms: int = DEFAULT_TIMEOUT_MS):
        super().__init__(name)
        self.endpoint = endpoint.rstrip("/")
        self.model = model
        self.source = source  # "Ollama" | "LM Studio" | None
        self.default_timeout = timeout_ms
        self.detected_context_tokens: Optional[int] = None

    def is_available(self) -> bool:
        return get_ok(f"{self.endpoint}/v1/models", timeout_s=3)

    def detect_context_window(self) -> Optional[int]:
        if self.source == "Ollama":
            self.detected_context_tokens = self._detect_ollama_context()
        return self.detected_context_tokens

    def get_max_source_chars(self) -> Optional[int]:
        ctx = self.detected_context_tokens or (
            LM_STUDIO_ASSUMED_CTX if self.source == "LM Studio" else None)
        if not ctx:
            return None
        available = max(ctx - RESPONSE_RESERVE_TOKENS - OVERHEAD_RESERVE_TOKENS,
                        MIN_AVAILABLE_TOKENS)
        return available * CHARS_PER_TOKEN_ESTIMATE

    def execute(self, prompt: str, timeout_ms: int = DEFAULT_TIMEOUT_MS) -> str:
        run = (self._execute_ollama if self.source == "Ollama"
               else self._execute_openai_compat)
        try:
            return run(prompt, timeout_ms or self.default_timeout)
        except AdapterError as e:
            if "Model reloaded" in e.message:
                time.sleep(3)
                return run(prompt, timeout_ms or self.default_timeout)
            raise

    def _execute_ollama(self, prompt: str, timeout_ms: int) -> str:
        num_ctx = (math.ceil(len(prompt) / CHARS_PER_TOKEN_ESTIMATE)
                   + RESPONSE_RESERVE_TOKENS + SAFETY_MARGIN_TOKENS)
        if self.detected_context_tokens:
            num_ctx = min(num_ctx, self.detected_context_tokens)
        try:
            data = post_json(f"{self.endpoint}/api/chat", {
                "model": self.model,
                "messages": [{"role": "user", "content": prompt}],
                "stream": False,
                "options": {"num_ctx": num_ctx},
            }, timeout_s=timeout_ms / 1000)
        except HttpError as e:
            raise AdapterError(f"Ollama error ({e.status}): {e.body}",
                               kind=classify_error(e))
        except Exception as e:
            raise AdapterError(str(e), kind=classify_error(e), cause=e)
        content = (data.get("message") or {}).get("content")
        if not content:
            raise AdapterError("Ollama returned empty response", kind="api")
        return content

    def _execute_openai_compat(self, prompt: str, timeout_ms: int) -> str:
        try:
            # No max_tokens: prompt + max_tokens > ctx gets rejected outright
            # by LM Studio; let the server size the response itself.
            data = post_json(f"{self.endpoint}/v1/chat/completions", {
                "model": self.model,
                "messages": [{"role": "user", "content": prompt}],
            }, timeout_s=timeout_ms / 1000)
        except HttpError as e:
            if self.source == "LM Studio" and _is_context_window_error(e.body):
                est = math.ceil(len(prompt) / CHARS_PER_TOKEN_ESTIMATE)
                raise AdapterError(
                    f"LM Studio context window too small (prompt needs "
                    f"~{est} tokens).\n"
                    "  Fix: In LM Studio → Developer → Model Settings → "
                    "increase Context Length.\n"
                    "  Also uncheck the Response Limit, or set it higher.\n"
                    "  Note: higher context = more VRAM. Find the sweet spot "
                    "for your GPU.", kind="api")
            raise AdapterError(f"Local LLM error ({e.status}): {e.body}",
                               kind=classify_error(e))
        except Exception as e:
            raise AdapterError(str(e), kind=classify_error(e), cause=e)
        try:
            content = data["choices"][0]["message"]["content"]
        except (KeyError, IndexError, TypeError):
            content = None
        if not content:
            raise AdapterError("Local LLM returned empty response", kind="api")
        return content

    def _detect_ollama_context(self) -> Optional[int]:
        try:
            data = post_json(f"{self.endpoint}/api/show",
                             {"name": self.model}, timeout_s=5)
        except Exception:
            return None
        model_info = data.get("model_info") or {}
        for key, value in model_info.items():
            if key.endswith(".context_length") and isinstance(value, int):
                return value
        return None
