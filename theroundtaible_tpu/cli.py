"""CLI entry point — the `roundtable` command.

Equivalent of reference src/index.ts:29-187: one subcommand per command
module, a single central error handler that is the ONLY place the process
exits with a nonzero code, and a fire-and-forget update check.
"""

from __future__ import annotations

import os
import sys

from . import __version__
from .core.errors import ExitCode, RoundtableError, format_error
from .utils.update_check import check_for_update


def _print_update_notice(current: str, latest: str) -> None:
    print(f"\n  Update available: {current} → {latest} "
          f"(pip install -U theroundtaible-tpu)\n", file=sys.stderr)


def handle_cli_error(err: BaseException) -> int:
    """Central error handler — the only exit-code authority
    (reference src/index.ts:29-46)."""
    if isinstance(err, KeyboardInterrupt):
        print("\nInterrupted.", file=sys.stderr)
        return int(ExitCode.GENERAL)
    print(format_error(err), file=sys.stderr)
    if os.environ.get("DEBUG"):
        import traceback
        traceback.print_exception(err)
    if isinstance(err, RoundtableError):
        return int(err.exit_code)
    return int(ExitCode.UNEXPECTED)


def build_parser():
    import argparse

    p = argparse.ArgumentParser(
        prog="roundtable",
        description="TheRoundtAIble-TPU — multi-LLM consensus discussions, "
                    "served from TPU.")
    p.add_argument("--version", action="version", version=__version__)
    sub = p.add_subparsers(dest="command")

    sub.add_parser("init", help="Interactive setup wizard")

    d = sub.add_parser("discuss", help="Start a roundtable discussion")
    dgroup = d.add_mutually_exclusive_group(required=True)
    dgroup.add_argument("topic", nargs="?", help="The question to discuss")
    dgroup.add_argument("--continue", dest="continue_session",
                        action="store_true",
                        help="Resume the latest unfinished session "
                             "(crash recovery)")
    d.add_argument("--read-code", action="store_true", default=None,
                   help="Read source code into context without asking")
    d.add_argument("--no-read-code", dest="read_code", action="store_false",
                   help="Skip reading source code without asking")

    v = sub.add_parser(
        "serve",
        help="Serve K concurrent discussions on one shared engine fleet")
    v.add_argument("topics", nargs="*",
                   help="Topics (one concurrent discussion each)")
    v.add_argument("--sessions", type=int, default=None,
                   help="Fan ONE topic into K concurrent discussions")
    v.add_argument("--journal", default=None, metavar="DIR",
                   help="Journal every committed turn to DIR (fsynced "
                        "JSONL per session) so a crashed process can "
                        "resume with --resume DIR")
    v.add_argument("--resume", dest="resume_dir", default=None,
                   metavar="DIR",
                   help="Replay the session journal at DIR through the "
                        "normal submit path (re-prefill; the prefix "
                        "cache makes it cheap), restoring every "
                        "session's KV at its last committed turn — "
                        "then serve the given topics (if any)")
    v.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="Serve across N data-parallel engine replicas "
                        "behind the session router (default 1 — "
                        "byte-identical to single-engine serving)")
    v.add_argument("--read-code", action="store_true", default=None,
                   help="Read source code into context without asking")
    v.add_argument("--no-read-code", dest="read_code",
                   action="store_false",
                   help="Skip reading source code without asking")

    g = sub.add_parser(
        "gateway",
        help="Serve the streaming HTTP/SSE front door: OpenAI-"
             "compatible /v1/chat/completions + native /v1/discussions "
             "over the shared engine, with SLO-driven admission, load "
             "shedding and crash-consistent mid-stream resume")
    g.add_argument("--host", default=None,
                   help="Bind address (default ROUNDTABLE_GATEWAY_HOST "
                        "or 127.0.0.1)")
    g.add_argument("--port", type=int, default=None,
                   help="Bind port (default ROUNDTABLE_GATEWAY_PORT "
                        "or 8080; 0 = ephemeral)")
    g.add_argument("--journal", default=None, metavar="DIR",
                   help="Journal every committed turn + stream intent "
                        "to DIR so a kill -9'd gateway resumes with "
                        "--resume DIR")
    g.add_argument("--resume", dest="resume_dir", default=None,
                   metavar="DIR",
                   help="Replay DIR's session journal on boot (library "
                        "seam shared with `serve --resume`), restoring "
                        "every session's KV at its last committed turn "
                        "so clients reconnect via Last-Event-ID with "
                        "no token loss or duplication")
    g.add_argument("--replicas", type=int, default=1, metavar="N",
                   help="Serve across N data-parallel engine replicas: "
                        "the session router places cold sessions by "
                        "live load score, keeps returning sessions on "
                        "the replica holding their KV, migrates "
                        "sessions across replicas over the host-RAM "
                        "tier, and rolls replicas one at a time with "
                        "zero lost sessions (default 1)")

    s = sub.add_parser("summon", help="Review the current git diff")
    s.add_argument("--read-code", action="store_true", default=None,
                   help="Read source code into context without asking")
    s.add_argument("--no-read-code", dest="read_code", action="store_false",
                   help="Skip reading source code without asking")

    lg = sub.add_parser(
        "loadgen",
        help="Offered-load capacity sweep: open-loop arrivals ramped "
             "to the shed point, knee fit, and derived admission "
             "thresholds (writes CAPACITY_r19.json in full mode)")
    lg.add_argument("--smoke", action="store_true",
                    help="Tiny ~30s sweep, no artifact")
    lg.add_argument("--seed", type=int, default=7)
    lg.add_argument("--arrival", default="poisson",
                    choices=["poisson", "diurnal", "mmpp"],
                    help="Arrival process for the sweep")
    lg.add_argument("--duration", type=float, default=None,
                    help="Seconds per sweep point")
    lg.add_argument("--rates", default=None, metavar="R,R,...",
                    help="Comma-separated offered rates "
                         "(default: geometric ramp)")
    lg.add_argument("--out", default=None,
                    help="Capacity-record path "
                         "(default ./CAPACITY_r19.json)")

    st = sub.add_parser("status", help="Show the latest session")
    st.add_argument("--telemetry", action="store_true",
                    help="Render the session's telemetry view: registry "
                         "snapshot, span summary, flight-recorder dumps")
    st.add_argument("--perf", action="store_true",
                    help="Render live performance attribution: roofline "
                         "table, compile observatory, memory ledger, "
                         "span-tree overhead breakdown")
    st.add_argument("--kv", action="store_true",
                    help="Render the KV-tier view: memory ledger with "
                         "the cross-session sharing split, prefix-cache "
                         "hit/miss series, host-RAM offload state")
    st.add_argument("--health", action="store_true",
                    help="Render fleet health: breakers, admission "
                         "gates, scheduler queues, and the supervisor's "
                         "engine-restart history")
    st.add_argument("--gateway", action="store_true",
                    help="Render the serving gateway's admission/shed "
                         "ledger: admitted/shed/expired counters by "
                         "reason, inflight streams, drop-to-summary "
                         "and resume counts")
    st.add_argument("--capacity", action="store_true",
                    help="Render the measured capacity frontier "
                         "(latest CAPACITY_r19.json or "
                         "ROUNDTABLE_GATEWAY_CAPACITY_FILE) against "
                         "the live gateway gauges: predicted vs "
                         "measured, knee, derived thresholds")
    st.add_argument("--fleet", action="store_true",
                    help="Render the multi-replica serving view: "
                         "per-replica liveness, session assignment, "
                         "queue/row gauges, and the router's "
                         "migration / failover / roll history")
    st.add_argument("--slo", action="store_true",
                    help="Render the SLO burn-rate view: the p95 TTFT "
                         "SLO from the capacity record, live fast/slow "
                         "burn-rate gauges against the error budget, "
                         "breach + flight-dump counters, and trace "
                         "retention")

    tr = sub.add_parser(
        "trace",
        help="Inspect retained request traces: per-request critical-"
             "path waterfalls (admission → queue → placement → prefill "
             "→ first flush → decode) stitched across reconnects, "
             "gateway restarts and replica failovers")
    tr.add_argument("action", choices=["list", "show", "stages"],
                    help="list = every retained trace; show <id> = one "
                         "stitched trace's per-leg waterfall; stages = "
                         "the aggregate critical-path table")
    tr.add_argument("trace_id", nargs="?", default=None,
                    help="Trace id (or unique prefix) for `show`")
    tr.add_argument("--dir", dest="trace_dir", default=None,
                    help="Trace directory (default ROUNDTABLE_TRACE_DIR "
                         "or <telemetry dumps>/traces)")

    sub.add_parser("list", help="List all sessions")
    sub.add_parser("chronicle", help="Show the decision chronicle")
    sub.add_parser("decrees", help="Show the King's Decree Log")

    m = sub.add_parser("manifest", help="Implementation manifest")
    msub = m.add_subparsers(dest="manifest_command")
    msub.add_parser("list", help="List manifest features")
    ma = msub.add_parser("add", help="Add a feature entry")
    ma.add_argument("--id", dest="feature_id")
    ma.add_argument("--files", default="")
    ma.add_argument("--status", default="implemented")
    md = msub.add_parser("deprecate", help="Deprecate a feature")
    md.add_argument("feature_id")
    md.add_argument("--replaced-by", default=None)
    msub.add_parser("check", help="Warn about stale manifest entries")

    a = sub.add_parser("apply", help="Let the Lead Knight execute the decision")
    a.add_argument("--noparley", action="store_true",
                   help="Skip per-file approval")
    a.add_argument("--dry-run", action="store_true",
                   help="Show planned edits without writing")
    a.add_argument("--override-scope", action="store_true",
                   help="Allow edits outside the consensus scope (audited)")
    a.add_argument("--session", default=None,
                   help="Apply a specific session instead of the latest")

    c = sub.add_parser("code-red", help="Diagnostic mode for a bug/incident")
    c.add_argument("description", help="What is broken")

    sub.add_parser("warmup",
                   help="Pre-compile the TPU serving programs so the "
                        "first discuss starts hot")

    li = sub.add_parser(
        "lint",
        help="Static serving-invariant analyzer: AST rules + "
             "device-free jaxpr audit (CI / tunnel preflight)")
    li.add_argument("--rules", default=None, metavar="ID,ID",
                    help="Comma-separated rule ids to run "
                         "(default: all)")
    li.add_argument("--jaxpr", action="store_true",
                    help="Also audit every registered serving program "
                         "(prefill/decode/ragged/spec/LoRA-setter) "
                         "device-free on CPU: donation safety, "
                         "callback-free hot loops, warmed-variant "
                         "count across the shape grid")
    li.add_argument("--json", dest="as_json", action="store_true",
                    help="Machine-readable findings (the preflight "
                         "step consumes this)")
    li.add_argument("--root", default=None,
                    help="Tree to lint (default: this checkout)")

    return p


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    if args.command is None:
        build_parser().print_help()
        return 0

    check_for_update(_print_update_notice)
    try:
        return dispatch(args) or 0
    except BaseException as err:  # noqa: BLE001 — single central handler
        return handle_cli_error(err)


def dispatch(args) -> int:
    """Route to command modules (imported lazily to keep startup instant)."""
    if args.command == "init":
        from .commands.init import init_command
        return init_command(__version__)
    if args.command == "discuss":
        if getattr(args, "continue_session", False):
            from .commands.discuss import continue_command
            return continue_command(read_code=args.read_code)
        from .commands.discuss import discuss_command
        return discuss_command(args.topic, read_code=args.read_code)
    if args.command == "serve":
        from .commands.serve import serve_command
        return serve_command(args.topics, sessions=args.sessions,
                             read_code=args.read_code,
                             journal_dir=args.journal,
                             resume_dir=args.resume_dir,
                             replicas=args.replicas)
    if args.command == "summon":
        from .commands.summon import summon_command
        return summon_command(read_code=args.read_code)
    if args.command == "gateway":
        from .commands.gateway_cmd import gateway_command
        return gateway_command(host=args.host, port=args.port,
                               journal_dir=args.journal,
                               resume_dir=args.resume_dir,
                               replicas=args.replicas)
    if args.command == "status":
        from .commands.status import status_command
        return status_command(
            telemetry_view=getattr(args, "telemetry", False),
            perf_view=getattr(args, "perf", False),
            kv_view=getattr(args, "kv", False),
            health_view=getattr(args, "health", False),
            gateway_view=getattr(args, "gateway", False),
            fleet_view=getattr(args, "fleet", False),
            capacity_view=getattr(args, "capacity", False),
            slo_view=getattr(args, "slo", False))
    if args.command == "trace":
        from .commands.trace_cmd import trace_command
        return trace_command(args.action, trace_id=args.trace_id,
                             trace_dir=args.trace_dir)
    if args.command == "loadgen":
        from .commands.loadgen_cmd import loadgen_command
        return loadgen_command(smoke=args.smoke, seed=args.seed,
                               arrival=args.arrival,
                               duration_s=args.duration,
                               rates=args.rates, out=args.out)
    if args.command == "list":
        from .commands.list_cmd import list_command
        return list_command()
    if args.command == "chronicle":
        from .commands.chronicle_cmd import chronicle_command
        return chronicle_command()
    if args.command == "decrees":
        from .commands.decrees import decrees_command
        return decrees_command()
    if args.command == "manifest":
        from .commands import manifest_cmd
        return manifest_cmd.run(args)
    if args.command == "apply":
        from .commands.apply import apply_command
        return apply_command(noparley=args.noparley, dry_run=args.dry_run,
                             override_scope=args.override_scope,
                             session_name=args.session)
    if args.command == "code-red":
        from .commands.code_red import code_red_command
        return code_red_command(args.description)
    if args.command == "warmup":
        from .commands.warmup_cmd import warmup_command
        return warmup_command()
    if args.command == "lint":
        from .commands.lint import lint_command
        rules = ([r.strip() for r in args.rules.split(",") if r.strip()]
                 if args.rules else None)
        return lint_command(rules=rules, jaxpr=args.jaxpr,
                            as_json=args.as_json, root=args.root)
    raise RoundtableError(f"Unknown command: {args.command}")


if __name__ == "__main__":
    sys.exit(main())
