"""Capacity frontier record: schema, knee fit, derived thresholds.

The frontier record is the durable artifact of a sweep — the thing
BENCH_NOTES calls re-runnable evidence and the thing
`gateway/admission.py` loads through ROUNDTABLE_GATEWAY_CAPACITY_FILE
(`Thresholds.from_capacity_record`). Hand-rolled validation (no
jsonschema dependency): `validate_record` returns a list of problems,
empty means valid.

Threshold derivation rules (documented in ARCHITECTURE "Load &
capacity"; every rule anchors to the measured knee):

- `p95_slo_s`      = knee p95 TTFT x `slo_margin` — the soft-shed SLO
  sits above what the server PROVABLY does at its best operating
  point, so it trips on regression, not on normal service.
- `max_inflight`   = peak concurrent sessions at the knee x
  `inflight_margin` — beyond measured peak concurrency the extra
  admissions only queue.
- `max_queue_depth`= Little's-law backlog at the knee
  (knee rate x knee p95 TTFT) x `queue_margin`, floor 2 — a queue
  deeper than the knee can drain within one SLO window is pure added
  latency.
"""

from __future__ import annotations

import json
import math
from typing import Any, Optional

CAPACITY_SCHEMA_ID = "roundtable.capacity_frontier.v1"

# Per-point keys the schema requires; the ttft percentiles may be null
# (a fully-shed point has no admitted sessions to time).
_POINT_NUM_KEYS = ("offered_rps", "duration_s", "arrivals", "admitted",
                   "shed", "shed_rate", "accepted_tok_s",
                   "peak_concurrent_sessions", "sessions_per_chip")
_POINT_NULLABLE_KEYS = ("ttft_p50_s", "ttft_p95_s", "ttft_p99_s")
_THRESHOLD_KEYS = ("max_inflight", "max_queue_depth", "p95_slo_s")


# --- knee fit --------------------------------------------------------

def fit_knee(points: list[dict], *, max_shed_rate: float = 0.05,
             ttft_slo_factor: float = 3.0) -> dict[str, Any]:
    """The knee: the highest offered rate the server absorbed — shed
    rate within `max_shed_rate` and p95 TTFT within
    `ttft_slo_factor` x the lightest point's p95. Past it, added
    offered load only buys shed + latency.

    Monotone in offered load by construction: each point's goodness
    depends only on itself and the FIRST point's baseline, so
    extending a sweep with higher-rate points never moves the knee
    DOWN — the property the tier-1 sweep test pins.
    """
    if not points:
        raise ValueError("fit_knee needs at least one point")
    ordered = sorted(range(len(points)),
                     key=lambda i: points[i]["offered_rps"])
    base_p95 = points[ordered[0]].get("ttft_p95_s")
    knee_i = ordered[0]
    reason = "lightest point (nothing else within limits)"
    for i in ordered:
        pt = points[i]
        if pt["shed_rate"] > max_shed_rate:
            continue
        p95 = pt.get("ttft_p95_s")
        if (base_p95 is not None and p95 is not None
                and p95 > ttft_slo_factor * max(base_p95, 1e-6)):
            continue
        if pt["offered_rps"] >= points[knee_i]["offered_rps"]:
            knee_i = i
            reason = (f"highest rate with shed<={max_shed_rate:g} "
                      f"and p95<={ttft_slo_factor:g}x baseline")
    knee = points[knee_i]
    return {
        "index": knee_i,
        "rate": knee["offered_rps"],
        "accepted_tok_s": knee["accepted_tok_s"],
        "ttft_p95_s": knee.get("ttft_p95_s"),
        "peak_concurrent_sessions": knee["peak_concurrent_sessions"],
        "max_shed_rate": max_shed_rate,
        "ttft_slo_factor": ttft_slo_factor,
        "reason": reason,
        # Trace ids of the knee point's slowest sessions (ISSUE 20):
        # the p95 behind the derived SLO is inspectable via
        # `roundtable trace show <id>` instead of being a bare number.
        "exemplar_traces": list(knee.get("exemplar_traces") or ()),
    }


def derive_thresholds(points: list[dict], knee: dict, *,
                      slo_margin: float = 1.5,
                      inflight_margin: float = 1.25,
                      queue_margin: float = 2.0) -> dict[str, Any]:
    """Admission thresholds from the measured knee (rules in the
    module docstring / ARCHITECTURE)."""
    p95 = knee.get("ttft_p95_s")
    peak = max(int(knee.get("peak_concurrent_sessions", 1)), 1)
    backlog = (knee["rate"] * p95) if p95 else 0.0
    return {
        "max_inflight": max(math.ceil(peak * inflight_margin), 1),
        "max_queue_depth": max(math.ceil(backlog * queue_margin), 2),
        "p95_slo_s": round(p95 * slo_margin, 4) if p95 else 0.0,
        "rules": {
            "slo_margin": slo_margin,
            "inflight_margin": inflight_margin,
            "queue_margin": queue_margin,
        },
    }


# --- record build / validate -----------------------------------------

def build_record(*, points: list[dict], arrival: dict, workload: dict,
                 seed: int, predicted: Optional[dict] = None,
                 gap: Optional[dict] = None,
                 chaos: Optional[dict] = None,
                 chip: Optional[dict] = None,
                 n_devices: int = 1,
                 knee_params: Optional[dict] = None) -> dict[str, Any]:
    """Assemble the full frontier record (fits the knee and derives
    thresholds on the way)."""
    knee = fit_knee(points, **(knee_params or {}))
    record = {
        "schema": CAPACITY_SCHEMA_ID,
        "seed": int(seed),
        "n_devices": int(n_devices),
        "arrival": arrival,
        "workload": workload,
        "points": points,
        "knee": knee,
        "derived_thresholds": derive_thresholds(points, knee),
    }
    if predicted is not None:
        record["predicted"] = predicted
    if gap is not None:
        record["gap"] = gap
    if chaos is not None:
        record["chaos"] = chaos
    if chip is not None:
        record["chip"] = chip
    errors = validate_record(record)
    if errors:  # a bug in this module, not in the caller's data
        raise AssertionError(
            "built an invalid capacity record: " + "; ".join(errors))
    return record


def validate_record(rec: Any) -> list[str]:
    """Problems with a frontier record ([] = valid). Never raises —
    admission's loud-degrade path depends on getting WORDS back, not
    a traceback."""
    errs: list[str] = []
    if not isinstance(rec, dict):
        return [f"record is {type(rec).__name__}, not a dict"]
    if rec.get("schema") != CAPACITY_SCHEMA_ID:
        errs.append(f"schema is {rec.get('schema')!r}, "
                    f"expected {CAPACITY_SCHEMA_ID!r}")
    points = rec.get("points")
    if not isinstance(points, list) or not points:
        errs.append("points must be a non-empty list")
        points = []
    prev_rate = 0.0
    for i, pt in enumerate(points):
        if not isinstance(pt, dict):
            errs.append(f"points[{i}] is not a dict")
            continue
        for k in _POINT_NUM_KEYS:
            v = pt.get(k)
            if not isinstance(v, (int, float)) \
                    or isinstance(v, bool):
                errs.append(f"points[{i}].{k} missing or non-numeric")
        for k in _POINT_NULLABLE_KEYS:
            v = pt.get(k, "absent")
            if v == "absent" or (v is not None and not
                                 isinstance(v, (int, float))):
                errs.append(f"points[{i}].{k} missing or non-numeric")
        rate = pt.get("offered_rps")
        if isinstance(rate, (int, float)):
            if rate <= 0:
                errs.append(f"points[{i}].offered_rps must be > 0")
            if rate < prev_rate:
                errs.append("points must be sorted by offered_rps "
                            f"(points[{i}] goes backwards)")
            prev_rate = rate
    knee = rec.get("knee")
    if not isinstance(knee, dict) or "rate" not in knee:
        errs.append("knee must be a dict with a fitted rate")
    th = rec.get("derived_thresholds")
    if not isinstance(th, dict):
        errs.append("derived_thresholds must be a dict")
    else:
        for k in _THRESHOLD_KEYS:
            v = th.get(k)
            if not isinstance(v, (int, float)) or isinstance(v, bool) \
                    or v < 0:
                errs.append(
                    f"derived_thresholds.{k} missing or invalid")
    return errs


def extract_thresholds(rec: Any) -> dict[str, Any]:
    """The derived thresholds out of a frontier record — accepts the
    bare record or a bench wrapper carrying it under
    detail.frontier (the CAPACITY_r19.json shape). Raises ValueError
    with every problem spelled out when the record is malformed."""
    if isinstance(rec, dict) and "schema" not in rec:
        inner = rec.get("detail", {})
        if isinstance(inner, dict) and \
                isinstance(inner.get("frontier"), dict):
            rec = inner["frontier"]
    errors = validate_record(rec)
    if errors:
        raise ValueError("malformed capacity record: "
                         + "; ".join(errors))
    return dict(rec["derived_thresholds"])


def load_record(path: str) -> dict[str, Any]:
    """Read + validate a frontier record from disk (bare or bench-
    wrapped). Raises ValueError (unreadable / bad JSON / malformed) —
    callers choose whether that is fatal."""
    try:
        with open(path, encoding="utf-8") as f:
            rec = json.load(f)
    except OSError as e:
        raise ValueError(f"cannot read capacity record {path!r}: {e}")
    except json.JSONDecodeError as e:
        raise ValueError(f"capacity record {path!r} is not JSON: {e}")
    extract_thresholds(rec)  # full validation
    if isinstance(rec, dict) and "schema" not in rec:
        inner = rec.get("detail", {})
        if isinstance(inner, dict) and \
                isinstance(inner.get("frontier"), dict):
            return inner["frontier"]
    return rec
