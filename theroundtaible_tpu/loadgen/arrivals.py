"""Seeded arrival processes: WHEN sessions show up.

Every process emits a deterministic schedule of relative offsets
(seconds from sweep start, sorted ascending) from its own
`random.Random` seeded by a string key — re-running the same seed and
parameters reproduces the identical schedule byte-for-byte, which is
what makes a capacity record re-runnable evidence rather than an
anecdote.

Open loop vs closed loop: an open-loop process decides arrival times
WITHOUT looking at the server — when the server falls behind, traffic
piles up and the shed machinery is exercised honestly. The closed-loop
arm (K clients, next request only after the last finished) is kept
strictly as a comparison arm: it self-throttles at exactly the
saturation point and therefore can never find it.
"""

from __future__ import annotations

import math
import random
from typing import Optional

# A runaway rate x duration must not OOM the harness building a list.
_MAX_ARRIVALS = 200_000


class ArrivalProcess:
    """One arrival process: `schedule()` maps (rate, duration) to the
    session start offsets."""

    kind = "base"
    open_loop = True

    def schedule(self, *, rate_rps: float,
                 duration_s: float) -> list[float]:
        raise NotImplementedError

    def describe(self) -> dict:
        return {"kind": self.kind, "open_loop": self.open_loop}


def _check(rate_rps: float, duration_s: float) -> None:
    if rate_rps <= 0:
        raise ValueError(f"rate_rps must be > 0, got {rate_rps}")
    if duration_s <= 0:
        raise ValueError(f"duration_s must be > 0, got {duration_s}")
    if rate_rps * duration_s > _MAX_ARRIVALS:
        raise ValueError(
            f"schedule of ~{rate_rps * duration_s:.0f} arrivals exceeds "
            f"the {_MAX_ARRIVALS} harness bound — shorten the point or "
            "lower the rate")


class PoissonArrivals(ArrivalProcess):
    """Memoryless arrivals at constant rate λ — the canonical
    open-loop baseline (exponential inter-arrival gaps)."""

    kind = "poisson"

    def __init__(self, seed: int = 0):
        self.seed = int(seed)

    def schedule(self, *, rate_rps: float,
                 duration_s: float) -> list[float]:
        _check(rate_rps, duration_s)
        rng = random.Random(f"arrivals:poisson:{self.seed}")
        out: list[float] = []
        t = rng.expovariate(rate_rps)
        while t < duration_s and len(out) < _MAX_ARRIVALS:
            out.append(t)
            t += rng.expovariate(rate_rps)
        return out

    def describe(self) -> dict:
        return {**super().describe(), "seed": self.seed}


class DiurnalArrivals(ArrivalProcess):
    """Poisson thinned against a sinusoidal rate profile
    λ(t) = rate x (1 + depth x sin(2πt/period)) — the compressed
    day/night cycle. Mean rate stays `rate_rps`; the peak runs
    (1 + depth) x above it."""

    kind = "diurnal"

    def __init__(self, seed: int = 0, *, period_s: float = 60.0,
                 depth: float = 0.5):
        if not 0.0 <= depth < 1.0:
            raise ValueError(f"depth must be in [0, 1), got {depth}")
        self.seed = int(seed)
        self.period_s = float(period_s)
        self.depth = float(depth)

    def schedule(self, *, rate_rps: float,
                 duration_s: float) -> list[float]:
        _check(rate_rps, duration_s)
        rng = random.Random(f"arrivals:diurnal:{self.seed}")
        peak = rate_rps * (1.0 + self.depth)
        out: list[float] = []
        t = rng.expovariate(peak)
        while t < duration_s and len(out) < _MAX_ARRIVALS:
            lam = rate_rps * (1.0 + self.depth * math.sin(
                2.0 * math.pi * t / self.period_s))
            if rng.random() < lam / peak:
                out.append(t)
            t += rng.expovariate(peak)
        return out

    def describe(self) -> dict:
        return {**super().describe(), "seed": self.seed,
                "period_s": self.period_s, "depth": self.depth}


class MMPPArrivals(ArrivalProcess):
    """Two-state Markov-modulated Poisson (bursty): a quiet state and a
    burst state at `burst_mult` x the quiet rate, with exponential
    dwell times. Rates are normalized so the MEAN offered rate is still
    `rate_rps` — sweeps stay comparable across processes."""

    kind = "mmpp"

    def __init__(self, seed: int = 0, *, burst_mult: float = 4.0,
                 dwell_s: float = 5.0):
        if burst_mult < 1.0:
            raise ValueError(
                f"burst_mult must be >= 1, got {burst_mult}")
        self.seed = int(seed)
        self.burst_mult = float(burst_mult)
        self.dwell_s = float(dwell_s)

    def schedule(self, *, rate_rps: float,
                 duration_s: float) -> list[float]:
        _check(rate_rps, duration_s)
        rng = random.Random(f"arrivals:mmpp:{self.seed}")
        # Equal expected dwell in each state: mean = (quiet+burst)/2.
        quiet = 2.0 * rate_rps / (1.0 + self.burst_mult)
        rates = (quiet, quiet * self.burst_mult)
        out: list[float] = []
        t, state = 0.0, 0
        flip = rng.expovariate(1.0 / self.dwell_s)
        while t < duration_s and len(out) < _MAX_ARRIVALS:
            gap = rng.expovariate(rates[state])
            if t + gap >= flip:
                t = flip
                state = 1 - state
                flip = t + rng.expovariate(1.0 / self.dwell_s)
                continue
            t += gap
            if t < duration_s:
                out.append(t)
        return out

    def describe(self) -> dict:
        return {**super().describe(), "seed": self.seed,
                "burst_mult": self.burst_mult, "dwell_s": self.dwell_s}


class ClosedLoopArrivals(ArrivalProcess):
    """The comparison arm: K concurrent clients, each submitting its
    next session only after the previous one finished. The schedule is
    just the initial batch — drivers keep K in flight from there.
    Deliberately NOT acceptable capacity evidence (see BENCH_NOTES)."""

    kind = "closed"
    open_loop = False

    def __init__(self, concurrency: int = 2):
        if concurrency < 1:
            raise ValueError(
                f"concurrency must be >= 1, got {concurrency}")
        self.concurrency = int(concurrency)

    def schedule(self, *, rate_rps: float = 1.0,
                 duration_s: float = 1.0) -> list[float]:
        return [0.0] * self.concurrency

    def describe(self) -> dict:
        return {**super().describe(), "concurrency": self.concurrency}


_KINDS = {
    "poisson": PoissonArrivals,
    "diurnal": DiurnalArrivals,
    "mmpp": MMPPArrivals,
    "closed": ClosedLoopArrivals,
}


def make_arrivals(kind: str, seed: Optional[int] = None,
                  **params) -> ArrivalProcess:
    """Factory over the registered processes ("poisson", "diurnal",
    "mmpp", "closed")."""
    cls = _KINDS.get(kind)
    if cls is None:
        raise ValueError(
            f"unknown arrival process {kind!r} "
            f"(have: {', '.join(sorted(_KINDS))})")
    if kind == "closed":
        return cls(**params)
    return cls(seed or 0, **params)
