"""Sweep controller: ramp offered load to the refusal/shed point.

One `run_point` per arrival rate — schedule from the arrival process,
sessions from the workload mix, offered through a driver, folded into
one frontier point (driver.summarize). `run_sweep` walks a rate ramp
and stops once the server visibly sheds (`stop_shed_rate`), so every
sweep records both sides of the knee without burning wall clock past
the collapse.

Determinism: the per-point workload seed is derived from (sweep seed,
point index) — re-running the same sweep offers byte-identical traffic
at every point, while distinct points never reuse session names (a
reused name would look like the same session's next turn to the
journal/affinity machinery and corrupt the measurement).
"""

from __future__ import annotations

import time
from typing import Any, Optional

from .arrivals import ArrivalProcess
from .driver import summarize
from .workload import WorkloadMix

# Session-name / draw-stream separation between sweep points.
_POINT_SEED_STRIDE = 7919


def ramp_rates(start: float, factor: float, n: int) -> list[float]:
    """Geometric offered-load ramp: start, start*factor, ..."""
    if start <= 0 or factor <= 1.0 or n < 1:
        raise ValueError(
            f"need start > 0, factor > 1, n >= 1 "
            f"(got {start}, {factor}, {n})")
    out, r = [], start
    for _ in range(n):
        out.append(round(r, 6))
        r *= factor
    return out


def point_seed(seed: int, index: int) -> int:
    return int(seed) + _POINT_SEED_STRIDE * int(index)


def run_point(driver, process: ArrivalProcess, mix: WorkloadMix, *,
              rate_rps: float, duration_s: float, seed: int,
              point_index: int = 0, timeout_s: Optional[float] = None,
              n_devices: int = 1) -> dict[str, Any]:
    """One frontier point: offer `rate_rps` for `duration_s` and
    summarize what came back."""
    schedule = process.schedule(rate_rps=rate_rps,
                                duration_s=duration_s)
    pseed = point_seed(seed, point_index)
    specs = [mix.draw(pseed, i) for i in range(len(schedule))]
    t0 = time.monotonic()
    records = driver.run(specs, schedule,
                         open_loop=process.open_loop,
                         timeout_s=timeout_s or (duration_s * 4 + 30))
    wall = time.monotonic() - t0
    point = summarize(records, offered_rps=rate_rps,
                      duration_s=duration_s, n_devices=n_devices)
    point["wall_s"] = round(wall, 3)
    point["seed"] = pseed
    return point


def run_sweep(driver, process: ArrivalProcess, mix: WorkloadMix,
              rates: list[float], *, duration_s: float, seed: int,
              stop_shed_rate: float = 0.5, min_points: int = 4,
              settle_s: float = 0.5, timeout_s: Optional[float] = None,
              n_devices: int = 1,
              log=None) -> list[dict[str, Any]]:
    """Walk the ramp; stop early once the shed point is on record
    (shed_rate >= stop_shed_rate) AND at least `min_points` points
    were measured — the frontier needs both the flat region and the
    collapse."""
    points: list[dict[str, Any]] = []
    for i, rate in enumerate(rates):
        pt = run_point(driver, process, mix, rate_rps=rate,
                       duration_s=duration_s, seed=seed,
                       point_index=i, timeout_s=timeout_s,
                       n_devices=n_devices)
        points.append(pt)
        if log is not None:
            log(f"point {i}: {rate:g}/s -> admitted={pt['admitted']} "
                f"shed={pt['shed']} ({pt['shed_rate']:.0%}) "
                f"p95={pt['ttft_p95_s']} tok/s={pt['accepted_tok_s']}")
        if (pt["shed_rate"] >= stop_shed_rate
                and len(points) >= min_points):
            break
        if settle_s > 0:
            time.sleep(settle_s)
    return points
