"""Seeded workload mixes: WHAT each arriving session asks for.

`WorkloadMix.draw(seed, index)` is a pure function of (mix parameters,
seed, index) — per-index RNG streams mean draw i is identical whether
the harness generates 10 sessions or 10,000, and identical across
runs: the determinism contract capacity records depend on.

The knobs map one-to-one onto the capacity-limiting axes the serving
stack exposes:

- heavy-tailed prompt/turn lengths (bounded Pareto) — KV pressure and
  ragged prefill;
- persona churn cycling MORE adapters than the LoraStore holds —
  eviction/residency pressure (the `adapters_busy` shed signal);
- priority + deadline mixes — the admission ladder's scaled caps and
  deadline propagation;
- mid-stream abandonment — clients that disconnect after a few tokens
  (the RT-GAUGE-LEAK regression surface).
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import Any, Optional

_KNIGHTS = ("galahad", "percival", "lancelot")

_WORDS = ("the knights debate the session store design at the "
          "roundtable while the grail quest waits siege banners "
          "lances shields crowns castles heralds squires stewards "
          "falcons ramparts scrolls oaths feasts tourneys").split()


@dataclass(frozen=True)
class SessionSpec:
    """One drawn session: everything a driver needs to offer it."""

    index: int
    session: str
    turns: list  # [(knight, prompt), ...]
    max_new_tokens: int
    adapters_per_turn: Optional[list] = None
    priority: str = "normal"
    deadline_s: Optional[float] = None
    # Client disconnects after reading this many tokens (None = stays).
    abandon_after_tokens: Optional[int] = None
    temperature: float = 0.0

    def rows(self) -> int:
        return len(self.turns)


def _pareto_int(rng: random.Random, lo: int, hi: int,
                tail: float) -> int:
    """Bounded Pareto draw in [lo, hi] — small values common, the tail
    reaches hi (heavy-tailed lengths are the realistic shape; uniform
    draws understate both KV pressure and batching raggedness)."""
    u = rng.random()
    n = int(lo * (1.0 - u) ** (-1.0 / tail))
    return max(lo, min(hi, n))


@dataclass
class WorkloadMix:
    """Parameterized session mix. All draws route through the per-index
    seeded RNG in `draw` — the mix object itself holds no state."""

    knights: tuple = _KNIGHTS
    max_new_tokens: int = 8
    # Turn count: bounded Pareto in [1, max_turns].
    max_turns: int = 2
    turn_tail: float = 1.6
    # Prompt length in words: bounded Pareto in prompt_words.
    prompt_words: tuple = (4, 32)
    prompt_tail: float = 1.3
    # Persona churn: with probability persona_churn, a turn carries an
    # adapter cycled from persona_pool. A pool LARGER than the
    # LoraStore's max_adapters is what forces eviction under load.
    persona_pool: tuple = ()
    persona_churn: float = 0.0
    # Priority class weights.
    priority_mix: dict = field(default_factory=lambda: {
        "high": 0.1, "normal": 0.8, "low": 0.1})
    # Fraction of sessions carrying a client deadline, drawn uniformly
    # from deadline_range_s.
    deadline_frac: float = 0.0
    deadline_range_s: tuple = (10.0, 60.0)
    # Fraction of clients that abandon mid-stream, after reading
    # uniform(abandon_after) tokens.
    abandon_frac: float = 0.0
    abandon_after: tuple = (1, 4)

    def draw(self, seed: int, index: int) -> SessionSpec:
        rng = random.Random(f"workload:{seed}:{index}")
        n_turns = _pareto_int(rng, 1, self.max_turns, self.turn_tail)
        turns = []
        adapters: list = []
        for t in range(n_turns):
            knight = self.knights[(index + t) % len(self.knights)]
            n_words = _pareto_int(rng, self.prompt_words[0],
                                  self.prompt_words[1],
                                  self.prompt_tail)
            words = [_WORDS[rng.randrange(len(_WORDS))]
                     for _ in range(n_words)]
            turns.append((knight, " ".join(words)))
            if (self.persona_pool
                    and rng.random() < self.persona_churn):
                adapters.append(self.persona_pool[
                    (index + t) % len(self.persona_pool)])
            else:
                adapters.append(None)
        priority = self._draw_priority(rng)
        deadline = None
        if rng.random() < self.deadline_frac:
            deadline = rng.uniform(*self.deadline_range_s)
        abandon = None
        if rng.random() < self.abandon_frac:
            abandon = rng.randint(*self.abandon_after)
        return SessionSpec(
            index=index, session=f"lg{seed}-{index}", turns=turns,
            max_new_tokens=self.max_new_tokens,
            adapters_per_turn=(adapters if any(a is not None
                                               for a in adapters)
                               else None),
            priority=priority, deadline_s=deadline,
            abandon_after_tokens=abandon)

    def draw_many(self, seed: int, n: int) -> list[SessionSpec]:
        return [self.draw(seed, i) for i in range(n)]

    def _draw_priority(self, rng: random.Random) -> str:
        total = sum(self.priority_mix.values()) or 1.0
        u = rng.random() * total
        acc = 0.0
        for name, w in sorted(self.priority_mix.items()):
            acc += w
            if u < acc:
                return name
        return "normal"

    def describe(self) -> dict[str, Any]:
        return {
            "knights": list(self.knights),
            "max_new_tokens": self.max_new_tokens,
            "max_turns": self.max_turns,
            "prompt_words": list(self.prompt_words),
            "persona_pool": list(self.persona_pool),
            "persona_churn": self.persona_churn,
            "priority_mix": dict(self.priority_mix),
            "deadline_frac": self.deadline_frac,
            "abandon_frac": self.abandon_frac,
        }


def default_persona_pool(n: int = 5) -> tuple:
    """Adapter ids for churn mixes — sized past the default LoraStore
    capacity so residency pressure actually evicts."""
    return tuple(f"persona{i:02d}" for i in range(n))


def register_personas(engine, pool) -> int:
    """Register seed-initialized personas on the engine's LoraStore
    (no-op without one). Returns how many were registered."""
    store = getattr(engine, "lora", None)
    if store is None:
        return 0
    count = 0
    for i, adapter in enumerate(pool):
        if not store.resolvable(adapter):
            store.register(adapter, {"seed": 100 + i})
            count += 1
    return count
