"""Offered-load traffic harness + capacity model (ISSUE 19).

One load-model module that both the benches and the admission
controller consume:

- `arrivals`  — seeded OPEN-LOOP arrival processes (Poisson, diurnal,
  MMPP burst) plus a closed-loop comparison arm. Open loop is the
  evidence standard: arrivals keep coming whether or not the server
  keeps up, so the shed point is a property of the server, not of the
  client's politeness.
- `workload`  — seeded session mixes: heavy-tailed prompt/turn
  lengths, persona churn over more adapters than the LoraStore holds,
  priority/deadline mixes, mid-stream abandonment draws.
- `driver`    — two ways to offer the traffic: in-process over
  `SessionScheduler.submit_async` and over-the-wire against the
  gateway SSE endpoints (single replica or a router fleet), plus
  chaos arms over the PR-12 fault points.
- `sweep`     — ramp offered load to the refusal/shed point, one
  frontier point per arrival rate.
- `capacity`  — the frontier record schema, the knee fit, and the
  DERIVED admission thresholds that `gateway/admission.py` loads via
  ROUNDTABLE_GATEWAY_CAPACITY_FILE.
- `bench`     — the orchestration shared by `bench_load.py` and the
  `roundtable loadgen` command (emits CAPACITY_r19.json).
"""

from .arrivals import (ArrivalProcess, ClosedLoopArrivals,  # noqa: F401
                       DiurnalArrivals, MMPPArrivals, PoissonArrivals,
                       make_arrivals)
from .capacity import (CAPACITY_SCHEMA_ID, build_record,  # noqa: F401
                       derive_thresholds, fit_knee, load_record,
                       validate_record)
from .driver import (GatewayDriver, InProcessDriver,  # noqa: F401
                     open_loop_peak, reset_test_counters, summarize)
from .sweep import ramp_rates, run_point, run_sweep  # noqa: F401
from .workload import SessionSpec, WorkloadMix  # noqa: F401
