"""Capacity-bench orchestration shared by `bench_load.py` and the
`roundtable loadgen` command.

Runs the whole loop IN ONE PROCESS: tiny-gemma engine + scheduler +
gateway on an ephemeral port, the GatewayDriver offering open-loop
traffic over real sockets — so the sweep exercises the exact serving
path (admission ladder, SSE pumps, resume ladder) while the perfmodel
spans and registry stay readable for the measured-vs-predicted gap
attribution.

Phases:
1. open-loop sweep (default Poisson) rate-ramped to the shed point;
2. chaos arm: one `device_lost` under load — every session must
   complete through the client retry/resume ladder (zero lost);
3. knee fit + derived thresholds -> frontier record -> bench record.
"""

from __future__ import annotations

import os
import tempfile
import time
from typing import Any, Optional

from ..utils import telemetry
from .arrivals import make_arrivals
from .capacity import build_record
from .driver import GatewayDriver, arm_chaos
from .sweep import ramp_rates, run_point, run_sweep
from .workload import (WorkloadMix, default_persona_pool,
                       register_personas)

_RETRYABLE = ("device_lost", "engine_dead", "restarting", "data_loss")


def _build_stack(workdir: str, *, smoke: bool,
                 max_inflight: int, max_queue_depth: int):
    """Engine + scheduler + in-process gateway; returns
    (gateway, scheduler, engine, port)."""
    os.environ.setdefault("ROUNDTABLE_PERF_CHIP", "v5e")
    os.environ.setdefault("ROUNDTABLE_DISABLE_TPU_DETECT", "1")
    from ..engine.engine import InferenceEngine
    from ..engine.models.registry import get_model_config
    from ..engine.scheduler import SessionScheduler
    from ..engine.session_journal import SessionJournal
    from ..gateway import Gateway
    from ..gateway.admission import AdmissionController
    cfg = get_model_config("tiny-gemma", max_seq_len=512)
    kw: dict[str, Any] = {"num_slots": 8}
    if not smoke:
        # Persona churn needs a LoRA store SMALLER than the persona
        # pool, so residency pressure actually evicts under load.
        kw["lora"] = {"rank": 4, "max_adapters": 3}
    engine = InferenceEngine(cfg, **kw)
    sched = SessionScheduler(engine, journal=SessionJournal(workdir))
    admission = AdmissionController(
        sched, max_inflight=max_inflight,
        max_queue_depth=max_queue_depth)
    gw = Gateway(sched, port=0, intent_dir=workdir,
                 admission=admission)
    port = gw.start_in_thread()
    return gw, sched, engine, port


def _predicted_block(engine, n_devices: int) -> Optional[dict]:
    perf = getattr(engine, "perf", None)
    if perf is None or perf.decode_ceiling is None:
        return None
    return {
        "decode_ceiling_tps": round(perf.decode_ceiling, 1),
        "chip": perf.chip.name if perf.chip else None,
        "chip_source": perf.chip_source,
        "n_devices": n_devices,
        "source": "perfmodel roofline (HBM-bound decode ceiling)",
    }


def _gap_block(points: list[dict],
               predicted: Optional[dict]) -> Optional[dict]:
    """Measured-vs-predicted with the span-overhead attribution: on
    CPU the gap is enormous by construction (the roofline models TPU
    HBM), which is exactly why the record carries WHERE the wall time
    went instead of a bare ratio."""
    if predicted is None or not points:
        return None
    from ..utils import perfmodel
    measured = max(pt["accepted_tok_s"] for pt in points)
    ceiling = predicted["decode_ceiling_tps"]
    snap = perfmodel.attribution_snapshot()
    return {
        "measured_peak_tok_s": measured,
        "predicted_tok_s": ceiling,
        "gap_frac": round(1.0 - measured / max(ceiling, 1e-9), 6),
        "overheads": snap.get("overheads", {}),
        "compiles": snap.get("compiles"),
    }


def _run_chaos_arm(driver: GatewayDriver, mix: WorkloadMix, *,
                   seed: int, n_sessions: int,
                   log) -> dict[str, Any]:
    """One `device_lost` restart while open-loop traffic is in
    flight: every admitted session must still COMPLETE through the
    retry/resume ladder — a lost session fails the bench."""
    arm_chaos("device_lost", count=1)
    chaos_mix = WorkloadMix(
        knights=mix.knights, max_new_tokens=mix.max_new_tokens,
        max_turns=1)
    specs = [chaos_mix.draw(seed + 999_331, i)
             for i in range(n_sessions)]
    offsets = [0.4 * i for i in range(n_sessions)]
    records = driver.run(specs, offsets, open_loop=True,
                         timeout_s=120.0)
    completed = sum(1 for r in records if r["outcome"] == "completed")
    shed = sum(1 for r in records if r["outcome"] == "shed")
    lost = [r for r in records
            if r["outcome"] not in ("completed", "shed")]
    reconnects = sum(r.get("reconnects", 0) for r in records)
    log(f"chaos: {completed}/{len(records)} completed, {shed} shed, "
        f"{len(lost)} lost, {reconnects} reconnects")
    return {
        "point": "device_lost",
        "armed": 1,
        "sessions": len(records),
        "completed": completed,
        "shed": shed,
        "lost": len(lost),
        "lost_sessions": [r["session"] for r in lost],
        "reconnects": reconnects,
    }


def run_capacity(*, smoke: bool = False, seed: int = 7,
                 arrival: str = "poisson",
                 rates: Optional[list[float]] = None,
                 duration_s: Optional[float] = None,
                 chaos: Optional[bool] = None,
                 log=print) -> dict[str, Any]:
    """The whole capacity bench; returns the bench record (the
    frontier record rides under detail.frontier)."""
    t_start = time.monotonic()
    telemetry.arm()
    if chaos is None:
        chaos = not smoke
    if rates is None:
        # Smoke starts higher and ramps to 24/s: tiny-gemma rounds
        # drain fast on CPU, so the shed point needs real pressure.
        rates = (ramp_rates(3.0, 2.0, 4) if smoke
                 else ramp_rates(1.0, 2.0, 6))
    if duration_s is None:
        duration_s = 3.0 if smoke else 8.0
    import jax
    n_devices = len(jax.devices())
    caps = (4, 2) if smoke else (12, 6)
    with tempfile.TemporaryDirectory(prefix="loadgen_") as workdir:
        gw, sched, engine, port = _build_stack(
            workdir, smoke=smoke,
            max_inflight=caps[0], max_queue_depth=caps[1])
        try:
            pool = ()
            if getattr(engine, "lora", None) is not None:
                pool = default_persona_pool(5)
                register_personas(engine, pool)
            mix = WorkloadMix(
                max_new_tokens=4 if smoke else 6,
                max_turns=1 if smoke else 2,
                prompt_words=(3, 12) if smoke else (4, 24),
                persona_pool=pool,
                persona_churn=0.5 if pool else 0.0,
                deadline_frac=0.2, deadline_range_s=(20.0, 60.0),
                abandon_frac=0.0 if smoke else 0.1,
                abandon_after=(1, 3))
            process = make_arrivals(arrival, seed)
            driver = GatewayDriver(port)
            # Discarded warmup point: absorb first-touch compiles so
            # the first MEASURED point's TTFT baseline is steady-state
            # serving, not the compile wall (the knee fit anchors its
            # latency filter to point 0's p95).
            run_point(driver, process, mix, rate_rps=2.0,
                      duration_s=1.5, seed=seed + 555_001,
                      point_index=0, n_devices=n_devices)
            log(f"sweep: {arrival} arrivals, rates {rates}, "
                f"{duration_s:g}s/point, caps inflight={caps[0]} "
                f"queue={caps[1]}")
            points = run_sweep(
                driver, process, mix, rates, duration_s=duration_s,
                seed=seed, stop_shed_rate=0.3, min_points=4,
                n_devices=n_devices, log=log)
            predicted = _predicted_block(engine, n_devices)
            gap = _gap_block(points, predicted)
            chaos_block = None
            if chaos:
                chaos_block = _run_chaos_arm(
                    driver, mix, seed=seed,
                    n_sessions=4 if smoke else 6, log=log)
            chip_block = (
                {"name": predicted.get("chip"),
                 "source": predicted.get("chip_source"),
                 "n_devices": n_devices}
                if predicted else {"name": None, "source": "none",
                                   "n_devices": n_devices})
            frontier = build_record(
                points=points, arrival=process.describe(),
                workload=mix.describe(), seed=seed,
                predicted=predicted, gap=gap, chaos=chaos_block,
                chip=chip_block, n_devices=n_devices)
        finally:
            gw.stop()
            sched.close()
            from ..engine import faults
            faults.disarm()
    wall = time.monotonic() - t_start
    shed_seen = any(pt["shed"] > 0 for pt in points)
    zero_lost = chaos_block is None or chaos_block["lost"] == 0
    meets = (len(points) >= 4 and shed_seen and zero_lost)
    knee = frontier["knee"]
    log(f"knee: {knee['rate']:g} sessions/s "
        f"(p95 TTFT {knee['ttft_p95_s']}s) -> thresholds "
        f"{frontier['derived_thresholds']}")
    return {
        "metric": "capacity_frontier_knee",
        "value": knee["rate"],
        "unit": "sessions_per_s",
        "detail": {
            "frontier": frontier,
            "smoke": smoke,
            "acceptance": {
                "criterion": (
                    ">=4 open-loop points swept to the shed point, "
                    "frontier record valid, chaos arm (device_lost "
                    "under load) loses zero sessions"),
                "meets": meets,
                "points": len(points),
                "shed_point_reached": shed_seen,
                "chaos_zero_lost": zero_lost,
            },
            "cpu_wall_caveat": True,
            "platform": "cpu",
            "wall_s": round(wall, 3),
        },
    }
