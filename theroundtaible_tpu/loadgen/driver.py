"""Traffic drivers: offer a schedule of SessionSpecs to the serving
stack and record what happened to each one.

Two drivers, one record shape:

- `InProcessDriver` — straight into `SessionScheduler.submit_async`
  (optionally consulting an `AdmissionController` first, so sweeps
  exercise the same decision ladder the gateway runs). Abandonment
  uses the scheduler's own seam: `request.abandoned = True`.
- `GatewayDriver` — over the wire against the gateway's SSE
  endpoints (`POST /v1/discussions`, reconnects via
  `GET /v1/streams/<id>` + Last-Event-ID), single replica or a
  router fleet alike. Abandonment closes the socket mid-stream —
  the real client-disconnect path.

Chaos arms: `arm_chaos()` wires the PR-12 fault points
(`device_lost`, `engine_wedged`, ...) for in-process runs; over-the-
wire children inherit them via `chaos_env()` → ROUNDTABLE_FAULTS.

Per-session record keys (every driver emits the same dict):
  index, session, outcome ∈ {completed, shed, failed, abandoned},
  shed_reason, error_kind, ttft_s, tokens, reconnects, offset_s,
  wall_s.
"""

from __future__ import annotations

import json
import socket
import threading
import time
from typing import Any, Optional

from ..utils import telemetry, tracing
from .workload import SessionSpec

# --- test counters (conftest `loadgen` marker guard) -----------------
# A loadgen-marked test that never held >= 2 open-loop sessions in
# flight at once proved nothing about offered load — the guard fails
# LOUD unless this peak moved (the scheduler test-counter pattern).

_test_lock = threading.Lock()
_open_loop_now = 0
_open_loop_peak = 0


def reset_test_counters() -> None:
    global _open_loop_now, _open_loop_peak
    with _test_lock:
        _open_loop_now = 0
        _open_loop_peak = 0


def open_loop_peak() -> int:
    return _open_loop_peak


def _note_start(open_loop: bool) -> None:
    global _open_loop_now, _open_loop_peak
    if not open_loop:
        return
    with _test_lock:
        _open_loop_now += 1
        _open_loop_peak = max(_open_loop_peak, _open_loop_now)


def _note_done(open_loop: bool) -> None:
    global _open_loop_now
    if not open_loop:
        return
    with _test_lock:
        _open_loop_now = max(_open_loop_now - 1, 0)


# --- chaos arms ------------------------------------------------------

def arm_chaos(point: str = "device_lost", count: int = 1,
              delay_s: float = 0.0) -> None:
    """Arm a PR-12 fault point in THIS process (in-process driver /
    in-process gateway runs)."""
    from ..engine import faults
    if point not in faults.POINTS:
        raise ValueError(f"unknown fault point {point!r}")
    faults.arm(point, count=count, delay_s=delay_s)


def chaos_env(point: str = "device_lost", count: int = 1,
              delay_s: float = 0.0) -> dict[str, str]:
    """The env var that arms the same fault in a CHILD gateway process
    (faults parse ROUNDTABLE_FAULTS at import)."""
    spec = f"{point}:{count}"
    if delay_s:
        spec += f"@{delay_s}"
    return {"ROUNDTABLE_FAULTS": spec}


# --- aggregation -----------------------------------------------------

def _percentile(ordered: list[float], q: float) -> Optional[float]:
    if not ordered:
        return None
    return ordered[min(int(len(ordered) * q), len(ordered) - 1)]


def summarize(records: list[dict], *, offered_rps: float,
              duration_s: float, n_devices: int = 1) -> dict[str, Any]:
    """Fold per-session records into one capacity-frontier point."""
    done = [r for r in records if r is not None]
    admitted = [r for r in done if r["outcome"] != "shed"]
    completed = [r for r in done if r["outcome"] == "completed"]
    failed = [r for r in done if r["outcome"] == "failed"]
    abandoned = [r for r in done if r["outcome"] == "abandoned"]
    shed = [r for r in done if r["outcome"] == "shed"]
    ttfts = sorted(r["ttft_s"] for r in admitted
                   if r.get("ttft_s") is not None)
    tokens = sum(r.get("tokens", 0) for r in admitted)
    peak = _peak_concurrency(admitted)
    # Exemplar trace ids for the point's slowest sessions: the knee
    # finder copies these onto the knee, so a capacity regression
    # links straight to retained traces (ISSUE 20).
    slowest = sorted((r for r in admitted
                      if r.get("ttft_s") is not None
                      and r.get("trace")),
                     key=lambda r: r["ttft_s"], reverse=True)
    exemplars = [r["trace"] for r in slowest[:3]]
    return {
        "offered_rps": offered_rps,
        "duration_s": round(duration_s, 3),
        "arrivals": len(done),
        "admitted": len(admitted),
        "completed": len(completed),
        "failed": len(failed),
        "abandoned": len(abandoned),
        "shed": len(shed),
        "shed_rate": round(len(shed) / max(len(done), 1), 4),
        "shed_reasons": _reason_counts(shed),
        "ttft_p50_s": _percentile(ttfts, 0.50),
        "ttft_p95_s": _percentile(ttfts, 0.95),
        "ttft_p99_s": _percentile(ttfts, 0.99),
        "accepted_tokens": tokens,
        "accepted_tok_s": round(tokens / max(duration_s, 1e-9), 3),
        "peak_concurrent_sessions": peak,
        "sessions_per_chip": round(peak / max(n_devices, 1), 3),
        "exemplar_traces": exemplars,
    }


def _reason_counts(shed: list[dict]) -> dict[str, int]:
    out: dict[str, int] = {}
    for r in shed:
        reason = r.get("shed_reason") or "unknown"
        out[reason] = out.get(reason, 0) + 1
    return out


def _peak_concurrency(records: list[dict]) -> int:
    """Max sessions simultaneously in flight, from (start, end) offsets."""
    marks = []
    for r in records:
        start = r.get("offset_s", 0.0)
        marks.append((start, 1))
        marks.append((start + r.get("wall_s", 0.0), -1))
    peak = cur = 0
    for _, d in sorted(marks):
        cur += d
        peak = max(peak, cur)
    return peak


def _new_record(spec: SessionSpec, offset_s: float) -> dict:
    # `trace` (ISSUE 20): every per-session record names its trace id,
    # so a capacity regression joins directly to retained traces.
    return {"index": spec.index, "session": spec.session,
            "outcome": "failed", "shed_reason": None,
            "error_kind": None, "ttft_s": None, "tokens": 0,
            "reconnects": 0, "offset_s": round(offset_s, 4),
            "wall_s": 0.0, "trace": None}


# --- in-process driver -----------------------------------------------

class InProcessDriver:
    """Offers traffic straight into one SessionScheduler. With
    `admission=`, each arrival first runs the gateway's decision
    ladder (`AdmissionController.decide`) — shed sessions never reach
    the scheduler, exactly like the HTTP front door."""

    def __init__(self, scheduler, *, admission=None):
        self.sched = scheduler
        self.admission = admission
        self._inflight = 0
        self._inflight_lock = threading.Lock()

    def run(self, specs: list[SessionSpec], offsets: list[float], *,
            open_loop: bool = True,
            timeout_s: float = 120.0) -> list[dict]:
        if open_loop:
            return self._run_open(specs, offsets, timeout_s)
        return self._run_closed(specs, len(offsets), timeout_s)

    # -- open loop: dispatch on the schedule, never wait --

    def _run_open(self, specs, offsets, timeout_s) -> list[dict]:
        records: list[Optional[dict]] = [None] * len(specs)
        waiters: list[threading.Thread] = []
        t0 = time.monotonic()
        for i, (spec, off) in enumerate(zip(specs, offsets)):
            delay = t0 + off - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            records[i] = rec = _new_record(spec, off)
            w = self._offer(spec, rec, timeout_s)
            if w is not None:
                waiters.append(w)
        bound = time.monotonic() + timeout_s
        for w in waiters:
            w.join(max(bound - time.monotonic(), 0.1))
        return [r for r in records if r is not None]

    # -- closed loop (comparison arm): K clients, submit-wait-repeat --

    def _run_closed(self, specs, concurrency, timeout_s) -> list[dict]:
        records: list[Optional[dict]] = [None] * len(specs)
        cursor = {"i": 0}
        lock = threading.Lock()
        t0 = time.monotonic()

        def client() -> None:
            while True:
                with lock:
                    i = cursor["i"]
                    if i >= len(specs):
                        return
                    cursor["i"] = i + 1
                spec = specs[i]
                records[i] = rec = _new_record(
                    spec, time.monotonic() - t0)
                w = self._offer(spec, rec, timeout_s, open_loop=False)
                if w is not None:
                    w.join(timeout_s)

        threads = [threading.Thread(target=client, daemon=True)
                   for _ in range(concurrency)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout_s)
        return [r for r in records if r is not None]

    def _offer(self, spec: SessionSpec, rec: dict,
               timeout_s: float,
               open_loop: bool = True) -> Optional[threading.Thread]:
        start = time.monotonic()
        trace = tracing.RequestTrace(
            kind="request", session=spec.session, endpoint="loadgen",
            priority=spec.priority, rows=spec.rows())
        rec["trace"] = trace.trace_id
        if self.admission is not None:
            with self._inflight_lock:
                inflight = self._inflight
            with telemetry.attached(trace.context()):
                dec = self.admission.decide(
                    rows=spec.rows(), inflight=inflight,
                    deadline_s=spec.deadline_s, priority=spec.priority,
                    adapters=spec.adapters_per_turn)
            if not dec.admit:
                rec["outcome"] = "shed"
                rec["shed_reason"] = dec.reason
                rec["wall_s"] = round(time.monotonic() - start, 4)
                trace.flag("shed")
                trace.finish(f"shed:{dec.reason}",
                             tail_stage="admission")
                return None
        trace.stage("admission")
        state = {"tokens": 0, "req": None}

        def on_commit(event: dict) -> None:
            if event.get("type") == "tokens":
                if rec["ttft_s"] is None:
                    trace.stage("prefill")
                    trace.carve("prefill", "queue_wait",
                                event.get("queue_wait_s"))
                    trace.stage("first_flush")
                    rec["ttft_s"] = round(
                        time.monotonic() - start, 4)
                    if self.admission is not None:
                        # Burn monitor only — note_ttft() would also
                        # feed the p95 shed window and shift sweep
                        # knees, so the decision ladder stays blind
                        # to driver-side TTFTs.
                        self.admission.slo.note_ttft(
                            trace.ttft(), trace.trace_id)
                state["tokens"] += len(event.get("tokens", ()))
                rec["tokens"] = state["tokens"]
                req = state["req"]
                if (req is not None
                        and spec.abandon_after_tokens is not None
                        and state["tokens"]
                        >= spec.abandon_after_tokens):
                    # The client walked away: the scheduler's health
                    # check fails the round and releases its holds.
                    req.abandoned = True

        try:
            with telemetry.attached(trace.context()):
                req = self.sched.submit_async(
                    spec.session, list(spec.turns),
                    max_new_tokens=spec.max_new_tokens,
                    timeout_s=min(timeout_s,
                                  spec.deadline_s or timeout_s),
                    adapters_per_turn=spec.adapters_per_turn,
                    on_commit=on_commit)
        except Exception as e:  # noqa: BLE001 — refusals are sheds
            from ..core.errors import classify_error
            rec["outcome"] = "shed"
            rec["shed_reason"] = getattr(e, "reason", None) \
                or classify_error(e)
            rec["wall_s"] = round(time.monotonic() - start, 4)
            trace.flag("shed")
            trace.finish(f"shed:{rec['shed_reason']}",
                         tail_stage="admission")
            return None
        state["req"] = req
        trace.stage("placement")
        if self.admission is not None:
            self.admission.note_admitted()
        with self._inflight_lock:
            self._inflight += 1
        _note_start(open_loop)

        def waiter() -> None:
            try:
                req.event.wait(timeout_s)
                rec["wall_s"] = round(time.monotonic() - start, 4)
                if spec.abandon_after_tokens is not None \
                        and req.abandoned:
                    rec["outcome"] = "abandoned"
                    trace.finish("abandoned")
                elif req.error is not None:
                    rec["outcome"] = "failed"
                    rec["error_kind"] = type(req.error).__name__
                    trace.flag("failed")
                    trace.finish(f"failed:{rec['error_kind']}")
                elif req.event.is_set():
                    rec["outcome"] = "completed"
                    trace.finish("ok")
                else:
                    rec["error_kind"] = "driver_timeout"
                    trace.flag("hung")
                    trace.finish("hung")
            finally:
                with self._inflight_lock:
                    self._inflight -= 1
                _note_done(open_loop)

        w = threading.Thread(target=waiter, daemon=True)
        w.start()
        return w


# --- over-the-wire driver --------------------------------------------

class _Conn:
    """Minimal raw-socket HTTP/1.1 + SSE client (stdlib only; the
    gateway speaks unframed SSE after the response head)."""

    def __init__(self, port: int, method: str, path: str, *,
                 host: str = "127.0.0.1", body: Optional[dict] = None,
                 headers: Optional[dict] = None, timeout: float = 60.0):
        self.sock = socket.create_connection((host, port),
                                             timeout=timeout)
        payload = (json.dumps(body).encode("utf-8")
                   if body is not None else b"")
        head = [f"{method} {path} HTTP/1.1", f"Host: {host}",
                "Accept: text/event-stream"]
        for k, v in (headers or {}).items():
            head.append(f"{k}: {v}")
        if payload:
            head.append("Content-Type: application/json")
            head.append(f"Content-Length: {len(payload)}")
        raw = ("\r\n".join(head) + "\r\n\r\n").encode("latin-1")
        self.sock.sendall(raw + payload)
        self.fp = self.sock.makefile("rb")
        status_line = self.fp.readline().decode("latin-1", "replace")
        parts = status_line.split(None, 2)
        self.status = int(parts[1]) if len(parts) >= 2 else 0
        self.headers: dict[str, str] = {}
        while True:
            line = self.fp.readline().decode("latin-1", "replace")
            if line in ("\r\n", "\n", ""):
                break
            k, _, v = line.partition(":")
            self.headers[k.strip().lower()] = v.strip()

    def body_json(self) -> dict:
        n = int(self.headers.get("content-length", "0") or 0)
        raw = self.fp.read(n) if n else b""
        try:
            return json.loads(raw.decode("utf-8", "replace") or "{}")
        except json.JSONDecodeError:
            return {}

    def events(self):
        """Yield (event_id, payload_dict) per SSE event until EOF."""
        eid, data = None, []
        while True:
            raw = self.fp.readline()
            if not raw:
                return
            line = raw.decode("utf-8", "replace").rstrip("\r\n")
            if line.startswith("id:"):
                eid = line[3:].strip()
            elif line.startswith("data:"):
                data.append(line[5:].strip())
            elif line == "" and data:
                joined = "\n".join(data)
                eid_out, data = eid, []
                if joined == "[DONE]":
                    yield eid_out, {"type": "done"}
                    continue
                try:
                    yield eid_out, json.loads(joined)
                except json.JSONDecodeError:
                    continue

    def close(self) -> None:
        try:
            self.fp.close()
        except OSError:
            pass
        try:
            self.sock.close()
        except OSError:
            pass


# Failure kinds a well-behaved client retries: the engine comes back
# (supervisor restart) and greedy + journal replay regenerate the
# round byte-identically on a fresh POST.
RETRYABLE_KINDS = ("device_lost", "engine_dead", "restarting",
                   "data_loss", "engine_wedged")


class GatewayDriver:
    """Offers traffic over the wire against a live gateway (single
    replica or router fleet — the driver only sees the front door).
    Failed streams walk the client retry ladder: a dropped socket
    reconnects GET /v1/streams/<id> with the Last-Event-ID watermark
    (up to `max_reconnects`); a stream FAILED with a retryable kind
    (device_lost, engine restarting, ...) re-POSTs the same session
    once the engine is back (up to `max_reposts`) — on one engine
    there is no surviving replica to fail over to, so the failed
    round must be resubmitted, and greedy decoding + the session
    journal make the regenerated round exact. A chaos arm counts a
    session LOST only when the whole ladder fails."""

    def __init__(self, port: int, *, host: str = "127.0.0.1",
                 max_reconnects: int = 8, max_reposts: int = 8):
        self.port = port
        self.host = host
        self.max_reconnects = max_reconnects
        self.max_reposts = max_reposts

    def run(self, specs: list[SessionSpec], offsets: list[float], *,
            open_loop: bool = True,
            timeout_s: float = 120.0) -> list[dict]:
        records: list[Optional[dict]] = [None] * len(specs)
        if open_loop:
            threads = []
            t0 = time.monotonic()
            for i, (spec, off) in enumerate(zip(specs, offsets)):
                delay = t0 + off - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                records[i] = rec = _new_record(spec, off)
                t = threading.Thread(
                    target=self._client, args=(spec, rec, timeout_s,
                                               open_loop),
                    daemon=True)
                t.start()
                threads.append(t)
            bound = time.monotonic() + timeout_s
            for t in threads:
                t.join(max(bound - time.monotonic(), 0.1))
            return [r for r in records if r is not None]
        # Closed-loop comparison arm.
        cursor = {"i": 0}
        lock = threading.Lock()
        t0 = time.monotonic()

        def client_loop() -> None:
            while True:
                with lock:
                    i = cursor["i"]
                    if i >= len(specs):
                        return
                    cursor["i"] = i + 1
                records[i] = rec = _new_record(
                    specs[i], time.monotonic() - t0)
                self._client(specs[i], rec, timeout_s, False)

        threads = [threading.Thread(target=client_loop, daemon=True)
                   for _ in range(len(offsets))]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout_s)
        return [r for r in records if r is not None]

    # -- one session over the wire --

    def _body(self, spec: SessionSpec) -> dict:
        body: dict[str, Any] = {
            "session": spec.session,
            "turns": [{"knight": k, "prompt": p}
                      for k, p in spec.turns],
            "max_new_tokens": spec.max_new_tokens,
            "priority": spec.priority,
            "temperature": spec.temperature,
        }
        if spec.adapters_per_turn is not None:
            body["adapters"] = spec.adapters_per_turn
        if spec.deadline_s is not None:
            body["deadline_s"] = spec.deadline_s
        return body

    def _client(self, spec: SessionSpec, rec: dict, timeout_s: float,
                open_loop: bool) -> None:
        start = time.monotonic()
        _note_start(open_loop)
        try:
            self._drive(spec, rec, start, timeout_s)
        except Exception as e:  # noqa: BLE001 — record, don't crash the run
            rec["outcome"] = "failed"
            rec["error_kind"] = type(e).__name__
        finally:
            rec["wall_s"] = round(time.monotonic() - start, 4)
            _note_done(open_loop)

    def _drive(self, spec: SessionSpec, rec: dict, start: float,
               timeout_s: float) -> None:
        reposts = 0
        while True:
            retry_kind = self._serve_once(
                spec, rec, start, timeout_s, first=(reposts == 0))
            if retry_kind is None:
                return
            reposts += 1
            if (reposts > self.max_reposts
                    or time.monotonic() - start > timeout_s):
                rec["outcome"] = "failed"
                rec["error_kind"] = retry_kind
                return
            # The regenerated round streams from token zero — don't
            # double-count what the dead stream already delivered.
            rec["tokens"] = 0
            rec["reconnects"] += 1
            time.sleep(min(0.5 * reposts, 2.0))

    def _serve_once(self, spec: SessionSpec, rec: dict, start: float,
                    timeout_s: float, *,
                    first: bool) -> Optional[str]:
        """POST + stream + GET-resume ladder. Returns None when `rec`
        is final, or a retryable failure kind when the caller should
        re-POST the session (engine restarting / round failed with a
        recoverable kind)."""
        try:
            conn = _Conn(self.port, "POST", "/v1/discussions",
                         host=self.host, body=self._body(spec),
                         timeout=timeout_s)
        except OSError:
            return "restarting"  # front door down mid-restart
        if conn.status != 200:
            err = conn.body_json()
            conn.close()
            reason = err.get("reason") or f"http_{conn.status}"
            rec["trace"] = err.get("trace") or rec.get("trace")
            if not first and reason in RETRYABLE_KINDS:
                # An admitted session mid-retry that hits the
                # restarting engine's refusal is NOT shed — keep
                # knocking until the repost budget runs out.
                return reason
            rec["outcome"] = "shed"
            rec["shed_reason"] = reason
            return None
        stream_id, last_id = None, None
        tokens = 0
        attempts = 0
        while True:
            terminal = None
            try:
                for eid, ev in conn.events():
                    if eid:
                        last_id = eid
                    kind = ev.get("type")
                    if kind == "stream":
                        stream_id = ev.get("stream")
                        rec["trace"] = (ev.get("trace")
                                        or rec.get("trace"))
                    elif kind == "tokens":
                        if rec["ttft_s"] is None:
                            rec["ttft_s"] = round(
                                time.monotonic() - start, 4)
                        tokens += len(ev.get("tokens", ()))
                    elif kind == "summary":
                        tokens += sum(
                            len(r.get("tokens", ()))
                            for r in ev.get("rows", {}).values())
                    elif kind in ("retired", "failed", "done"):
                        terminal = (kind, ev)
                    rec["tokens"] = tokens
                    if (spec.abandon_after_tokens is not None
                            and tokens >= spec.abandon_after_tokens):
                        # Mid-stream client disconnect: just drop the
                        # socket — the gateway must clean up.
                        conn.close()
                        rec["outcome"] = "abandoned"
                        return None
                    if terminal is not None:
                        break
            finally:
                conn.close()
            if terminal is not None and terminal[0] != "failed":
                rec["outcome"] = "completed"
                return None
            if terminal is not None:
                # Terminal FAILED: reconnecting would only replay the
                # same failed state — re-POST if the kind is one the
                # engine recovers from, else the session is done.
                fail_kind = terminal[1].get("kind", "unknown")
                if fail_kind in RETRYABLE_KINDS:
                    return fail_kind
                rec["outcome"] = "failed"
                rec["error_kind"] = fail_kind
                return None
            # Socket died without a terminal (gateway restart / pump
            # crash): walk the resume ladder from our watermark until
            # a reconnect serves 200 or the attempt budget runs out.
            reconnected = False
            while not reconnected:
                attempts += 1
                if stream_id is None or attempts > self.max_reconnects:
                    rec["outcome"] = "failed"
                    rec["error_kind"] = "disconnected"
                    return None
                if time.monotonic() - start > timeout_s:
                    rec["outcome"] = "failed"
                    rec["error_kind"] = "driver_timeout"
                    return None
                time.sleep(min(0.25 * attempts, 1.0))
                rec["reconnects"] += 1
                headers = ({"Last-Event-ID": last_id}
                           if last_id else None)
                try:
                    conn = _Conn(self.port, "GET",
                                 f"/v1/streams/{stream_id}",
                                 host=self.host, headers=headers,
                                 timeout=timeout_s)
                except OSError:
                    continue
                if conn.status != 200:
                    conn.close()
                    continue
                reconnected = True
