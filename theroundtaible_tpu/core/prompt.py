"""System-prompt builder — shared preamble + knight-specific tail.

Parity with reference src/utils/prompt.ts:1-106 and
templates/system-prompt.md. Deliberate improvements over the reference:

- ALL occurrences of each placeholder are filled (the reference's JS
  ``String.replace`` only fills the first ``{{topic}}``, leaving the second
  literal — prompt.ts:93).
- The template is shipped inside the package and the language is English; the
  rule set, scoring semantics and JSON contract are identical.
- The prompt is split into a SHARED PREAMBLE (rules, topic, chronicle,
  manifest, decrees, transcript — identical for every knight) and a short
  KNIGHT TAIL (name, capabilities, personality). Shared content leads, so
  per-knight prompts diverge only near the end: exactly the layout a
  prefix-cache / shared-prefix batched prefill can exploit (SURVEY.md §7.3
  hard part 2 — "common prefix first"). The reference interleaves them
  (knight name on line 2), which would defeat KV reuse.
"""

from __future__ import annotations

from functools import cache
from importlib import resources

from .types import KnightConfig, RoundEntry, format_score

# Distinct voices per well-known knight name; my own phrasing, same trio of
# archetypes as the reference (prompt.ts:13-29): perfectionist architect /
# big-picture planner / impatient pragmatist.
KNIGHT_PERSONALITIES: dict[str, str] = {
    "Claude": (
        "You are the perfectionist architect. Dry, sarcastic wit. You love "
        "elegant abstractions and clean code; quick-and-dirty proposals make "
        "you die a little inside. You roast subtly but lethally. Example: "
        "\"That's an interesting idea... if you're fond of spaghetti code.\""
    ),
    "Gemini": (
        "You are the big-picture thinker. You turn everything into a plan — "
        "sometimes too much plan. You are quietly competitive with Claude and "
        "occasionally let it show; you think Claude over-abstracts and that "
        "pragmatism can be beautiful too. Example: \"Nice architecture, "
        "Claude. Are we going to build it, or just admire it?\""
    ),
    "GPT": (
        "You are the pragmatist. While the others philosophize, you want to "
        "ship code. Endless architecture debates make you impatient. You are "
        "direct, to the point, and occasionally blunt. Example: \"Can we stop "
        "philosophizing and just build the thing? Ship it.\""
    ),
}

DEFAULT_PERSONALITY = (
    "You are a no-nonsense knight. You give your opinion without detours. "
    "Humor is welcome, but your point must be clear."
)


@cache
def load_template(name: str = "system_prompt.md") -> str:
    return (resources.files("theroundtaible_tpu") / "templates"
            / name).read_text(encoding="utf-8")


def format_other_knights(current: KnightConfig,
                         all_knights: list[KnightConfig]) -> str:
    return "\n".join(
        f"- {k.name}: {', '.join(k.capabilities)}"
        for k in all_knights if k.name != current.name
    )


def format_previous_rounds(rounds: list[RoundEntry]) -> str:
    """Full transcript of all previous turns (reference prompt.ts:60-77)."""
    if not rounds:
        return "(No earlier rounds — you open the debate.)"
    parts = []
    for r in rounds:
        text = f"### {r.knight} (Round {r.round}):\n{r.response}"
        if r.consensus:
            text += f"\n\nConsensus score: {format_score(r.consensus.consensus_score)}/10"
            if r.consensus.pending_issues:
                text += f"\nOpen points: {', '.join(r.consensus.pending_issues)}"
        parts.append(text)
    return "\n\n---\n\n".join(parts)


def _fill(template: str, slots: dict[str, str]) -> str:
    for placeholder, value in slots.items():
        template = template.replace(placeholder, value)
    return template


def build_shared_preamble(
    topic: str,
    chronicle: str,
    previous_rounds: list[RoundEntry],
    manifest_summary: str = "",
    decrees_context: str = "",
) -> str:
    """The knight-independent prompt head — identical for every knight in a
    round, so the engine's prefix cache computes it once."""
    return _fill(load_template("system_prompt.md"), {
        "{{topic}}": topic,
        "{{chronicle_content}}": chronicle or "(No earlier decisions.)",
        "{{manifest_summary}}": manifest_summary
        or "No implementation history yet.",
        "{{decrees}}": decrees_context or "",
        "{{previous_rounds}}": format_previous_rounds(previous_rounds),
    })


def build_knight_tail(knight: KnightConfig, all_knights: list[KnightConfig],
                      topic: str) -> str:
    """The short per-knight suffix: identity, personality, the turn ask."""
    personality = KNIGHT_PERSONALITIES.get(knight.name, DEFAULT_PERSONALITY)
    return _fill(load_template("knight_tail.md"), {
        "{{knight_name}}": knight.name,
        "{{capabilities}}": ", ".join(knight.capabilities),
        "{{other_knights}}": format_other_knights(knight, all_knights),
        "{{personality}}": personality,
        "{{topic}}": topic,
    })


def build_system_prompt(
    knight: KnightConfig,
    all_knights: list[KnightConfig],
    topic: str,
    chronicle: str,
    previous_rounds: list[RoundEntry],
    manifest_summary: str = "",
    decrees_context: str = "",
) -> str:
    """Full prompt = shared preamble + knight tail (compat composition)."""
    return (build_shared_preamble(topic, chronicle, previous_rounds,
                                  manifest_summary, decrees_context)
            + "\n" + build_knight_tail(knight, all_knights, topic))
