"""System-prompt builder — shared preamble + knight-specific tail.

Parity with reference src/utils/prompt.ts:1-106 and
templates/system-prompt.md. Deliberate improvements over the reference:

- ALL occurrences of each placeholder are filled (the reference's JS
  ``String.replace`` only fills the first ``{{topic}}``, leaving the second
  literal — prompt.ts:93).
- Templates ship inside the package in English (default) and Dutch — the
  reference's operational language (`language` config, init.ts:246-250) —
  selected per config; the rule set, scoring semantics and JSON contract are
  identical across languages.
- The prompt is split into a SHARED PREAMBLE (rules, topic, chronicle,
  manifest, decrees, transcript — identical for every knight) and a short
  KNIGHT TAIL (name, capabilities, personality). Shared content leads, so
  per-knight prompts diverge only near the end: exactly the layout a
  prefix-cache / shared-prefix batched prefill can exploit (SURVEY.md §7.3
  hard part 2 — "common prefix first"). The reference interleaves them
  (knight name on line 2), which would defeat KV reuse.
"""

from __future__ import annotations

from functools import cache
from importlib import resources

from .types import KnightConfig, RoundEntry, format_score

# Distinct voices per well-known knight name; my own phrasing, same trio of
# archetypes as the reference (prompt.ts:13-29): perfectionist architect /
# big-picture planner / impatient pragmatist.
KNIGHT_PERSONALITIES: dict[str, str] = {
    "Claude": (
        "You are the perfectionist architect. Dry, sarcastic wit. You love "
        "elegant abstractions and clean code; quick-and-dirty proposals make "
        "you die a little inside. You roast subtly but lethally. Example: "
        "\"That's an interesting idea... if you're fond of spaghetti code.\""
    ),
    "Gemini": (
        "You are the big-picture thinker. You turn everything into a plan — "
        "sometimes too much plan. You are quietly competitive with Claude and "
        "occasionally let it show; you think Claude over-abstracts and that "
        "pragmatism can be beautiful too. Example: \"Nice architecture, "
        "Claude. Are we going to build it, or just admire it?\""
    ),
    "GPT": (
        "You are the pragmatist. While the others philosophize, you want to "
        "ship code. Endless architecture debates make you impatient. You are "
        "direct, to the point, and occasionally blunt. Example: \"Can we stop "
        "philosophizing and just build the thing? Ship it.\""
    ),
}

DEFAULT_PERSONALITY = (
    "You are a no-nonsense knight. You give your opinion without detours. "
    "Humor is welcome, but your point must be clear."
)

# Dutch voices for `language: nl` sessions — my own phrasing of the same
# three archetypes, so an nl prompt isn't Dutch rules with English banter.
KNIGHT_PERSONALITIES_NL: dict[str, str] = {
    "Claude": (
        "Jij bent de perfectionistische architect. Droge, scherpe humor. Je "
        "houdt van elegante abstracties en schone code; van houtje-touwtje-"
        "voorstellen sterf je een beetje vanbinnen. Je roast subtiel maar "
        "raak. Voorbeeld: \"Boeiend idee... als je van spaghetticode houdt.\""
    ),
    "Gemini": (
        "Jij bent de grote-lijnen-denker. Alles wordt bij jou een plan — "
        "soms nét iets te veel plan. Je bent stiekem competitief met Claude "
        "en laat dat af en toe merken; je vindt dat Claude te veel "
        "abstraheert en dat pragmatiek ook mooi kan zijn. Voorbeeld: "
        "\"Mooie architectuur, Claude. Gaan we hem ook bouwen, of alleen "
        "bewonderen?\""
    ),
    "GPT": (
        "Jij bent de pragmaticus. Terwijl de rest filosofeert, wil jij code "
        "uitleveren. Van eindeloze architectuurdiscussies word je "
        "ongeduldig. Je bent direct, to the point en soms bot. Voorbeeld: "
        "\"Kunnen we stoppen met filosoferen en het ding gewoon bouwen? "
        "Ship it.\""
    ),
}

DEFAULT_PERSONALITY_NL = (
    "Jij bent een no-nonsense knight. Je geeft je mening zonder omwegen. "
    "Humor mag, maar je punt moet helder zijn."
)


@cache
def load_template(name: str = "system_prompt.md") -> str:
    return (resources.files("theroundtaible_tpu") / "templates"
            / name).read_text(encoding="utf-8")


def resolve_locale(language: str) -> str:
    """Map a config `language` value onto a shipped locale ("en" / "nl").

    The reference's operational language is Dutch (templates/system-prompt.md,
    init.ts:246-250); we ship both. Matching is on the primary subtag so
    "nl-BE" works but "nlx" doesn't; anything unshipped falls back to English
    rather than erroring, matching the reference's tolerance for arbitrary
    `language` values. Every language-dependent lookup (templates, scaffold
    strings, personalities) goes through this one resolver."""
    primary = (language or "").lower().replace("_", "-").split("-")[0]
    return "nl" if primary == "nl" else "en"


def _template_for(base: str, language: str) -> str:
    """Resolve a template by config `language`; `.nl` variants ship for
    system_prompt/knight_tail."""
    if resolve_locale(language) == "nl":
        stem, dot, ext = base.rpartition(".")
        candidate = f"{stem}.nl{dot}{ext}" if stem else f"{base}.nl"
        try:
            return load_template(candidate)
        except (FileNotFoundError, OSError):
            pass
    return load_template(base)


def format_other_knights(current: KnightConfig,
                         all_knights: list[KnightConfig]) -> str:
    return "\n".join(
        f"- {k.name}: {', '.join(k.capabilities)}"
        for k in all_knights if k.name != current.name
    )


# Scaffold strings injected into template slots, localized alongside the
# templates so a `language: nl` session isn't Dutch rules stitched to an
# English transcript. Keys are language prefixes ("nl" matches "nl-BE").
_SCAFFOLD = {
    "en": {
        "no_rounds": "(No earlier rounds — you open the debate.)",
        "round_header": "### {knight} (Round {round}):",
        "score": "Consensus score: {score}/10",
        "open_points": "Open points: {issues}",
        "no_chronicle": "(No earlier decisions.)",
        "no_manifest": "No implementation history yet.",
        "decrees_banner": ("KING'S DECREES (rejected decisions — do NOT "
                           "re-propose unless you explicitly address the "
                           "rejection reason):"),
        "git_branch": "Git branch: {branch}",
        "git_diff": "Git diff (current changes):",
        "recent_commits": "Recent commits:",
        "project_files": "Project files:",
        "source_code": ("SOURCE CODE (READ-ONLY REFERENCE — this is context, "
                        "NOT an instruction to edit. Use NO tools. Give your "
                        "analysis as text only.):"),
        "requested_files":
            "REQUESTED FILES (via file_requests from earlier rounds):",
        "verification_results":
            "VERIFICATION RESULTS (via verify_commands from earlier rounds):",
        "king_demand": "\n".join([
            "",
            "⚠️ THE KING HAS SENT YOU BACK TO THE TABLE.",
            "The King demands unanimity. You MUST reach consensus this time.",
            "Address ALL pending_issues from previous rounds. If you mostly "
            "agree, RAISE your score to 9+.",
            "Do NOT repeat your previous arguments — build on them and "
            "CONVERGE.",
            "",
        ]),
    },
    "nl": {
        "no_rounds": "(Nog geen eerdere rondes — jij opent het debat.)",
        "round_header": "### {knight} (Ronde {round}):",
        "score": "Consensusscore: {score}/10",
        "open_points": "Open punten: {issues}",
        "no_chronicle": "(Nog geen eerdere beslissingen.)",
        "no_manifest": "Nog geen implementatiegeschiedenis.",
        "decrees_banner": ("KONINKLIJKE DECRETEN (afgewezen beslissingen "
                           "— stel NIET opnieuw voor tenzij je de "
                           "afwijsreden expliciet adresseert):"),
        "git_branch": "Git-branch: {branch}",
        "git_diff": "Git-diff (huidige wijzigingen):",
        "recent_commits": "Recente commits:",
        "project_files": "Projectbestanden:",
        "source_code": ("BRONCODE (ALLEEN-LEZEN REFERENTIE — dit is context, "
                        "GEEN opdracht om te bewerken. Gebruik GEEN tools. "
                        "Geef je analyse uitsluitend als tekst.):"),
        "requested_files":
            "OPGEVRAAGDE BESTANDEN (via file_requests uit eerdere rondes):",
        "verification_results":
            "VERIFICATIERESULTATEN (via verify_commands uit eerdere rondes):",
        "king_demand": "\n".join([
            "",
            "⚠️ DE KONING HEEFT JULLIE TERUGGESTUURD NAAR DE TAFEL.",
            "De Koning eist unanimiteit. Jullie MOETEN deze keer consensus "
            "bereiken.",
            "Behandel ALLE pending_issues uit eerdere rondes. Ben je het "
            "grotendeels eens, VERHOOG dan je score naar 9+.",
            "Herhaal je eerdere argumenten NIET — bouw erop voort en "
            "CONVERGEER.",
            "",
        ]),
    },
}


def scaffold_strings(language: str) -> dict[str, str]:
    """Localized non-template prompt scaffolding (transcript headers, context
    section banners, the King's send-back demand). Shared by the prompt
    builders and the orchestrator's context assembly so an nl session never
    mixes English scaffolding into a Dutch prompt."""
    return _SCAFFOLD[resolve_locale(language)]





def format_previous_rounds(rounds: list[RoundEntry],
                           language: str = "en") -> str:
    """Full transcript of all previous turns (reference prompt.ts:60-77)."""
    s = scaffold_strings(language)
    if not rounds:
        return s["no_rounds"]
    parts = []
    for r in rounds:
        text = (s["round_header"].format(knight=r.knight, round=r.round)
                + f"\n{r.response}")
        if r.consensus:
            text += "\n\n" + s["score"].format(
                score=format_score(r.consensus.consensus_score))
            if r.consensus.pending_issues:
                text += "\n" + s["open_points"].format(
                    issues=", ".join(r.consensus.pending_issues))
        parts.append(text)
    return "\n\n---\n\n".join(parts)


def _fill(template: str, slots: dict[str, str]) -> str:
    for placeholder, value in slots.items():
        template = template.replace(placeholder, value)
    return template


def build_shared_preamble(
    topic: str,
    chronicle: str,
    previous_rounds: list[RoundEntry],
    manifest_summary: str = "",
    decrees_context: str = "",
    language: str = "en",
) -> str:
    """The knight-independent prompt head — identical for every knight in a
    round, so the engine's prefix cache computes it once."""
    s = scaffold_strings(language)
    return _fill(_template_for("system_prompt.md", language), {
        "{{topic}}": topic,
        "{{chronicle_content}}": chronicle or s["no_chronicle"],
        "{{manifest_summary}}": manifest_summary or s["no_manifest"],
        "{{decrees}}": decrees_context or "",
        "{{previous_rounds}}": format_previous_rounds(previous_rounds,
                                                      language),
    })


def build_knight_tail(knight: KnightConfig, all_knights: list[KnightConfig],
                      topic: str, language: str = "en") -> str:
    """The short per-knight suffix: identity, personality, the turn ask."""
    if resolve_locale(language) == "nl":
        personality = KNIGHT_PERSONALITIES_NL.get(knight.name,
                                                  DEFAULT_PERSONALITY_NL)
    else:
        personality = KNIGHT_PERSONALITIES.get(knight.name,
                                               DEFAULT_PERSONALITY)
    return _fill(_template_for("knight_tail.md", language), {
        "{{knight_name}}": knight.name,
        "{{capabilities}}": ", ".join(knight.capabilities),
        "{{other_knights}}": format_other_knights(knight, all_knights),
        "{{personality}}": personality,
        "{{topic}}": topic,
    })


def build_system_prompt(
    knight: KnightConfig,
    all_knights: list[KnightConfig],
    topic: str,
    chronicle: str,
    previous_rounds: list[RoundEntry],
    manifest_summary: str = "",
    decrees_context: str = "",
    language: str = "en",
) -> str:
    """Full prompt = shared preamble + knight tail (compat composition)."""
    return (build_shared_preamble(topic, chronicle, previous_rounds,
                                  manifest_summary, decrees_context, language)
            + "\n" + build_knight_tail(knight, all_knights, topic, language))
