"""Shared data types for the roundtable core.

Behavioral parity with reference src/types.ts:1-149, re-expressed as Python
dataclasses. These types are the contract between the orchestrator, the
consensus engine, the adapters, and the on-disk ``.roundtable/`` store — the
JSON shapes written to disk match the reference byte-for-byte so a user's
existing ``.roundtable/`` project directory keeps working.
"""

from __future__ import annotations

from dataclasses import dataclass, field, asdict
from typing import Any, Optional

# Caps shared with the reference (src/types.ts:56-57, src/orchestrator.ts:171):
MAX_FILE_REQUESTS_PER_ROUND = 4
MAX_VERIFY_COMMANDS_PER_ROUND = 4


def format_score(score: float) -> str:
    """Render a consensus score the way the reference's JS does: integral
    values without a decimal point (9, not 9.0), fractional as-is."""
    return str(int(score)) if float(score).is_integer() else str(score)


@dataclass
class KnightConfig:
    """One seat at the table (reference src/types.ts:1-7)."""

    name: str
    adapter: str
    capabilities: list[str] = field(default_factory=list)
    priority: int = 1
    fallback: Optional[str] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "KnightConfig":
        return cls(
            name=d["name"],
            adapter=d["adapter"],
            capabilities=list(d.get("capabilities", [])),
            priority=int(d.get("priority", 1)),
            fallback=d.get("fallback"),
        )

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "name": self.name,
            "adapter": self.adapter,
            "capabilities": self.capabilities,
            "priority": self.priority,
        }
        if self.fallback:
            d["fallback"] = self.fallback
        return d


@dataclass
class RulesConfig:
    """Discussion rules (reference src/types.ts:9-16; defaults init.ts:204-220)."""

    max_rounds: int = 5
    consensus_threshold: int = 9
    timeout_per_turn_seconds: int = 120
    escalate_to_user_after: int = 3
    auto_execute: bool = False
    ignore: list[str] = field(
        default_factory=lambda: [".git", "node_modules", "dist", "build", ".next"]
    )
    # TPU-build extension: when true and all knights share one batch-capable
    # adapter (tpu-llm), each round is ONE batched forward pass — knights
    # speak simultaneously instead of seeing same-round earlier turns.
    parallel_rounds: bool = False
    # Time-ladder roots (ISSUE 2, engine/deadlines.py): hard wall-clock
    # budgets for the whole discussion and for each round. None (the
    # default, and the reference's behavior) = unbounded; the per-turn
    # timeout remains the only clock. When set, run_discussion derives
    # the round budgets from the discussion budget top-down and returns
    # PARTIAL results (escalated, transcript intact) when the discussion
    # budget is exhausted instead of running the window into a hard kill.
    discussion_budget_seconds: Optional[float] = None
    round_budget_seconds: Optional[float] = None

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RulesConfig":
        default = cls()
        return cls(
            max_rounds=int(d.get("max_rounds", default.max_rounds)),
            consensus_threshold=int(
                d.get("consensus_threshold", default.consensus_threshold)
            ),
            timeout_per_turn_seconds=int(
                d.get("timeout_per_turn_seconds", default.timeout_per_turn_seconds)
            ),
            escalate_to_user_after=int(
                d.get("escalate_to_user_after", default.escalate_to_user_after)
            ),
            auto_execute=bool(d.get("auto_execute", default.auto_execute)),
            ignore=list(d.get("ignore", default.ignore)),
            parallel_rounds=bool(d.get("parallel_rounds",
                                       default.parallel_rounds)),
            discussion_budget_seconds=(
                float(d["discussion_budget_seconds"])
                if d.get("discussion_budget_seconds") else None),
            round_budget_seconds=(
                float(d["round_budget_seconds"])
                if d.get("round_budget_seconds") else None),
        )

    def to_dict(self) -> dict[str, Any]:
        d = asdict(self)
        # Unset budgets are omitted so a config written before the keys
        # existed round-trips byte-identically (reference schema parity).
        for key in ("discussion_budget_seconds", "round_budget_seconds"):
            if d[key] is None:
                del d[key]
        return d


@dataclass
class RoundtableConfig:
    """Project config, `.roundtable/config.json` (reference src/types.ts:38-46).

    ``adapter_config`` values are kept as raw dicts: the shape is adapter-kind
    dependent (CLI {command,args,model?} / API {model,env_key} / local
    {endpoint,model,name?,source?} / tpu-llm {checkpoint,mesh,…} — reference
    src/types.ts:18-36 plus our new variant).
    """

    version: str
    project: str
    language: str
    knights: list[KnightConfig]
    rules: RulesConfig
    chronicle: str
    adapter_config: dict[str, dict[str, Any]]

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RoundtableConfig":
        return cls(
            version=d.get("version", "1.0"),
            project=d.get("project", ""),
            # The reference defaults to "nl" (src/types.ts via init.ts:246),
            # but here `language` actually selects templates, so a config
            # written before the key existed must keep getting English —
            # init, the example config, and the prompt builders all say "en".
            language=d.get("language", "en"),
            knights=[KnightConfig.from_dict(k) for k in d.get("knights", [])],
            rules=RulesConfig.from_dict(d.get("rules", {})),
            chronicle=d.get("chronicle", ".roundtable/chronicle.md"),
            adapter_config=dict(d.get("adapter_config", {})),
        )

    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "project": self.project,
            "language": self.language,
            "knights": [k.to_dict() for k in self.knights],
            "rules": self.rules.to_dict(),
            "chronicle": self.chronicle,
            "adapter_config": self.adapter_config,
        }


@dataclass
class ConsensusBlock:
    """The structured tail of every knight turn (reference src/types.ts:48-58)."""

    knight: str
    round: int
    consensus_score: float
    agrees_with: list[str] = field(default_factory=list)
    pending_issues: list[str] = field(default_factory=list)
    proposal: Optional[str] = None
    files_to_modify: list[str] = field(default_factory=list)
    file_requests: list[str] = field(default_factory=list)
    verify_commands: list[str] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "knight": self.knight,
            "round": self.round,
            "consensus_score": self.consensus_score,
            "agrees_with": self.agrees_with,
            "pending_issues": self.pending_issues,
        }
        if self.proposal is not None:
            d["proposal"] = self.proposal
        d["files_to_modify"] = self.files_to_modify
        d["file_requests"] = self.file_requests
        d["verify_commands"] = self.verify_commands
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ConsensusBlock":
        return cls(
            knight=d.get("knight", ""),
            round=int(d.get("round", 0)),
            consensus_score=d.get("consensus_score", 0),
            agrees_with=list(d.get("agrees_with", [])),
            pending_issues=list(d.get("pending_issues", [])),
            proposal=d.get("proposal"),
            files_to_modify=list(d.get("files_to_modify", [])),
            file_requests=list(d.get("file_requests", [])),
            verify_commands=list(d.get("verify_commands", [])),
        )


@dataclass
class RoundEntry:
    """One knight turn in the transcript (reference src/types.ts:60-66)."""

    knight: str
    round: int
    response: str
    consensus: Optional[ConsensusBlock]
    timestamp: str

    def to_dict(self) -> dict[str, Any]:
        return {
            "knight": self.knight,
            "round": self.round,
            "response": self.response,
            "consensus": self.consensus.to_dict() if self.consensus else None,
            "timestamp": self.timestamp,
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "RoundEntry":
        consensus = d.get("consensus")
        return cls(
            knight=d["knight"],
            round=int(d["round"]),
            response=d.get("response", ""),
            consensus=ConsensusBlock.from_dict(consensus) if consensus else None,
            timestamp=d.get("timestamp", ""),
        )


# Session phases (reference src/types.ts:68-71). "applying"/"completed" are
# used by the apply subsystem (reference README.md:159-207).
SESSION_PHASES = (
    "discussing",
    "consensus_reached",
    "escalated",
    "applying",
    "completed",
)


@dataclass
class SessionStatus:
    """`status.json` schema (reference src/types.ts:73-83)."""

    phase: str
    current_knight: Optional[str]
    round: int
    consensus_reached: bool
    started_at: str
    updated_at: str
    lead_knight: Optional[str] = None
    decisions_hash: Optional[str] = None
    allowed_files: Optional[list[str]] = None
    # Written only when True so pre-existing status.json files (and the
    # reference's schema, src/types.ts:73-83) round-trip byte-identically.
    # The reference loses this distinction after the process exits
    # (orchestrator.ts:616 writes the same phase for rejection); persisting
    # it lets `status`/`list` render rejection distinctly afterward.
    unanimous_rejection: bool = False

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "phase": self.phase,
            "current_knight": self.current_knight,
            "round": self.round,
            "consensus_reached": self.consensus_reached,
            "started_at": self.started_at,
            "updated_at": self.updated_at,
        }
        if self.lead_knight is not None:
            d["lead_knight"] = self.lead_knight
        if self.decisions_hash is not None:
            d["decisions_hash"] = self.decisions_hash
        if self.allowed_files is not None:
            d["allowed_files"] = self.allowed_files
        if self.unanimous_rejection:
            d["unanimous_rejection"] = True
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "SessionStatus":
        return cls(
            phase=d.get("phase", "discussing"),
            current_knight=d.get("current_knight"),
            round=int(d.get("round", 0)),
            consensus_reached=bool(d.get("consensus_reached", False)),
            started_at=d.get("started_at", ""),
            updated_at=d.get("updated_at", ""),
            lead_knight=d.get("lead_knight"),
            decisions_hash=d.get("decisions_hash"),
            allowed_files=d.get("allowed_files"),
            unanimous_rejection=bool(d.get("unanimous_rejection", False)),
        )


@dataclass
class SessionResult:
    """Return value of a discussion run (reference src/types.ts:85-98)."""

    session_path: str
    consensus: bool
    rounds: int
    decision: Optional[str]
    blocks: list[ConsensusBlock]
    all_rounds: list[RoundEntry]
    unanimous_rejection: bool = False
    resolved_files: str = ""
    resolved_commands: str = ""


@dataclass
class ContinueOptions:
    """State for re-entering a session (reference src/types.ts:101-107).

    Two users: the King's "send back" (unchanged reference behavior,
    king_demand=True injects the unanimity ultimatum into every prompt)
    and crash resume via `discuss --continue` (king_demand=False — the
    knights just pick up where the dead process stopped; reference marks
    this future work at TODO.md:179)."""

    session_path: str
    all_rounds: list[RoundEntry]
    start_round: int
    resolved_files: str = ""
    resolved_commands: str = ""
    king_demand: bool = True


# --- Manifest types (reference src/types.ts:109-129) ---

MANIFEST_STATUSES = ("implemented", "partial", "deprecated")


@dataclass
class ManifestEntry:
    id: str
    session: str
    status: str
    files: list[str]
    summary: str
    applied_at: str
    lead_knight: str
    files_skipped: Optional[list[str]] = None
    replaced_by: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        d: dict[str, Any] = {
            "id": self.id,
            "session": self.session,
            "status": self.status,
            "files": self.files,
        }
        if self.files_skipped is not None:
            d["files_skipped"] = self.files_skipped
        d.update(
            {
                "summary": self.summary,
                "applied_at": self.applied_at,
                "lead_knight": self.lead_knight,
            }
        )
        if self.replaced_by is not None:
            d["replaced_by"] = self.replaced_by
        return d

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "ManifestEntry":
        return cls(
            id=d["id"],
            session=d.get("session", ""),
            status=d.get("status", "implemented"),
            files=list(d.get("files", [])),
            summary=d.get("summary", ""),
            applied_at=d.get("applied_at", ""),
            lead_knight=d.get("lead_knight", ""),
            files_skipped=d.get("files_skipped"),
            replaced_by=d.get("replaced_by"),
        )


@dataclass
class Manifest:
    version: str = "1.0"
    last_updated: str = ""
    features: list[ManifestEntry] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "last_updated": self.last_updated,
            "features": [f.to_dict() for f in self.features],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "Manifest":
        return cls(
            version=d.get("version", "1.0"),
            last_updated=d.get("last_updated", ""),
            features=[ManifestEntry.from_dict(f) for f in d.get("features", [])],
        )


# --- Decree Log types (reference src/types.ts:131-148) ---

DECREE_TYPES = ("rejected_no_apply", "deferred")


@dataclass
class DecreeEntry:
    id: str
    type: str
    session: str
    topic: str
    reason: str
    revoked: bool
    date: str

    def to_dict(self) -> dict[str, Any]:
        return asdict(self)

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DecreeEntry":
        return cls(
            id=d["id"],
            type=d.get("type", "deferred"),
            session=d.get("session", ""),
            topic=d.get("topic", ""),
            reason=d.get("reason", ""),
            revoked=bool(d.get("revoked", False)),
            date=d.get("date", ""),
        )


@dataclass
class DecreeLog:
    version: str = "1.0"
    entries: list[DecreeEntry] = field(default_factory=list)

    def to_dict(self) -> dict[str, Any]:
        return {
            "version": self.version,
            "entries": [e.to_dict() for e in self.entries],
        }

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DecreeLog":
        return cls(
            version=d.get("version", "1.0"),
            entries=[DecreeEntry.from_dict(e) for e in d.get("entries", [])],
        )
