"""Consensus engine: parse, repair, validate and score knight responses.

Pure string→struct logic with zero I/O (unit-testable in isolation), with
behavioral parity to reference src/consensus.ts:1-292:

- fenced ```json block → plain fenced block → balanced-brace extraction
  (string-aware state machine, reference :71-112)
- JSON repair for LLM artifacts: // comments, trailing commas, single quotes
  (reference :287-292) — our repair pass is string-aware so it never corrupts
  apostrophes inside values (a strict superset of inputs parsed)
- "none"-style pending_issues sanitization incl. Dutch variants (reference
  :154-169)
- files_to_modify path validation with NEW: prefix (reference :10-49)
- positive check: ALL scores >= threshold; pending_issues are deliberately
  NON-blocking (reference :211-223 — docs claim otherwise, code wins)
- negative check: >= 2 knights, all scores <= 3 (reference :230-239)
"""

from __future__ import annotations

import json
import re
from typing import Any, Optional

from .types import (
    ConsensusBlock,
    format_score,
    MAX_FILE_REQUESTS_PER_ROUND,
    MAX_VERIFY_COMMANDS_PER_ROUND,
)

# LLMs write ["none"], ["n/a"], ["geen"] instead of [] (reference :154-169).
_MEANINGLESS_ISSUES = {
    "", "none", "no", "n/a", "na", "nil", "null", "-",
    "no issues", "no open issues", "no pending issues",
    "geen", "geen issues", "geen open issues",
    "all resolved", "all issues resolved", "resolved",
    "nothing", "no concerns", "no remaining issues",
}

_FENCED_JSON_RE = re.compile(r"```json\s*\n?(.*?)\n?\s*```", re.DOTALL)
_FENCED_ANY_RE = re.compile(r"```\s*\n?(.*?)\n?\s*```", re.DOTALL)


def validate_files_to_modify(raw: Any) -> list[str]:
    """Normalize a files_to_modify list (reference src/consensus.ts:10-49).

    Relative forward-slash paths only, no traversal, NEW: prefix normalized,
    deduped; invalid entries silently dropped.
    """
    if not isinstance(raw, list):
        return []
    seen: set[str] = set()
    result: list[str] = []
    for item in raw:
        if not isinstance(item, str):
            continue
        path = item.strip()
        if not path:
            continue
        is_new = path.upper().startswith("NEW:")
        if is_new:
            path = path[4:].strip()
        path = path.replace("\\", "/")
        if path.startswith("./"):
            path = path[2:]
        if not path or path.startswith("/") or ".." in path:
            continue
        normalized = f"NEW:{path}" if is_new else path
        if normalized in seen:
            continue
        seen.add(normalized)
        result.append(normalized)
    return result


def sanitize_pending_issues(raw: Any) -> list[str]:
    if not isinstance(raw, list):
        return []
    out = []
    for item in raw:
        if not isinstance(item, str):
            continue
        s = item.strip()
        if s.lower() in _MEANINGLESS_ISSUES:
            continue
        out.append(s)
    return out


def extract_balanced_json(text: str, key: str) -> list[str]:
    """Extract top-level balanced ``{...}`` candidates containing ``"key"``.

    String-aware brace matching (reference src/consensus.ts:71-112): braces
    inside JSON strings, including escaped quotes, do not affect depth.
    """
    key_token = f'"{key}"'
    candidates: list[str] = []
    depth = 0
    start = -1
    in_string = False
    escaped = False
    for i, ch in enumerate(text):
        if in_string:
            if escaped:
                escaped = False
            elif ch == "\\":
                escaped = True
            elif ch == '"':
                in_string = False
            continue
        if ch == '"':
            in_string = True
        elif ch == "{":
            if depth == 0:
                start = i
            depth += 1
        elif ch == "}":
            if depth == 0:
                continue
            depth -= 1
            if depth == 0 and start >= 0:
                candidate = text[start:i + 1]
                if key_token in candidate:
                    candidates.append(candidate)
                start = -1
    return candidates


def repair_json(raw: str) -> str:
    """Best-effort repair of LLM-broken JSON (reference src/consensus.ts:287-292).

    String-aware single pass: outside strings, strip ``// comments``, drop
    trailing commas before ``}``/``]``, and promote single-quoted strings to
    double-quoted (escaping embedded double quotes).
    """
    out: list[str] = []
    i = 0
    n = len(raw)
    in_dq = False  # inside a double-quoted string
    while i < n:
        ch = raw[i]
        if in_dq:
            out.append(ch)
            if ch == "\\" and i + 1 < n:
                out.append(raw[i + 1])
                i += 2
                continue
            if ch == '"':
                in_dq = False
            i += 1
            continue
        if ch == '"':
            in_dq = True
            out.append(ch)
            i += 1
            continue
        if ch == "'":
            # single-quoted string → double-quoted
            j = i + 1
            buf: list[str] = []
            while j < n and raw[j] != "'":
                if raw[j] == "\\" and j + 1 < n:
                    buf.append(raw[j:j + 2])
                    j += 2
                    continue
                buf.append(raw[j])
                j += 1
            inner = "".join(buf).replace('"', '\\"')
            out.append(f'"{inner}"')
            i = j + 1
            continue
        if ch == "/" and i + 1 < n and raw[i + 1] == "/":
            while i < n and raw[i] != "\n":
                i += 1
            continue
        if ch == ",":
            # trailing comma? peek past whitespace
            j = i + 1
            while j < n and raw[j] in " \t\r\n":
                j += 1
            if j < n and raw[j] in "}]":
                i += 1  # drop the comma
                continue
        out.append(ch)
        i += 1
    return "".join(out)


def _parse_consensus_json(text: str, knight_name: str, round_num: int
                          ) -> Optional[ConsensusBlock]:
    try:
        parsed = json.loads(text)
    except (json.JSONDecodeError, RecursionError):
        return None
    if not isinstance(parsed, dict):
        return None
    score = parsed.get("consensus_score")
    if not isinstance(score, (int, float)) or isinstance(score, bool):
        return None
    agrees = parsed.get("agrees_with")
    file_requests = parsed.get("file_requests")
    verify_commands = parsed.get("verify_commands")
    return ConsensusBlock(
        knight=parsed.get("knight") or knight_name,
        round=parsed.get("round") or round_num,
        consensus_score=score,
        agrees_with=[a for a in agrees if isinstance(a, str)]
        if isinstance(agrees, list) else [],
        pending_issues=sanitize_pending_issues(parsed.get("pending_issues")),
        proposal=parsed.get("proposal")
        if isinstance(parsed.get("proposal"), str) else None,
        files_to_modify=validate_files_to_modify(parsed.get("files_to_modify")),
        file_requests=[f for f in file_requests if isinstance(f, str)]
        [:MAX_FILE_REQUESTS_PER_ROUND]
        if isinstance(file_requests, list) else [],
        verify_commands=[c for c in verify_commands if isinstance(c, str)]
        [:MAX_VERIFY_COMMANDS_PER_ROUND]
        if isinstance(verify_commands, list) else [],
    )


def try_parse_consensus(text: str, knight_name: str, round_num: int
                        ) -> Optional[ConsensusBlock]:
    """Raw parse first, then repaired (reference src/consensus.ts:171-181)."""
    for attempt in (text, repair_json(text)):
        block = _parse_consensus_json(attempt, knight_name, round_num)
        if block is not None:
            return block
    return None


def parse_consensus_from_response(response: str, knight_name: str,
                                  round_num: int) -> Optional[ConsensusBlock]:
    """Find + parse the consensus block in a free-text LLM response.

    Fenced ```json → any fenced block → balanced-brace fallback (reference
    src/consensus.ts:118-145).
    """
    for pattern in (_FENCED_JSON_RE, _FENCED_ANY_RE):
        for m in pattern.finditer(response):
            if not m.group(1):
                continue
            block = try_parse_consensus(m.group(1).strip(), knight_name, round_num)
            if block is not None:
                return block
    for candidate in extract_balanced_json(response, "consensus_score"):
        block = try_parse_consensus(candidate, knight_name, round_num)
        if block is not None:
            return block
    return None


def strip_consensus_json(response: str) -> str:
    """Remove the consensus JSON from a response for display purposes
    (reference src/orchestrator.ts:79-109 behavior)."""
    text = response
    for pattern in (_FENCED_JSON_RE, _FENCED_ANY_RE):
        for m in pattern.finditer(text):
            if "consensus_score" in m.group(0):
                return (text[:m.start()] + text[m.end():]).strip()
    for candidate in extract_balanced_json(text, "consensus_score"):
        text = text.replace(candidate, "")
    return text.strip()


def check_consensus(blocks: list[ConsensusBlock], threshold: float) -> bool:
    """Positive consensus: every knight's score >= threshold.

    pending_issues are informational, NOT blocking — knights put notes there
    even at 10/10 (reference src/consensus.ts:211-223; the docs' claim that
    pending_issues must be empty is deliberately not implemented).
    """
    if not blocks:
        return False
    return all(b.consensus_score >= threshold for b in blocks)


def check_negative_consensus(blocks: list[ConsensusBlock],
                             rejection_threshold: float = 3) -> bool:
    """Unanimous rejection: >= 2 knights, all scores <= rejection_threshold
    (reference src/consensus.ts:230-239)."""
    if len(blocks) < 2:
        return False
    return all(b.consensus_score <= rejection_threshold for b in blocks)


def summarize_consensus(blocks: list[ConsensusBlock]) -> str:
    """Human-readable consensus state (reference src/consensus.ts:244-279)."""
    if not blocks:
        return "No consensus data yet."
    lines: list[str] = []
    for b in blocks:
        status = ("AGREES" if b.consensus_score >= 9
                  else "PARTIAL" if b.consensus_score >= 6
                  else "DISAGREES")
        lines.append(f"- **{b.knight}** (Round {b.round}): "
                     f"Score {format_score(b.consensus_score)}/10 [{status}]")
        if b.agrees_with:
            lines.append(f"  Agrees with: {', '.join(b.agrees_with)}")
        if b.pending_issues:
            lines.append(f"  Pending: {', '.join(b.pending_issues)}")
        if b.files_to_modify:
            lines.append(f"  Scope: {', '.join(b.files_to_modify)}")
    avg = sum(b.consensus_score for b in blocks) / len(blocks)
    lines.append(f"\nAverage score: {avg:.1f}/10")
    return "\n".join(lines)


def warn_missing_scope_at_consensus(block: ConsensusBlock) -> Optional[str]:
    """Return a warning string when a knight agreed without naming scope
    (reference src/consensus.ts:54-66). Caller decides how to display it."""
    if block.consensus_score >= 9 and not block.files_to_modify:
        return (f"Warning: {block.knight} agreed (score "
                f"{block.consensus_score}) but didn't specify files_to_modify. "
                f"Scope enforcement will be skipped for this knight.")
    return None
