"""The orchestrator — round loop until consensus, rejection, or escalation.

Parity with reference src/orchestrator.ts:271-673:

- source budget = min over adapters' get_max_source_chars (fairness, :281-292)
- round 1 in priority order; later rounds shuffled against yes-man drift
  (:348-357)
- per-knight turn: status write → prompt build → execute with runtime
  fallback → consensus parse → file_requests/verify_commands resolution;
  a crashed knight is classified, hinted, and the round continues (:521-535)
- per-round: discussion.md rewrite, positive/negative consensus checks,
  escalation warning; terminal writes to decisions.md/status/chronicle
- "King sends back" resume via ContinueOptions (:313-344)

TPU-build addition: when every knight in a round is served by one adapter
that supports batched rounds (the tpu-llm engine) and the config opts in
(`rules` extension `parallel_rounds`), the inner serial loop collapses into
one batched dispatch — knights speak simultaneously instead of seeing
same-round earlier turns. Default stays the reference's sequential semantics.
"""

from __future__ import annotations

import random
import time
from dataclasses import dataclass, field
from datetime import datetime, timezone
from pathlib import Path
from typing import Callable, Optional

from ..adapters.base import BaseAdapter, KnightTurn
from ..adapters.factory import create_adapter
from ..engine import deadlines
from ..utils import telemetry
from ..utils.chronicle import append_to_chronicle
from ..utils.context import ProjectContext, build_context
from ..utils.decree_log import (
    format_decrees_for_prompt,
    get_active_decrees,
    read_decree_log,
)
from ..utils.manifest import get_manifest_summary, read_manifest
from ..utils.session import (
    create_session,
    now_iso,
    update_status,
    write_decisions,
    write_discussion,
    write_transcript,
)
from ..utils.verify import resolve_verify_commands
from .consensus import (
    check_consensus,
    check_negative_consensus,
    strip_consensus_json,
    summarize_consensus,
    warn_missing_scope_at_consensus,
)
from .errors import classify_error, hint_for_kind
from .types import (
    ConsensusBlock,
    ContinueOptions,
    KnightConfig,
    RoundEntry,
    RoundtableConfig,
    SessionResult,
)

DEFAULT_MAX_SOURCE_CHARS = 200_000
GIT_DIFF_PROMPT_CHARS = 3000
FILE_REQUEST_DEFAULT_LINES = 200


class Reporter:
    """Display hooks for the command layer; the default is silent so the
    orchestrator stays import-safe for tests and embedding. The CLI installs
    a console reporter (commands/discuss.py)."""

    def context_start(self) -> None: ...
    def context_done(self, context: ProjectContext, manifest_features: int,
                     decree_count: int) -> None: ...
    def session_started(self, session_path: str, resumed: bool) -> None: ...
    def round_started(self, round_num: int, order: list[str],
                      shuffled: bool) -> None: ...
    def knight_skipped(self, knight: str) -> None: ...
    def knight_thinking(self, knight: str) -> Callable[[], None]:
        return lambda: None
    def knight_spoke(self, knight: str, round_num: int, display_text: str,
                     consensus: Optional[ConsensusBlock]) -> None: ...
    def knight_failed(self, knight: str, kind: str, message: str,
                      hint: Optional[str]) -> None: ...
    def fallback_engaged(self, knight: str, fallback_id: str) -> None: ...
    def resolving_files(self, knight: str, requests: list[str]) -> None: ...
    def resolving_commands(self, knight: str) -> None: ...
    def verify_event(self, kind: str, message: str) -> None: ...
    def consensus_reached(self, blocks: list[ConsensusBlock],
                          allowed_files: list[str]) -> None: ...
    def unanimous_rejection(self, blocks: list[ConsensusBlock]) -> None: ...
    def escalation_warning(self, round_num: int, rounds_left: int) -> None: ...
    def escalated(self, blocks: list[ConsensusBlock]) -> None: ...
    def overflow_warning(self, skipped: int, max_chars: int) -> None: ...
    def round_footer(self, round_metric) -> None: ...


def shuffle_order(knights: list[KnightConfig],
                  rng: Optional[random.Random] = None) -> list[KnightConfig]:
    order = list(knights)
    (rng or random).shuffle(order)
    return order


def _budget_kwargs(adapter: BaseAdapter, budget) -> dict:
    """The budget kwarg, but only for adapters that opted in
    (accepts_budget) — third-party/test subclasses overriding the
    legacy (turns, timeout_ms) signatures keep working unchanged."""
    if budget is not None and getattr(adapter, "accepts_budget", False):
        return {"budget": budget}
    return {}


def execute_with_fallback(
    primary: BaseAdapter, knight: KnightConfig, config: RoundtableConfig,
    prompt: str, timeout_ms: int, adapters: dict[str, BaseAdapter],
    reporter: Reporter, budget=None,
) -> tuple[str, BaseAdapter]:
    """Primary execute; on failure lazily create + cache the knight's
    configured fallback adapter and retry once (reference :45-73).
    Returns (response, the adapter that actually served it). `budget` is
    the knight's turn-rung Budget (engine/deadlines.py); the fallback
    attempt gets its own sibling node so a primary that burned the turn
    hanging still leaves the fallback the round's remaining time."""
    try:
        return primary.execute_for(
            knight.name, prompt, timeout_ms,
            **_budget_kwargs(primary, budget)), primary
    except Exception as primary_error:
        if not knight.fallback:
            raise
        cache_key = f"__fallback_{knight.name}"
        fallback = adapters.get(cache_key)
        if fallback is None:
            created = create_adapter(knight.fallback, config, timeout_ms)
            if created is not None and created.is_available():
                adapters[cache_key] = created
                fallback = created
        if fallback is None:
            raise primary_error
        reporter.fallback_engaged(knight.name, knight.fallback)
        fb_budget = budget.parent.child("turn") if (
            budget is not None and budget.parent is not None) else None
        return fallback.execute_for(
            knight.name, prompt, timeout_ms,
            **_budget_kwargs(fallback, fb_budget)), fallback


def select_lead_knight(knights: list[KnightConfig],
                       blocks: list[ConsensusBlock]) -> KnightConfig:
    """Top scorer of the last round; priority (lowest number) breaks ties;
    fallback = highest-priority knight (reference :114-141)."""
    if blocks:
        last_round = max(b.round for b in blocks)
        last_blocks = [b for b in blocks if b.round == last_round]
        if last_blocks:
            max_score = max(b.consensus_score for b in last_blocks)
            top = [b for b in last_blocks if b.consensus_score == max_score]
            by_name = {k.name: k for k in knights}
            candidates = sorted(
                (by_name[b.knight] for b in top if b.knight in by_name),
                key=lambda k: k.priority)
            if candidates:
                return candidates[0]
    return sorted(knights, key=lambda k: k.priority)[0]


def compute_allowed_files(blocks: list[ConsensusBlock]) -> list[str]:
    """Dedup union of all knights' files_to_modify (reference :145-158)."""
    seen: dict[str, None] = {}
    for block in blocks:
        for f in block.files_to_modify:
            seen.setdefault(f)
    return list(seen)


def resolve_file_requests(file_requests: list[str], project_root: str,
                          ignore_patterns: list[str]) -> str:
    """Read requested files with traversal/ignore guards and range syntax
    `path:start-end`; 200-line default cap (reference :164-222)."""
    import os
    import re

    results: list[str] = []
    for req in file_requests[:4]:
        m = re.match(r"^(.+?):(\d+)-(\d+)$", req)
        file_path = m.group(1) if m else req
        start = int(m.group(2)) if m else None
        end = int(m.group(3)) if m else None

        normalized = os.path.normpath(file_path).replace("\\", "/")
        if ".." in normalized.split("/") or normalized.startswith("/"):
            results.append(f"[DENIED] {req} — path traversal not allowed")
            continue
        if any(normalized.startswith(p) or f"/{p}/" in normalized
               for p in ignore_patterns):
            results.append(f"[DENIED] {req} — matches ignore pattern")
            continue
        full = Path(project_root) / normalized
        if not full.exists():
            results.append(f"[NOT FOUND] {req}")
            continue
        try:
            lines = full.read_text(encoding="utf-8",
                                   errors="replace").split("\n")
        except OSError:
            results.append(f"[ERROR] {req} — could not read file")
            continue
        if start is not None and end is not None:
            excerpt = "\n".join(lines[max(0, start - 1):min(len(lines), end)])
        else:
            excerpt = "\n".join(lines[:FILE_REQUEST_DEFAULT_LINES])
            if len(lines) > FILE_REQUEST_DEFAULT_LINES:
                excerpt += (f"\n...({len(lines) - FILE_REQUEST_DEFAULT_LINES}"
                            " more lines)")
        results.append(f"### {req}\n```\n{excerpt}\n```")
    return "\n\n".join(results)


def king_demand_text(language: str = "en") -> str:
    """The King's send-back demand, in the session's language."""
    from .prompt import scaffold_strings
    return scaffold_strings(language)["king_demand"]


def assemble_shared_context(king_demand: str, context: ProjectContext,
                            resolved_files: str, resolved_commands: str,
                            language: str = "en") -> str:
    """The knight-independent context block (reference :386-425's non-persona
    sections). Sits between the shared preamble and the knight tail so the
    whole head of every prompt is byte-identical across knights — the engine
    prefix-caches it once per round. Section banners are localized with the
    templates (prompt.scaffold_strings) so an nl session isn't Dutch rules
    stitched to English context headers."""
    from .prompt import scaffold_strings
    s = scaffold_strings(language)
    parts = [
        king_demand,
        s["git_branch"].format(branch=context.git_branch)
        if context.git_branch else "",
        (f"{s['git_diff']}\n```\n"
         f"{context.git_diff[:GIT_DIFF_PROMPT_CHARS]}\n```")
        if context.git_diff else "",
        f"{s['recent_commits']}\n{context.recent_commits}"
        if context.recent_commits else "",
        f"\n{s['project_files']}\n{context.key_file_contents}"
        if context.key_file_contents else "",
        f"\n{s['source_code']}\n{context.source_file_contents}"
        if context.source_file_contents else "",
        f"\n{s['requested_files']}\n{resolved_files}"
        if resolved_files else "",
        f"\n{s['verification_results']}\n{resolved_commands}"
        if resolved_commands else "",
    ]
    return "\n".join(p for p in parts if p)


@dataclass
class _RunState:
    all_rounds: list[RoundEntry]
    latest_blocks: dict[str, ConsensusBlock]
    resolved_files: str = ""
    resolved_commands: str = ""
    metrics: object = None  # SessionMetrics (utils/metrics.py)


def run_discussion(
    topic: str,
    config: RoundtableConfig,
    adapters: dict[str, BaseAdapter],
    project_root: str,
    read_source_code: bool = False,
    continue_from: Optional[ContinueOptions] = None,
    reporter: Optional[Reporter] = None,
    rng: Optional[random.Random] = None,
) -> SessionResult:
    """The hot loop owner (reference :271-673)."""
    reporter = reporter or Reporter()
    rules = config.rules
    threshold = rules.consensus_threshold
    timeout_ms = rules.timeout_per_turn_seconds * 1000

    # Fairness: every knight sees the same source budget = min over adapters.
    max_source_chars = DEFAULT_MAX_SOURCE_CHARS
    for knight in config.knights:
        adapter = adapters.get(knight.adapter)
        if adapter:
            budget = adapter.get_max_source_chars()
            if budget is not None and budget < max_source_chars:
                max_source_chars = budget

    reporter.context_start()
    context = build_context(project_root, config, read_source_code,
                            max_source_chars,
                            on_overflow=reporter.overflow_warning)
    manifest = read_manifest(project_root)
    manifest_summary = get_manifest_summary(manifest, config.language)
    decree_log = read_decree_log(project_root)
    active_decrees = get_active_decrees(decree_log)
    decrees_context = format_decrees_for_prompt(active_decrees,
                                                config.language)
    reporter.context_done(context, len(manifest.features), len(active_decrees))

    if continue_from:
        session_path = continue_from.session_path
    else:
        session_path = str(create_session(project_root, topic))
    reporter.session_started(session_path, resumed=continue_from is not None)

    sorted_knights = sorted(config.knights, key=lambda k: k.priority)
    state = _RunState(
        all_rounds=list(continue_from.all_rounds) if continue_from else [],
        latest_blocks={},
        resolved_files=continue_from.resolved_files if continue_from else "",
        resolved_commands=(continue_from.resolved_commands
                           if continue_from else ""),
    )
    if continue_from:
        for entry in continue_from.all_rounds:
            if entry.consensus:
                state.latest_blocks[entry.knight] = entry.consensus

    start_round = continue_from.start_round if continue_from else 1
    end_round = start_round + rules.max_rounds - 1
    king_demand = (king_demand_text(config.language)
                   if continue_from and continue_from.king_demand else "")

    from ..utils.metrics import SessionMetrics, maybe_profile
    state.metrics = SessionMetrics(session_path)

    # Time-ladder root (ISSUE 2): the discussion budget bounds every
    # round budget, which bounds every turn — threaded top-down through
    # the budget-aware adapters into the engines' prefill/decode/dispatch
    # rungs (engine/deadlines.py). Unset budgets are unbounded roots, so
    # the reference's timeout-per-turn-only behavior is the default.
    discussion_budget = deadlines.Budget.root(
        rules.discussion_budget_seconds, rung="discussion")

    # Span-tree root (ISSUE 5): the discussion span mirrors the root
    # Budget above; the per-session JSONL sink rides the span tree so
    # every child — across adapter pool threads and the scheduler —
    # lands in <session>/telemetry/spans.jsonl. Under maybe_profile the
    # "profile" root wraps this, sharing one trace id with xprof.
    tele_sink = (telemetry.session_sink(session_path)
                 if telemetry.ACTIVE else None)
    with maybe_profile(session_path), telemetry.span(
            "discussion", sink=tele_sink,
            session=Path(session_path).name, knights=len(sorted_knights)):
        for round_num in range(start_round, end_round + 1):
            if discussion_budget.expired:
                # Hard discussion budget exhausted: return PARTIAL
                # results through the normal escalation path (transcript
                # and blocks intact, culprit named) instead of letting
                # the window die with nothing.
                # The budget can also come from a configured discussion
                # rung cap, so name whichever bound actually applied.
                bound = (f"{rules.discussion_budget_seconds:.0f}s"
                         if rules.discussion_budget_seconds
                         else f"rung cap {deadlines.rung_cap('discussion'):.0f}s")
                reporter.verify_event(
                    "warning",
                    f"discussion budget ({bound}) exhausted before "
                    f"round {round_num} — returning partial results")
                break
            round_budget = discussion_budget.child(
                "round", timeout_s=rules.round_budget_seconds)
            is_first = round_num == start_round and not continue_from
            round_order = (sorted_knights if is_first
                           else shuffle_order(sorted_knights, rng))
            reporter.round_started(round_num, [k.name for k in round_order],
                                   shuffled=not is_first)

            state.metrics.start_round(round_num)
            with telemetry.span("round", round=round_num):
                _run_round_turns(
                    round_order, round_num, topic, config, adapters,
                    project_root, session_path, context, manifest_summary,
                    decrees_context, king_demand, state, timeout_ms,
                    reporter, round_budget)
            state.metrics.end_round()
            if state.metrics.rounds:
                reporter.round_footer(state.metrics.rounds[-1])

            write_discussion(session_path, state.all_rounds)
            write_transcript(session_path, state.all_rounds)
            current_blocks = list(state.latest_blocks.values())

            if check_consensus(current_blocks, threshold):
                state.metrics.finish("consensus_reached")
                return _finish_consensus(
                    topic, config, project_root, session_path, round_num,
                    current_blocks, state, reporter)

            if check_negative_consensus(current_blocks):
                state.metrics.finish("unanimous_rejection")
                return _finish_rejection(
                    topic, config, project_root, session_path, round_num,
                    current_blocks, state, reporter)

            if rules.escalate_to_user_after <= round_num < end_round:
                reporter.escalation_warning(round_num, end_round - round_num)

    state.metrics.finish("escalated")
    reporter.escalated(list(state.latest_blocks.values()))
    update_status(session_path, phase="escalated", consensus_reached=False,
                  round=end_round)
    return SessionResult(
        session_path=session_path, consensus=False, rounds=end_round,
        decision=None, blocks=list(state.latest_blocks.values()),
        all_rounds=state.all_rounds,
        resolved_files=state.resolved_files,
        resolved_commands=state.resolved_commands,
    )


def _build_turn_prompt(knight, config, topic, context, manifest_summary,
                       decrees_context, king_demand, state):
    from .prompt import build_knight_tail, build_shared_preamble

    shared = (build_shared_preamble(
        topic, context.chronicle, state.all_rounds, manifest_summary,
        decrees_context, config.language)
        + "\n" + assemble_shared_context(
            king_demand, context, state.resolved_files,
            state.resolved_commands, config.language))
    return shared + "\n" + build_knight_tail(knight, config.knights, topic,
                                             config.language)


def _batch_groups(round_order, adapters):
    """Partition the round into batch-capable adapter groups + the rest.

    Knights sharing one batch-capable adapter (same resident model) form a
    group served by ONE batched device program. DIFFERENT batch-capable
    adapters (heterogeneous fleet — per-model submeshes, engine/fleet.py)
    become separate groups that run CONCURRENTLY: their submeshes are
    disjoint chips, so the round's wall-clock is max, not sum. Knights on
    non-batchable adapters (CLI/API/local) stay on the serial path.
    """
    groups: dict[int, tuple[BaseAdapter, list]] = {}
    serial = []
    for k in round_order:
        a = adapters.get(k.adapter)
        # A KNOWN-sick batch adapter (open circuit breaker, dead engine)
        # routes its knights to the SERIAL path, where
        # execute_with_fallback engages each knight's configured
        # fallback — the discussion continues instead of the whole group
        # failing every round (ISSUE 1 engine→adapter-fallback rung).
        # known_unhealthy, not is_available: grouping must stay cheap —
        # is_available lazily BUILDS the engine, which would serialize
        # first-round construction here instead of in the group pool.
        if (a is not None and a.supports_batched_rounds()
                and not a.known_unhealthy()):
            groups.setdefault(id(a), (a, []))[1].append(k)
        else:
            serial.append(k)
    # A lone batchable knight gains nothing from the batch path but would
    # lose its place in the speaking order (batch groups dispatch against
    # the round-start snapshot, ahead of serial knights) — keep the round
    # fully serial unless there's real batching or fleet concurrency.
    if sum(len(ks) for _, ks in groups.values()) < 2:
        return [], list(round_order)
    return list(groups.values()), serial


def _run_round_turns(round_order, round_num, topic, config, adapters,
                     project_root, session_path, context, manifest_summary,
                     decrees_context, king_demand, state, timeout_ms,
                     reporter, round_budget=None) -> None:
    if config.rules.parallel_rounds:
        groups, serial_order = _batch_groups(round_order, adapters)
    else:
        groups, serial_order = [], round_order

    if groups:
        # Batched dispatch: each group's knights speak against the same
        # transcript snapshot in ONE device program (SURVEY.md §7.1);
        # multiple groups (heterogeneous models) dispatch concurrently.
        update_status(session_path, phase="discussing", current_knight=None,
                      round=round_num)
        jobs = []
        for adapter, knights in groups:
            turns = [KnightTurn(
                knight_name=k.name,
                prompt=_build_turn_prompt(
                    k, config, topic, context, manifest_summary,
                    decrees_context, king_demand, state))
                for k in knights]
            jobs.append((adapter, knights, turns))

        # The round span lives on THIS thread; group jobs run on pool
        # threads, so the span context is handed across explicitly and
        # re-attached there (telemetry's cross-thread parenting seam) —
        # the engines' turn/prefill/decode spans then nest under the
        # right round in the session's JSONL.
        tele_ctx = telemetry.current_context() if telemetry.ACTIVE \
            else None

        def run_group(job):
            adapter, knights, turns = job
            t0 = time.monotonic()
            # Each group receives the round budget directly (the adapter
            # derives its own round-rung child): groups run CONCURRENTLY
            # on disjoint submeshes, so they share the round's
            # wall-clock, not a division of it.
            with telemetry.attached(tele_ctx):
                responses = adapter.execute_round(
                    turns, timeout_ms,
                    **_budget_kwargs(adapter, round_budget))
            if len(responses) != len(turns):
                raise RuntimeError(
                    f"batched round returned {len(responses)} responses "
                    f"for {len(turns)} turns")
            return responses, time.monotonic() - t0, adapter.last_stats()

        if len(jobs) == 1:
            results = [_try(run_group, jobs[0])]
        else:
            from concurrent.futures import ThreadPoolExecutor
            with ThreadPoolExecutor(max_workers=len(jobs)) as pool:
                results = list(pool.map(lambda j: _try(run_group, j), jobs))

        # Record in round order regardless of completion order.
        response_by_knight = {}
        retry_serially = []
        for (adapter, knights, turns), outcome in zip(jobs, results):
            if isinstance(outcome, Exception):
                # Group-failure degradation (ISSUE 1): the batched
                # dispatch (and the adapter's own serial retry) gave up —
                # fall through to the per-knight serial path below, where
                # execute_with_fallback retries the primary once more and
                # then engages the knight's configured fallback adapter.
                # Only knights that fail THERE too are reported failed.
                retry_serially.extend(knights)
                continue
            responses, group_wall, engine_stats = outcome
            if state.metrics:
                # one batched program served the whole group: group wall
                # for every knight, engine numbers attached once (to the
                # first knight) so totals don't multiply. Scheduler
                # provenance (queue wait, decode-batch occupancy) is a
                # property of the whole round, not a summable quantity —
                # every knight's turn record carries it (ISSUE 4).
                sched = (engine_stats or {}).get("sched") or {}
                for i, (k, t, resp) in enumerate(
                        zip(knights, turns, responses)):
                    state.metrics.record_turn(
                        k.name, round_num, group_wall,
                        chars_in=len(t.prompt), chars_out=len(resp),
                        engine=engine_stats if i == 0 else None,
                        queue_wait_s=sched.get("queue_wait_s"),
                        batch_occupancy=sched.get("occupancy_mean"))
            for k, resp in zip(knights, responses):
                response_by_knight[k.name] = (resp, adapter)
        for knight in round_order:
            if knight.name in response_by_knight:
                resp, adapter = response_by_knight[knight.name]
                _record_turn(knight, round_num, resp, adapter, config,
                             project_root, state, reporter)
        if retry_serially:
            serial_order = list(serial_order) + retry_serially

    for knight in serial_order:
        adapter = adapters.get(knight.adapter)
        if adapter is None:
            reporter.knight_skipped(knight.name)
            continue
        update_status(session_path, phase="discussing",
                      current_knight=knight.name, round=round_num)
        prompt = _build_turn_prompt(
            knight, config, topic, context, manifest_summary,
            decrees_context, king_demand, state)
        stop_thinking = reporter.knight_thinking(knight.name)
        t0 = time.monotonic()
        turn_budget = (round_budget.child(
            "turn", timeout_s=timeout_ms / 1000)
            if round_budget is not None else None)
        try:
            response, served_by = execute_with_fallback(
                adapter, knight, config, prompt, timeout_ms, adapters,
                reporter, budget=turn_budget)
        except Exception as error:  # noqa: BLE001 — turn-level containment
            stop_thinking()
            kind = classify_error(error)
            reporter.knight_failed(knight.name, kind, str(error),
                                   hint_for_kind(kind))
            continue
        stop_thinking()
        if state.metrics:
            state.metrics.record_turn(
                knight.name, round_num, time.monotonic() - t0,
                chars_in=len(prompt), chars_out=len(response),
                engine=served_by.last_stats())
        _record_turn(knight, round_num, response, served_by, config,
                     project_root, state, reporter)


def _try(fn, arg):
    """Run fn(arg), returning the exception instead of raising (used to
    contain per-group failures in the concurrent fan-out)."""
    try:
        return fn(arg)
    except Exception as e:  # noqa: BLE001 — containment by design
        return e


def _record_turn(knight, round_num, response, adapter, config, project_root,
                 state, reporter) -> None:
    consensus = adapter.parse_consensus(response, round_num)
    if consensus is not None:
        # Adapter-level parse keeps the adapter's own knight naming; pin the
        # turn to the configured knight name for transcript consistency.
        consensus.knight = knight.name
    entry = RoundEntry(knight=knight.name, round=round_num, response=response,
                       consensus=consensus, timestamp=now_iso())
    state.all_rounds.append(entry)
    display = strip_consensus_json(response)
    reporter.knight_spoke(knight.name, round_num, display, consensus)

    if consensus is None:
        return
    state.latest_blocks[knight.name] = consensus
    if consensus.file_requests:
        reporter.resolving_files(knight.name, consensus.file_requests)
        new_files = resolve_file_requests(
            consensus.file_requests, project_root, config.rules.ignore)
        if new_files:
            state.resolved_files += \
                ("\n\n" if state.resolved_files else "") + new_files
    if consensus.verify_commands:
        reporter.resolving_commands(knight.name)
        new_commands = resolve_verify_commands(
            consensus.verify_commands, project_root,
            on_event=reporter.verify_event)
        if new_commands:
            state.resolved_commands += \
                ("\n\n" if state.resolved_commands else "") + new_commands


def _finish_consensus(topic, config, project_root, session_path, round_num,
                      current_blocks, state, reporter) -> SessionResult:
    for block in current_blocks:
        warning = warn_missing_scope_at_consensus(block)
        if warning:
            reporter.verify_event("warning", warning)
    allowed_files = compute_allowed_files(current_blocks)
    reporter.consensus_reached(current_blocks, allowed_files)

    last_proposal = None
    for entry in reversed(state.all_rounds):
        if entry.consensus and entry.consensus.proposal:
            last_proposal = entry.consensus.proposal
            break
    if last_proposal is None:
        last_proposal = (state.all_rounds[-1].response if state.all_rounds
                         else "No proposal text available.")

    lead = select_lead_knight(config.knights, current_blocks)
    write_decisions(session_path, topic, last_proposal, state.all_rounds)
    update_status(session_path, phase="consensus_reached",
                  consensus_reached=True, round=round_num,
                  allowed_files=allowed_files if allowed_files else None,
                  lead_knight=lead.name)
    append_to_chronicle(
        project_root, config.chronicle, topic=topic,
        outcome=(f"Consensus in {round_num} round(s). "
                 f"Lead Knight: {lead.name}.\n\n{last_proposal}"),
        knights=[b.knight for b in current_blocks],
        date=datetime.now(timezone.utc).strftime("%Y-%m-%d"))
    return SessionResult(
        session_path=session_path, consensus=True, rounds=round_num,
        decision=last_proposal, blocks=current_blocks,
        all_rounds=state.all_rounds,
        resolved_files=state.resolved_files,
        resolved_commands=state.resolved_commands,
    )


def _finish_rejection(topic, config, project_root, session_path, round_num,
                      current_blocks, state, reporter) -> SessionResult:
    reporter.unanimous_rejection(current_blocks)
    rejection_summary = "\n\n---\n\n".join(
        f"## {r.knight}\n\n{r.response}"
        for r in state.all_rounds if r.round == round_num)
    write_decisions(session_path, topic, rejection_summary, state.all_rounds)
    update_status(session_path, phase="consensus_reached",
                  consensus_reached=True, round=round_num,
                  unanimous_rejection=True)
    append_to_chronicle(
        project_root, config.chronicle, topic=topic,
        outcome=(f"Unanimous rejection in {round_num} round(s). "
                 "All knights advise against this."),
        knights=[b.knight for b in current_blocks],
        date=datetime.now(timezone.utc).strftime("%Y-%m-%d"))
    return SessionResult(
        session_path=session_path, consensus=True, unanimous_rejection=True,
        rounds=round_num, decision=rejection_summary, blocks=current_blocks,
        all_rounds=state.all_rounds,
        resolved_files=state.resolved_files,
        resolved_commands=state.resolved_commands,
    )
