"""Code-red diagnostic engine — doctor blocks, fuzzy keys, convergence.

The reference's diagnostic system (architecture-docs.md:154-167): each
doctor ends a turn with

    {"confidence_score": 8, "root_cause_key": "stale-auth-token",
     "evidence": [...], "rules_out": [...], "confirms": [...],
     "file_requests": [...], "next_test": "..."}

Convergence = 2+ doctors agree on the root_cause_key (exact or fuzzy) with
confidence >= 8 (architecture-docs.md:166). Pure logic, zero I/O — same
testability stance as the consensus engine.
"""

from __future__ import annotations

import json
import re
from dataclasses import dataclass, field
from typing import Any, Optional

from .consensus import extract_balanced_json, repair_json

CONVERGENCE_CONFIDENCE = 8
CONVERGENCE_DOCTORS = 2
MAX_FILE_REQUESTS = 4


@dataclass
class DiagnosticBlock:
    """One doctor's structured diagnosis."""

    doctor: str
    round: int
    confidence_score: float
    root_cause_key: str
    evidence: list[str] = field(default_factory=list)
    rules_out: list[str] = field(default_factory=list)
    confirms: list[str] = field(default_factory=list)
    file_requests: list[str] = field(default_factory=list)
    next_test: Optional[str] = None


def _as_str_list(raw: Any) -> list[str]:
    if not isinstance(raw, list):
        return []
    return [str(x).strip() for x in raw if str(x).strip()]


def _from_dict(d: dict[str, Any], doctor: str, round_num: int
               ) -> Optional[DiagnosticBlock]:
    if "confidence_score" not in d and "root_cause_key" not in d:
        return None
    try:
        confidence = float(d.get("confidence_score", 0))
    except (TypeError, ValueError):
        confidence = 0.0
    confidence = max(0.0, min(10.0, confidence))
    return DiagnosticBlock(
        doctor=doctor,
        round=round_num,
        confidence_score=confidence,
        root_cause_key=str(d.get("root_cause_key", "")).strip(),
        evidence=_as_str_list(d.get("evidence")),
        rules_out=_as_str_list(d.get("rules_out")),
        confirms=_as_str_list(d.get("confirms")),
        file_requests=_as_str_list(
            d.get("file_requests"))[:MAX_FILE_REQUESTS],
        next_test=(str(d["next_test"]).strip()
                   if d.get("next_test") else None),
    )


def parse_diagnostic_from_response(response: str, doctor: str,
                                   round_num: int
                                   ) -> Optional[DiagnosticBlock]:
    """Same repair ladder as the consensus parser: fenced ```json block
    first, then balanced-brace extraction, then repair_json retry."""
    fenced = re.findall(r"```(?:json)?\s*([\s\S]*?)```", response)
    candidates = [c for c in fenced if "confidence_score" in c
                  or "root_cause_key" in c]
    candidates += extract_balanced_json(response, "confidence_score")
    candidates += extract_balanced_json(response, "root_cause_key")
    for raw in candidates:
        for attempt in (raw, repair_json(raw)):
            try:
                d = json.loads(attempt)
            except (json.JSONDecodeError, ValueError):
                continue
            if isinstance(d, dict):
                block = _from_dict(d, doctor, round_num)
                if block is not None:
                    return block
    return None


# --- fuzzy key matching ---

_STOPWORDS = {"the", "a", "an", "in", "on", "of", "is", "not", "bug",
              "issue", "error", "problem"}


def _key_tokens(key: str) -> set[str]:
    tokens = re.split(r"[\s\-_/.:]+", key.lower())
    return {t for t in tokens if t and t not in _STOPWORDS}


def keys_match(a: str, b: str) -> bool:
    """Exact or fuzzy equality of root-cause keys. Fuzzy = token-set
    Jaccard >= 0.5 or one side's tokens contained in the other (doctors
    phrase the same cause at different verbosity)."""
    if not a or not b:
        return False
    if a.strip().lower() == b.strip().lower():
        return True
    ta, tb = _key_tokens(a), _key_tokens(b)
    if not ta or not tb:
        return False
    if ta <= tb or tb <= ta:
        return True
    overlap = len(ta & tb)
    return overlap / len(ta | tb) >= 0.5


def check_convergence(blocks: list[DiagnosticBlock]
                      ) -> Optional[tuple[str, list[DiagnosticBlock]]]:
    """Largest fuzzy-matching group with >= CONVERGENCE_DOCTORS members,
    every member confident (>= CONVERGENCE_CONFIDENCE). Returns
    (representative_key, group) or None."""
    confident = [b for b in blocks
                 if b.confidence_score >= CONVERGENCE_CONFIDENCE
                 and b.root_cause_key]
    best: Optional[tuple[str, list[DiagnosticBlock]]] = None
    for anchor in confident:
        group = [b for b in confident
                 if keys_match(anchor.root_cause_key, b.root_cause_key)]
        # one block per doctor (latest wins)
        by_doctor: dict[str, DiagnosticBlock] = {}
        for b in group:
            by_doctor[b.doctor] = b
        group = list(by_doctor.values())
        if len(group) >= CONVERGENCE_DOCTORS and (
                best is None or len(group) > len(best[1])):
            best = (anchor.root_cause_key, group)
    return best


def summarize_diagnosis(key: str, group: list[DiagnosticBlock]) -> str:
    """Human-readable convergence report for decisions.md / error-log."""
    lines = [f"ROOT CAUSE: {key}", ""]
    for b in sorted(group, key=lambda x: -x.confidence_score):
        lines.append(f"- **{b.doctor}** (confidence "
                     f"{b.confidence_score:g}/10): {b.root_cause_key}")
        for e in b.evidence[:3]:
            lines.append(f"  - evidence: {e}")
        if b.next_test:
            lines.append(f"  - next test: {b.next_test}")
    return "\n".join(lines)


def strip_diagnostic_json(response: str) -> str:
    """Remove the trailing diagnostic JSON for display purposes."""
    out = re.sub(
        r"```(?:json)?\s*\{[\s\S]*?(?:confidence_score|root_cause_key)"
        r"[\s\S]*?\}\s*```", "", response)
    for raw in extract_balanced_json(out, "confidence_score"):
        out = out.replace(raw, "")
    for raw in extract_balanced_json(out, "root_cause_key"):
        out = out.replace(raw, "")
    return out.strip()
