"""Error hierarchy, classification and exit codes.

Parity with reference src/utils/errors.ts:1-151: a typed error tree with exit
codes, message-sniffing classification into actionable kinds, and a single
formatting helper. ``process.exit`` discipline (only the CLI entry exits —
reference src/index.ts:29-46) is preserved: nothing in this module exits.
"""

from __future__ import annotations

from enum import IntEnum
from typing import Optional


class ExitCode(IntEnum):
    """Reference src/utils/errors.ts:7-16."""

    OK = 0
    GENERAL = 1
    CONFIG = 2
    ADAPTER = 3
    SESSION = 4
    FILE_WRITE = 5
    CONSENSUS = 6
    UNEXPECTED = 99


class RoundtableError(Exception):
    """Base of the tree (reference src/utils/errors.ts:23-80)."""

    exit_code: ExitCode = ExitCode.GENERAL

    def __init__(self, message: str, hint: Optional[str] = None,
                 cause: Optional[BaseException] = None):
        super().__init__(message)
        self.message = message
        self.hint = hint
        self.cause = cause


class ConfigError(RoundtableError):
    exit_code = ExitCode.CONFIG


class AdapterError(RoundtableError):
    exit_code = ExitCode.ADAPTER

    def __init__(self, message: str, kind: str = "unknown",
                 hint: Optional[str] = None, cause: Optional[BaseException] = None):
        super().__init__(message, hint=hint, cause=cause)
        # not_installed | timeout | auth | api | oom | hang |
        # device_lost | unknown
        self.kind = kind


class SessionError(RoundtableError):
    exit_code = ExitCode.SESSION


class FileWriteError(RoundtableError):
    exit_code = ExitCode.FILE_WRITE


class ConsensusError(RoundtableError):
    exit_code = ExitCode.CONSENSUS


# --- classification (reference src/utils/errors.ts:86-126) ---

_KIND_HINTS = {
    "not_installed": "Is the tool installed and on PATH? Try running it by hand.",
    "timeout": "The knight ran out of time. Raise rules.timeout_per_turn_seconds "
               "or pick a faster model.",
    "auth": "Check your API key (env var or ~/.theroundtaible/keys.json).",
    "api": "The backend returned an error. Check its status page / server logs.",
    "oom": "The device ran out of memory. Use a smaller model, shorter context, "
           "or a larger mesh.",
    "hang": "A device wait exceeded its watchdog budget — the program is "
            "presumed wedged. Check device health, or raise the rung budget "
            "(ROUNDTABLE_RUNG_BUDGETS) if the wait was legitimate.",
    "device_lost": "The accelerator itself failed or disappeared — no "
                   "retry on this engine can succeed. The engine "
                   "supervisor rebuilds it (engine/supervisor.py); if "
                   "this persists past the restart budget, check "
                   "device health / the platform runtime.",
    "deadline_expired": "The request's SLO budget was already spent at "
                        "submission — it never ran. Raise the client "
                        "deadline, or shed load upstream so requests "
                        "arrive with budget to spare.",
    "unknown": None,
}

_NOT_INSTALLED_MARKERS = (
    "enoent", "not found", "command not found", "no such file",
    "is not recognized",
)
_TIMEOUT_MARKERS = ("timed out", "timeout", "etimedout", "abort", "deadline")
_AUTH_MARKERS = (
    "401", "403", "unauthorized", "forbidden", "invalid api key",
    "invalid x-api-key", "authentication", "permission denied",
)
_API_MARKERS = ("429", "500", "502", "503", "529", "overloaded",
                "rate limit", "econnrefused", "fetch failed", "bad gateway")
# TPU-engine-specific kinds (no reference counterpart; SURVEY.md §5.3 calls for
# HBM OOM classification mapped onto the taxonomy).
_OOM_MARKERS = ("resource_exhausted", "out of memory", "hbm", "oom",
                "allocation failure")
# Watchdog hang detection (engine/deadlines.py): a wait that exceeded
# its rung budget is a WEDGED program, not a polite timeout — it must
# classify ahead of the timeout markers so the ladder treats it like a
# crash (no blind retry, revive + re-seat). Markers are whole words the
# watchdog/fault messages carry ("hang" alone would match "change").
_HANG_MARKERS = ("watchdog", "wedged", "hang detected", "(hang)")
# Device loss (ISSUE 12): the accelerator itself died or vanished — the
# strongest failure kind, classified FIRST: neither a retry nor a
# revive on the same engine can succeed, only the supervisor's
# tear-down/rebuild (engine/supervisor.py) helps. Markers match the
# real runtime messages ("DATA_LOSS: ...", "device is lost", libtpu
# halt strings) and the deterministic fault injection.
_DEVICE_LOST_MARKERS = ("device lost", "device is lost", "data_loss",
                        "device halted", "chip reboot",
                        "(device_lost)")


# Declarative class -> kind classification for the IN-TREE exception
# classes the serving engine raises (ISSUE 15). Message sniffing stays
# the primary classifier — fault injection deliberately crafts messages
# that classify like their real counterparts ("hbm" -> oom), and that
# must keep winning — but a class whose message carries no marker used
# to fall through to "unknown" and take the wrong recovery ladder (the
# PR-12 device_lost ordering bug class). This table is consulted LAST,
# by class name up the MRO, and is also the registration the static
# analyzer checks: `roundtable lint` (RT-ERROR-KIND) fails when engine
# code raises an in-tree class that neither descends from
# RoundtableError nor appears here. AdapterError subclasses (EngineDead)
# carry their kind directly and need no entry.
ERROR_KIND_TABLE: dict[str, str] = {
    # engine/deadlines.py — the time ladder
    "HangDetected": "hang",          # wedged program, not a polite timeout
    "StaleWait": "hang",             # watchdog-abandoned wait completed late
    "BudgetExceeded": "timeout",     # the rung's deadline authority fired
    "Cancelled": "timeout",          # cooperative cancel at a rung boundary
    "DrainingError": "draining",     # admission gate closed, not a failure
    # engine/faults.py — chaos injection (plain-message injections only;
    # kind-mimicking messages classify by their markers above)
    "FaultInjected": "fault_injected",
    # engine/scheduler.py — admission verdicts
    "SchedulerRefused": "refused",   # never-fits: actionable config change
    "SchedulerClosed": "closed",
    # SLO budget spent at submit — failed fast before any prefill
    # dispatch (gateway deadline propagation, ISSUE 16). Its own kind,
    # not "timeout": the request never ran, so the timeout ladder's
    # retry/raise-budget hints would mislead.
    "DeadlineExpired": "deadline_expired",
    # engine/compile_watch.py — the steady-state sentinel
    "RecompileInSteadyState": "recompile",
    # engine/spec_decode.py — benign capacity pressure, drafting skipped
    "DraftUnavailable": "draft_unavailable",
}


def classify_error(err: BaseException) -> str:
    """Map a raw exception onto an actionable kind: message sniffing
    first (fault injections mimic real kinds by message), then the
    declarative in-tree class table for marker-less classes."""
    if isinstance(err, AdapterError):
        return err.kind
    msg = str(err).lower()
    if any(m in msg for m in _DEVICE_LOST_MARKERS):
        return "device_lost"
    if any(m in msg for m in _NOT_INSTALLED_MARKERS):
        return "not_installed"
    if any(m in msg for m in _OOM_MARKERS):
        return "oom"
    if any(m in msg for m in _HANG_MARKERS):
        return "hang"
    if any(m in msg for m in _TIMEOUT_MARKERS):
        return "timeout"
    if any(m in msg for m in _AUTH_MARKERS):
        return "auth"
    if any(m in msg for m in _API_MARKERS):
        return "api"
    for cls in type(err).__mro__:
        kind = ERROR_KIND_TABLE.get(cls.__name__)
        if kind is not None:
            return kind
    return "unknown"


def hint_for_kind(kind: str) -> Optional[str]:
    return _KIND_HINTS.get(kind)


def format_error(err: BaseException) -> str:
    """Human-facing one/two-liner (reference src/utils/errors.ts:131-140)."""
    lines = [str(err)]
    hint = getattr(err, "hint", None) or hint_for_kind(classify_error(err))
    if hint:
        lines.append(f"  hint: {hint}")
    cause = getattr(err, "cause", None)
    if cause:
        lines.append(f"  cause: {cause}")
    return "\n".join(lines)
