"""Project config load + validation — `.roundtable/config.json`.

Parity with reference src/utils/config.ts:13-86.
"""

from __future__ import annotations

import json
from pathlib import Path

from .errors import ConfigError
from .types import RoundtableConfig


def config_path(project_root: str | Path) -> Path:
    return Path(project_root) / ".roundtable" / "config.json"


def load_config(project_root: str | Path) -> RoundtableConfig:
    path = config_path(project_root)
    if not path.exists():
        raise ConfigError("No .roundtable/config.json found.",
                          hint='Run "roundtable init" first.')
    try:
        raw = json.loads(path.read_text(encoding="utf-8"))
    except json.JSONDecodeError:
        raise ConfigError("Invalid config.json — could not parse JSON.",
                          hint="Check for syntax errors in .roundtable/config.json")
    validate_config_dict(raw)
    return RoundtableConfig.from_dict(raw)


def save_config(project_root: str | Path, config: RoundtableConfig) -> None:
    path = config_path(project_root)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(config.to_dict(), indent=2), encoding="utf-8")


def validate_config_dict(config: dict) -> None:
    """Field/range validation on the raw dict (reference config.ts:41-86)."""
    if not config.get("version"):
        raise ConfigError("config.json missing 'version' field.")

    knights = config.get("knights")
    if not isinstance(knights, list) or not knights:
        raise ConfigError("config.json must have at least one knight.")
    for knight in knights:
        if not knight.get("name") or not knight.get("adapter"):
            raise ConfigError(
                f"Knight missing required fields (name, adapter): "
                f"{json.dumps(knight)}")
        if not isinstance(knight.get("capabilities"), list):
            raise ConfigError(
                f"Knight \"{knight['name']}\" missing capabilities array.")
        if not isinstance(knight.get("priority"), (int, float)) \
                or isinstance(knight.get("priority"), bool):
            raise ConfigError(
                f"Knight \"{knight['name']}\" missing numeric priority.")

    rules = config.get("rules")
    if not rules:
        raise ConfigError("config.json missing 'rules' section.")
    max_rounds = rules.get("max_rounds")
    if not isinstance(max_rounds, (int, float)) or max_rounds < 1:
        raise ConfigError("rules.max_rounds must be a positive number.")
    threshold = rules.get("consensus_threshold")
    if not isinstance(threshold, (int, float)) or not 0 <= threshold <= 10:
        raise ConfigError("rules.consensus_threshold must be between 0 and 10.")
    timeout = rules.get("timeout_per_turn_seconds")
    if not isinstance(timeout, (int, float)) or timeout < 1:
        raise ConfigError("rules.timeout_per_turn_seconds must be a positive number.")
    # Time-ladder roots (optional — engine/deadlines.py): when present
    # they must be positive numbers, and a round budget must not exceed
    # the discussion budget it nests inside (the tree min()s them anyway,
    # but a config that says otherwise is a mistake worth naming).
    for key in ("discussion_budget_seconds", "round_budget_seconds"):
        value = rules.get(key)
        if value is not None and (
                not isinstance(value, (int, float))
                or isinstance(value, bool) or value <= 0):
            raise ConfigError(f"rules.{key} must be a positive number.")
    disc = rules.get("discussion_budget_seconds")
    rnd = rules.get("round_budget_seconds")
    if disc is not None and rnd is not None and rnd > disc:
        raise ConfigError(
            "rules.round_budget_seconds must not exceed "
            "rules.discussion_budget_seconds (round budgets nest inside "
            "the discussion budget).")

    if not config.get("adapter_config"):
        raise ConfigError("config.json missing 'adapter_config' section.")
