"""Fleet-wide admission signals (ISSUE 17 satellite: the gateway's
admission controller consults the FLEET, not one hard-wired engine).

`FleetSignals` implements the same provider protocol as admission.py's
single-engine `SchedulerSignals`, with the semantics shifted from "is
THIS engine saturated" to "is ANY replica able to serve":

| signal        | N=1 (SchedulerSignals)      | fleet (this class)        |
|---------------|-----------------------------|---------------------------|
| drain_state   | scheduler paused / DRAINING | DRAINING, or EVERY live replica paused |
| dead_reason   | this engine dead            | EVERY replica dead        |
| queue_depth   | this scheduler's queue      | MIN over live replicas    |
| kv_pressure   | this pool in headroom band  | EVERY live pool pressured |
| adapters_busy | this store can't admit      | NO live store can admit   |

A classified refusal with `Retry-After` therefore only happens when
the whole fleet is saturated — one rolling or dead replica never sheds
traffic the rest of the fleet can carry.
"""

from __future__ import annotations

from typing import Optional

from ..engine import deadlines


class FleetSignals:
    """Admission signal provider over a SessionRouter's live fleet."""

    def __init__(self, router):
        self.router = router

    def _live(self):
        return [r for r in self.router.replicas
                if r.name not in self.router._retired
                and r.dead_reason() is None]

    def drain_state(self) -> Optional[str]:
        if deadlines.DRAINING:
            return "draining"
        live = self._live()
        if not live:
            return None   # dead fleet reports through dead_reason()
        reasons = []
        for r in live:
            paused = r.scheduler.paused
            if paused is None:
                return None   # someone is open for business
            reasons.append(paused)
        if any(p == "fleet.drain" for p in reasons):
            return "draining"
        return f"paused:{reasons[0]}"

    def dead_reason(self) -> Optional[str]:
        reasons = [r.dead_reason() for r in self.router.replicas
                   if r.name not in self.router._retired]
        if reasons and all(x is not None for x in reasons):
            return reasons[0]
        return None

    def queue_depth(self) -> int:
        live = self._live()
        if not live:
            return 0
        return min(r.scheduler.describe()["admission"]["queued"]
                   for r in live)

    def kv_pressure(self, headroom: float) -> bool:
        live = self._live()
        if not live:
            return False
        pressured = 0
        paged = 0
        for r in live:
            engine = r.engine
            if getattr(engine, "kv_layout", None) != "paged":
                return False   # a contiguous replica never pressures
            paged += 1
            kv = engine.kv
            floor = int(kv.usable_pages() * headroom)
            if (kv.free_pages() <= floor
                    and getattr(engine, "kv_offload", None) is None):
                pressured += 1
        return paged > 0 and pressured == paged

    def adapters_busy(self, adapters) -> bool:
        live = self._live()
        if not live:
            return False
        for r in live:
            store = getattr(r.engine, "lora", None)
            if store is None or store.can_admit(adapters):
                return False
        return True
