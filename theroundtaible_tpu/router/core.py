"""Router core: per-session replica assignment, cross-replica KV
migration, rolling restarts, and failure containment (ISSUE 17).

Everything through PR 16 scales ONE engine; this module turns those
single-engine capabilities into a serving tier. The pieces it composes
were all built replica-independent on purpose:

- `HostOffloadTier.evacuate()/adopt()` is a pool-independent,
  byte-identical KV manifest — promoted here from spill target to the
  cross-replica transfer fabric (quantized pages move at their stored
  int8/int4 width, so handoff bandwidth is already halved-to-quartered).
- The fsynced `SessionJournal` is a replica-independent session record
  — `replay_turns` re-establishes KV on a survivor when a dead
  replica's pool (and any un-evacuated pages in it) is gone.
- `EngineSupervisor.restart` already quiesces, evacuates, rebuilds
  under the PR-12 budget, and re-adopts — `roll()` wraps it with
  fleet-side drain (idle sessions migrate to peers first) so a planned
  roll loses zero sessions and zero tokens.

Routing signals (cold sessions pick the minimum `load_score`):

| signal              | source                                | weight env |
|---------------------|---------------------------------------|------------|
| queue depth + rows  | scheduler describe()                  | ROUNDTABLE_ROUTER_QUEUE_WEIGHT (1.0) |
| paged page fill     | kv.free_pages()/usable_pages()        | ROUNDTABLE_ROUTER_PAGE_WEIGHT (4.0)  |
| LoRA residency      | LoraStore.can_admit(adapters)         | fixed +2.0 |
| supervisor state    | engine_dead_reason / paused / rolling | inf / +1e3 |

Returning sessions never re-route while their replica lives: the
replica holds their KV (resident or host-spilled), and affinity is
what makes prefix reuse and own-slot reuse work across turns. After a
process restart the assignment map is empty, so affinity falls back to
the journal's `replica=` meta on the session's last committed turn.

Thread model: `_lock` guards the assignment map (gateway submit
threads), `_op_lock` serializes the fleet operations (migrate / roll /
failover). Engine-touching steps additionally take the source engine's
serve lock, same as the supervisor, so a migration can never race an
in-flight dispatch on the pages it is moving.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Optional

from ..engine.session_journal import replay_turns
from ..utils import telemetry

# --- test counters (tests/conftest.py `router` marker guard) ---

_test_crossings = 0


def reset_test_counters() -> None:
    global _test_crossings
    _test_crossings = 0


def boundary_crossings() -> int:
    return _test_crossings


def note_boundary_crossing() -> None:
    """One session's state crossed a replica boundary (migration
    adopt, or failover replay). The conftest guard requires marked
    router tests to move this — a "router test" that never left its
    replica is testing the N=1 path under a multi-replica name."""
    global _test_crossings
    _test_crossings += 1


# --- module-wide active router (fleet_health / status roll-up) ---

_active: Optional["SessionRouter"] = None


def active_router() -> Optional["SessionRouter"]:
    return _active


def set_active_router(router: Optional["SessionRouter"]) -> None:
    global _active
    _active = router


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


class NoLiveReplica(RuntimeError):
    """Every replica is dead or rolling — nothing can serve. The
    gateway's fleet admission sheds `engine_dead` before submits get
    here; this raise is the backstop for direct scheduler_for users."""


class Replica:
    """One data-parallel serving replica: an engine plus its session
    scheduler, under a fleet-unique name (replicas share the engine
    config's `name`, so telemetry needs the extra label)."""

    def __init__(self, name: str, engine, scheduler):
        self.name = name
        self.engine = engine
        self.scheduler = scheduler
        self._bind()

    def _bind(self) -> None:
        self.engine._replica_name = self.name
        self.scheduler.set_replica(self.name)

    @property
    def tier(self):
        return getattr(self.engine, "kv_offload", None)

    def dead_reason(self) -> Optional[str]:
        from ..engine.supervisor import engine_dead_reason
        return engine_dead_reason(self.engine)

    def refresh_engine(self) -> None:
        """Re-sync after a supervised restart swapped the scheduler's
        engine (reattach_engine) — the replica must point at, and
        label, the rebuilt engine."""
        self.engine = self.scheduler.engine
        self._bind()

    def snapshot_sessions(self) -> dict[str, str]:
        try:
            return self.scheduler.snapshot()["sessions"]
        except Exception:  # noqa: BLE001 — advisory
            return {}

    def describe(self) -> dict[str, Any]:
        d = self.scheduler.describe()
        return {
            "name": self.name,
            "engine": getattr(self.engine.cfg, "name", "?"),
            "dead": self.dead_reason(),
            "paused": d["admission"]["paused"],
            "queued": d["admission"]["queued"],
            "active_rows": d["active_rows"],
        }


class SessionRouter:
    """The session→replica map and the fleet operations over it."""

    def __init__(self, replicas: list[Replica], *,
                 journal=None,
                 roll_timeout_s: Optional[float] = None):
        if not replicas:
            raise ValueError("SessionRouter needs at least one replica")
        names = [r.name for r in replicas]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate replica names: {names}")
        self.replicas = list(replicas)
        self.journal = journal
        self.roll_timeout_s = roll_timeout_s \
            if roll_timeout_s is not None \
            else _env_float("ROUNDTABLE_ROUTER_ROLL_TIMEOUT_S", 30.0)
        self.queue_weight = _env_float(
            "ROUNDTABLE_ROUTER_QUEUE_WEIGHT", 1.0)
        self.page_weight = _env_float(
            "ROUNDTABLE_ROUTER_PAGE_WEIGHT", 4.0)
        self._assign: dict[str, str] = {}
        self._rolling: set[str] = set()
        self._retired: set[str] = set()
        self._lock = threading.RLock()
        self._op_lock = threading.RLock()
        self.migrations = 0
        self.failovers = 0
        self.rolls = 0
        from ..engine import supervisor as sup
        sup.on_engine_dead(self._on_engine_dead)
        for r in self.replicas:
            self._publish_sessions(r.name)

    # --- lookup ---

    def _replica(self, name: str) -> Replica:
        for r in self.replicas:
            if r.name == name:
                return r
        raise KeyError(f"no replica named {name!r}")

    def _live(self, *, exclude: Optional[str] = None) -> list[Replica]:
        out = []
        for r in self.replicas:
            if r.name in self._retired or r.name == exclude:
                continue
            if r.name in self._rolling or r.dead_reason() is not None:
                continue
            out.append(r)
        return out

    def _publish_sessions(self, name: str) -> None:
        if name in self._retired:
            return
        n = sum(1 for v in self._assign.values() if v == name)
        telemetry.set_gauge("roundtable_router_sessions", n,
                            replica=name)

    # --- routing ---

    def load_score(self, rep: Replica,
                   adapters: Optional[list] = None) -> float:
        """Cold-session placement score from the replica's EXISTING
        backpressure signals — nothing here samples the device."""
        if rep.dead_reason() is not None:
            return float("inf")
        score = 0.0
        if rep.name in self._rolling:
            score += 1e6
        d = rep.scheduler.describe()
        if d["admission"]["paused"] is not None:
            score += 1e3
        score += self.queue_weight * (d["admission"]["queued"]
                                      + d["active_rows"])
        engine = rep.engine
        if getattr(engine, "kv_layout", None) == "paged":
            kv = engine.kv
            usable = max(kv.usable_pages(), 1)
            score += self.page_weight * (1.0 - kv.free_pages() / usable)
        store = getattr(engine, "lora", None)
        if (store is not None and adapters
                and any(a is not None for a in adapters)
                and not store.can_admit(adapters)):
            score += 2.0
        return score

    def replica_for(self, session: str,
                    adapters: Optional[list] = None) -> Replica:
        """Sticky per-session assignment with journal affinity for
        sessions from before this process, load-scored placement for
        cold ones. Raises NoLiveReplica when nothing can serve.

        Armed telemetry wraps the lookup in a `placement` span
        (ISSUE 20): the gateway calls this under the request trace's
        context, so the span lands in the request's waterfall naming
        the replica that won."""
        if not telemetry.ACTIVE:
            return self._place(session, adapters)
        with telemetry.span("placement", session=session) as sp:
            rep = self._place(session, adapters)
            sp.set_attr("replica", rep.name)
            return rep

    def _place(self, session: str,
               adapters: Optional[list] = None) -> Replica:
        with self._lock:
            name = self._assign.get(session)
            if name is not None and name not in self._retired:
                rep = self._replica(name)
                if (rep.dead_reason() is None
                        and name not in self._rolling):
                    return rep
                # Dead or mid-roll: fall through and re-place. The
                # failover callback normally re-assigns first; this is
                # the race window where a submit beat it.
            if name is None and self.journal is not None:
                last = None
                try:
                    last = self.journal.last_replica(session)
                except Exception:  # noqa: BLE001 — affinity is advisory
                    pass
                if last is not None and last not in self._retired:
                    try:
                        rep = self._replica(last)
                    except KeyError:
                        rep = None
                    if (rep is not None and rep.dead_reason() is None
                            and last not in self._rolling):
                        self._assign[session] = last
                        self._publish_sessions(last)
                        return rep
            live = self._live()
            if not live:
                raise NoLiveReplica(
                    "no live replica (all dead, rolling, or retired)")
            rep = min(live, key=lambda r: self.load_score(r, adapters))
            self._assign[session] = rep.name
            self._publish_sessions(rep.name)
            return rep

    def scheduler_for(self, session: str,
                      adapters: Optional[list] = None):
        return self.replica_for(session, adapters).scheduler

    def signals(self):
        """The gateway admission controller's fleet-wide signal
        provider (the N=1 case is admission.py's SchedulerSignals)."""
        from .signals import FleetSignals
        return FleetSignals(self)

    # --- migration (the host tier as transfer fabric) ---

    def _session_idle(self, rep: Replica, session: str) -> bool:
        state = rep.snapshot_sessions().get(session, "")
        return not (state.startswith("queued")
                    or state.startswith("active"))

    def migrate(self, session: str,
                dst: Optional[str] = None) -> Replica:
        """Move one idle session's KV to another replica:
        `evacuate()` on the source → `adopt()` onto the destination →
        `restore_for` fires transparently on the destination's next
        dispatch. Byte-identical — quantized pages move at stored
        width. Falls back to journal replay when either side has no
        host tier. Raises if the session is mid-turn on the source."""
        with self._op_lock, telemetry.span("migration",
                                           session=session):
            with self._lock:
                src_name = self._assign.get(session)
            src = self._replica(src_name) if src_name else None
            if dst is not None:
                target = self._replica(dst)
                if target.dead_reason() is not None:
                    raise NoLiveReplica(
                        f"migration target {dst!r} is dead")
            else:
                live = self._live(exclude=src_name)
                if not live:
                    raise NoLiveReplica(
                        f"no live migration target for {session!r}")
                target = min(live, key=self.load_score)
            if src is None or src is target:
                self._assign_to(session, target.name, src_name)
                return target
            if src.dead_reason() is not None:
                self._failover_session(session, src, target)
                return target
            if not self._session_idle(src, session):
                raise RuntimeError(
                    f"session {session!r} has in-flight work on "
                    f"{src.name!r} — migrate only idle sessions "
                    "(quiesce or wait for the turn to retire)")
            self._transfer(session, src, target)
            self._assign_to(session, target.name, src_name)
            self.migrations += 1
            telemetry.inc("roundtable_router_migrations_total",
                          replica=target.name)
            note_boundary_crossing()
            telemetry.recorder().record(
                "router_migrate", session=session, src=src.name,
                dst=target.name)
            return target

    def _assign_to(self, session: str, name: str,
                   old: Optional[str]) -> None:
        with self._lock:
            self._assign[session] = name
            self._publish_sessions(name)
            if old is not None and old != name:
                self._publish_sessions(old)

    def _transfer(self, session: str, src: Replica,
                  dst: Replica) -> None:
        """The KV handoff itself. Serialized against the source
        engine's dispatches exactly like the supervisor's cycle: the
        serve lock is the one mutex every generate path holds."""
        if src.tier is not None and dst.tier is not None:
            lock = getattr(src.engine, "_serve_lock", None)
            held = False
            if lock is not None:
                if not lock.acquire(timeout=self.roll_timeout_s):
                    raise TimeoutError(
                        f"serve lock on {src.name!r} never freed — "
                        f"cannot migrate {session!r}")
                held = True
            try:
                src.tier.evacuate(sessions=[session])
                adopted = dst.tier.adopt(src.tier, sessions=[session])
            finally:
                if held:
                    lock.release()
            if session in adopted:
                return
            # evacuate() ran but adopt() refused (no host-resident
            # record — e.g. the session held no KV). Fall through to
            # replay, which also covers the no-KV case by rebuilding
            # from the journal.
        if self.journal is None:
            raise RuntimeError(
                f"cannot migrate {session!r}: no host tier on both "
                "sides and no journal to replay from")
        replay_turns(self.journal, session, dst.scheduler.submit)

    # --- rolling restart ---

    def roll(self, name: Optional[str] = None) -> list[dict]:
        """Roll one replica (or, with no name, the whole fleet one
        replica at a time): drain it — admission closed, in-flight
        turns finish, idle sessions migrate to peers — supervise the
        rebuild under the PR-12 restart budget, re-admit. Sessions
        that could not move ride the supervisor's own
        evacuate→rebuild→adopt cycle inside the replica. Streams
        crossing the roll reconnect through the PR-16 resume ladder
        untouched."""
        targets = [name] if name is not None \
            else [r.name for r in self.replicas
                  if r.name not in self._retired]
        return [self._roll_one(t) for t in targets]

    def _roll_one(self, name: str) -> dict:
        rep = self._replica(name)
        with self._op_lock, telemetry.span("roll", replica=name):
            report: dict[str, Any] = {"replica": name, "op": "roll"}
            rep.scheduler.pause_admission("router.roll")
            with self._lock:
                self._rolling.add(name)
            try:
                report["quiesced"] = rep.scheduler.quiesce(
                    self.roll_timeout_s)
                report["migrated"] = self._evacuate_sessions(rep)
                from ..engine.supervisor import supervisor, EngineDead
                try:
                    sup_report = supervisor().restart(
                        rep.engine, reason="roll",
                        scheduler=rep.scheduler)
                    report["ok"] = bool(sup_report.get("ok"))
                    report["restart"] = sup_report.get("restart")
                except EngineDead as e:
                    # Budget exhausted mid-roll: the death callback
                    # already moved this replica's sessions to
                    # survivors; report the truth.
                    report["ok"] = False
                    report["dead"] = str(e)[:200]
                rep.refresh_engine()
            finally:
                with self._lock:
                    self._rolling.discard(name)
                rep.scheduler.reopen_admission()
            self.rolls += 1
            telemetry.inc("roundtable_router_rolls_total",
                          replica=name)
            telemetry.recorder().record("router_roll", replica=name,
                                        ok=report.get("ok"))
            return report

    def _evacuate_sessions(self, rep: Replica) -> int:
        """Migrate every idle session assigned to `rep` onto live
        peers. Sessions that refuse to move (or have nowhere to go)
        stay — the supervisor's in-replica evacuation covers them."""
        with self._lock:
            mine = [s for s, n in self._assign.items()
                    if n == rep.name]
        moved = 0
        for session in mine:
            live = self._live(exclude=rep.name)
            if not live:
                break
            try:
                self.migrate(session,
                             dst=min(live, key=self.load_score).name)
                moved += 1
            except Exception:  # noqa: BLE001 — stay-behind is safe
                pass
        return moved

    # --- failure containment ---

    def _on_engine_dead(self, engine, reason: str, kind: str) -> None:
        """Supervisor death callback: an unplanned dead replica's
        journaled sessions migrate to survivors. Host-resident spill
        records survive the lost device and adopt() straight across;
        everything else re-establishes KV by journal replay."""
        dead_name = getattr(engine, "_replica_name", None)
        rep = None
        for r in self.replicas:
            if r.engine is engine or (dead_name is not None
                                      and r.name == dead_name):
                rep = r
                break
        if rep is None or rep.name in self._retired:
            return
        with self._op_lock:
            telemetry.recorder().record(
                "router_replica_dead", replica=rep.name,
                reason=reason[:200], failure_kind=kind)
            with self._lock:
                sessions = [s for s, n in self._assign.items()
                            if n == rep.name]
            # Journal-only sessions (a pre-restart process served
            # them) also belong to this replica — fold them in so
            # their next turn finds KV on a survivor.
            if self.journal is not None:
                try:
                    for s in self.journal.sessions():
                        if (s not in sessions
                                and self.journal.last_replica(s)
                                == rep.name):
                            sessions.append(s)
                except Exception:  # noqa: BLE001 — advisory
                    pass
            for session in sessions:
                live = self._live(exclude=rep.name)
                if not live:
                    # Whole fleet down: leave assignments; admission
                    # sheds engine_dead with Retry-After until a
                    # replica returns.
                    break
                dst = min(live, key=self.load_score)
                try:
                    self._failover_session(session, rep, dst)
                except Exception as e:  # noqa: BLE001 — containment
                    telemetry.recorder().record(
                        "router_failover_error", session=session,
                        replica=rep.name, error=str(e)[:200])

    def _failover_session(self, session: str, dead: Replica,
                          dst: Replica) -> None:
        with telemetry.span("failover", session=session,
                            src=dead.name, dst=dst.name) as sp:
            adopted: list[str] = []
            if dead.tier is not None and dst.tier is not None:
                try:
                    # NEVER spill from a dead engine — only records
                    # that were already fully host-resident cross here.
                    adopted = dst.tier.adopt(dead.tier,
                                             sessions=[session])
                except Exception:  # noqa: BLE001 — fall back to replay
                    adopted = []
            if session not in adopted:
                if self.journal is None:
                    raise RuntimeError(
                        f"session {session!r} lost with {dead.name!r}: "
                        "no host-resident KV and no journal to replay")
                replay_turns(self.journal, session,
                             dst.scheduler.submit)
            with self._lock:
                self._assign[session] = dst.name
                self._publish_sessions(dst.name)
                self._publish_sessions(dead.name)
            self.failovers += 1
            telemetry.inc("roundtable_router_failovers_total",
                          replica=dead.name)
            note_boundary_crossing()
            sp.set_attr("via", "adopt" if adopted else "replay")
            telemetry.recorder().record(
                "router_failover", session=session, src=dead.name,
                dst=dst.name, via="adopt" if adopted else "replay")

    # --- retirement (RT-GAUGE-LEAK: series die with the replica) ---

    def retire(self, name: str) -> None:
        """Drop a replica from the fleet and remove every telemetry
        series labeled with it — a long-lived router must not keep one
        dead series per replica ever rolled out."""
        rep = self._replica(name)
        with self._op_lock:
            with self._lock:
                for s, n in list(self._assign.items()):
                    if n == name:
                        del self._assign[s]
                self._retired.add(name)
                self._rolling.discard(name)
            ename = getattr(rep.engine.cfg, "name", "engine")
            tname = rep.scheduler._tname
            telemetry.remove_gauge("roundtable_router_sessions",
                                   replica=name)
            telemetry.remove_gauge("roundtable_engine_dead",
                                   engine=ename, replica=name)
            telemetry.remove_gauge("roundtable_sched_queue_depth",
                                   engine=tname, replica=name)
            telemetry.remove_gauge("roundtable_sched_active_rows",
                                   engine=tname, replica=name)
            telemetry.recorder().record("router_retire", replica=name)

    # --- lifecycle / observability ---

    def describe(self) -> dict[str, Any]:
        with self._lock:
            assigned = dict(self._assign)
            rolling = sorted(self._rolling)
            retired = sorted(self._retired)
        per = {}
        for r in self.replicas:
            if r.name in retired:
                continue
            d = r.describe()
            d["sessions"] = sum(1 for v in assigned.values()
                                if v == r.name)
            per[r.name] = d
        return {
            "replicas": per,
            "sessions": len(assigned),
            "rolling": rolling,
            "retired": retired,
            "migrations": self.migrations,
            "failovers": self.failovers,
            "rolls": self.rolls,
        }

    def close(self) -> None:
        from ..engine import supervisor as sup
        sup.remove_death_callback(self._on_engine_dead)
        if active_router() is self:
            set_active_router(None)


def build_replicas(engine, n: int, *, journal=None,
                   **scheduler_opts) -> list[Replica]:
    """Build an N-replica fleet around an existing engine: replica
    `r0` wraps the given engine and its (acquired) scheduler; replicas
    `r1..` are fresh clones from the same `_engine_config` rebuild
    recipe — the identical recipe the supervisor uses, so a rolled or
    replaced replica is indistinguishable from a built one. All
    schedulers share one journal: turn numbering (and the gateway's
    resume ladder) stays global across the fleet."""
    if n < 1:
        raise ValueError(f"need at least 1 replica, got {n}")
    cfg = getattr(engine, "_engine_config", None)
    if n > 1 and cfg is None:
        raise ValueError(
            "multi-replica serving needs a rebuild recipe "
            "(engine._engine_config) — construct the engine via "
            "from_config/get_engine")
    from ..engine.scheduler import acquire_scheduler
    replicas = []
    for i in range(n):
        eng = engine if i == 0 \
            else type(engine).from_config(dict(cfg))
        sched, created = acquire_scheduler(eng, **scheduler_opts)
        if journal is not None and sched.journal is not journal:
            sched.attach_journal(journal)
        rep = Replica(f"r{i}", eng, sched)
        # Whether THIS build created the scheduler — the caller closes
        # only those (replica 0 may wrap a pre-existing scheduler that
        # other sessions still share).
        rep.owned_scheduler = created
        replicas.append(rep)
    return replicas
