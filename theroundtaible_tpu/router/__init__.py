"""Session-routing tier: one gateway front door, N data-parallel
engine replicas (ISSUE 17).

The router owns the session→replica map and the three fleet
operations built on it — cold-session placement by live load score,
cross-replica KV migration over the host-RAM tier, and zero-loss
rolling restarts — while `FleetSignals` feeds the gateway's admission
controller fleet-wide backpressure instead of one engine's.
"""

from .core import (  # noqa: F401
    NoLiveReplica,
    Replica,
    SessionRouter,
    active_router,
    boundary_crossings,
    build_replicas,
    note_boundary_crossing,
    reset_test_counters,
    set_active_router,
)
from .signals import FleetSignals  # noqa: F401
