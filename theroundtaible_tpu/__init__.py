"""TheRoundtAIble-TPU — a TPU-native multi-LLM consensus framework.

A ground-up reimplementation of the capabilities of polatinos/TheRoundtAIble
(reference: /root/reference, TypeScript CLI orchestrating external LLM CLIs/APIs),
re-designed TPU-first:

- ``theroundtaible_tpu.core``      — orchestrator, consensus engine, config, types.
  Pure host Python, no JAX dependency; byte-compatible ``.roundtable/`` state.
- ``theroundtaible_tpu.adapters``  — the "knight" boundary (reference
  src/adapters/base.ts:10-29). Cloud/CLI adapters kept for drop-in parity; the
  new ``tpu-llm`` adapter serves knights from an in-tree JAX/XLA engine.
- ``theroundtaible_tpu.engine``    — JAX/XLA/Pallas inference engine: sharded
  prefill + decode over a jax.sharding.Mesh, per-knight persistent KV slots,
  ring-attention long-context prefill.
- ``theroundtaible_tpu.commands``  — CLI commands (init/discuss/summon/status/
  list/chronicle/decrees/manifest/apply/code-red).
"""

__version__ = "0.1.0"
