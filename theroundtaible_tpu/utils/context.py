"""Project context builder — chronicle + git + key files + source files.

Parity with reference src/utils/context.ts:1-187: recursive walk with ignore
patterns, key-file reader (2KB each, max 5), source reader (whitelist
extensions, exclude lockfiles/.env, max 30 files, char-budget truncation with
an overflow warning surfaced through a callback).
"""

from __future__ import annotations

import os
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Optional

from ..core.types import RoundtableConfig
from .chronicle import read_chronicle
from .git import get_git_branch, get_git_diff, get_recent_commits

KEY_FILE_PATTERNS = ("package.json", "tsconfig.json", "README.md", "CLAUDE.md",
                     "pyproject.toml", "setup.py")
KEY_FILE_CHAR_LIMIT = 2000
MAX_KEY_FILES = 5

SOURCE_EXTENSIONS = (".ts", ".tsx", ".js", ".jsx", ".py", ".rs", ".go",
                     ".java", ".json", ".c", ".cc", ".cpp", ".h")
SOURCE_EXCLUDE = ("package-lock.json", "yarn.lock", "pnpm-lock.yaml",
                  "bun.lockb", ".env", ".env.local")
MAX_SOURCE_FILES = 30
DEFAULT_MAX_SOURCE_CHARS = 200_000


def get_project_files(root_dir: str | Path, ignore_patterns: list[str]
                      ) -> list[str]:
    """Recursive walk honoring ignore patterns (reference context.ts:12-46)."""
    root_dir = Path(root_dir)
    files: list[str] = []

    def ignored(rel_path: str, name: str) -> bool:
        return any(
            rel_path.startswith(p) or name == p or f"/{p}/" in rel_path
            for p in ignore_patterns
        )

    for dirpath, dirnames, filenames in os.walk(root_dir):
        rel_dir = os.path.relpath(dirpath, root_dir)
        # prune ignored directories in place so walk skips them
        dirnames[:] = [
            d for d in dirnames
            if not ignored(os.path.normpath(os.path.join(rel_dir, d))
                           if rel_dir != "." else d, d)
        ]
        for fname in filenames:
            rel = os.path.normpath(os.path.join(rel_dir, fname)) \
                if rel_dir != "." else fname
            if not ignored(rel, fname):
                files.append(rel)
    return files


def read_key_files(root_dir: str | Path, files: list[str]) -> str:
    """Common config/readme files, 2KB each, max 5 (reference context.ts:52-81)."""
    key_files = [f for f in files
                 if any(f.endswith(p) for p in KEY_FILE_PATTERNS)]
    contents: list[str] = []
    for file in key_files[:MAX_KEY_FILES]:
        try:
            content = (Path(root_dir) / file).read_text(
                encoding="utf-8", errors="replace")
        except OSError:
            continue
        if len(content) > KEY_FILE_CHAR_LIMIT:
            content = content[:KEY_FILE_CHAR_LIMIT] + "\n...(truncated)"
        contents.append(f"### {file}\n```\n{content}\n```")
    return "\n\n".join(contents)


def read_source_files(
    project_root: str | Path, ignore_patterns: list[str],
    max_chars: int = 50_000,
    on_overflow: Optional[Callable[[int, int], None]] = None,
) -> str:
    """Source whitelist read under a char budget (reference context.ts:108-149).

    ``on_overflow(skipped_count, max_chars)`` fires when files were dropped.
    """
    files = get_project_files(project_root, ignore_patterns)
    source_files = [
        f for f in files
        if any(f.endswith(ext) for ext in SOURCE_EXTENSIONS)
        and not any(f.endswith(ex) for ex in SOURCE_EXCLUDE)
    ][:MAX_SOURCE_FILES]

    contents: list[str] = []
    total = 0
    overflowed = 0  # files skipped entirely or cut mid-file by the budget
    for file in source_files:
        if total >= max_chars:
            overflowed += 1
            continue
        try:
            content = (Path(project_root) / file).read_text(
                encoding="utf-8", errors="replace")
        except OSError:
            continue
        truncated = content[:max_chars - total]
        if len(truncated) < len(content):
            overflowed += 1
            contents.append(f"### {file}\n```\n{truncated}\n...(truncated)\n```")
        else:
            contents.append(f"### {file}\n```\n{truncated}\n```")
        total += len(truncated)
    if overflowed and on_overflow:
        on_overflow(overflowed, max_chars)
    return "\n\n".join(contents)


@dataclass
class ProjectContext:
    chronicle: str = ""
    git_branch: Optional[str] = None
    git_diff: Optional[str] = None
    recent_commits: Optional[str] = None
    project_files: list[str] = field(default_factory=list)
    key_file_contents: str = ""
    source_file_contents: str = ""


def build_context(
    project_root: str | Path, config: RoundtableConfig,
    read_source_code: bool = False,
    max_source_chars: int = DEFAULT_MAX_SOURCE_CHARS,
    on_overflow: Optional[Callable[[int, int], None]] = None,
) -> ProjectContext:
    """Parallel-gather chronicle + git + file walk (reference context.ts:156-187)."""
    root = str(project_root)
    with ThreadPoolExecutor(max_workers=5) as pool:
        chronicle_f = pool.submit(read_chronicle, root, config.chronicle)
        branch_f = pool.submit(get_git_branch, root)
        diff_f = pool.submit(get_git_diff, root)
        commits_f = pool.submit(get_recent_commits, 5, root)
        files_f = pool.submit(get_project_files, root, config.rules.ignore)
        chronicle = chronicle_f.result()
        branch = branch_f.result()
        diff = diff_f.result()
        commits = commits_f.result()
        files = files_f.result()

    key_file_contents = read_key_files(root, files)
    source_file_contents = ""
    if read_source_code:
        source_file_contents = read_source_files(
            root, config.rules.ignore, max_source_chars, on_overflow)

    return ProjectContext(
        chronicle=chronicle,
        git_branch=branch,
        git_diff=diff,
        recent_commits=commits,
        project_files=files,
        key_file_contents=key_file_contents,
        source_file_contents=source_file_contents,
    )
