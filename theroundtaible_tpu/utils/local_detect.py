"""Detection of local OpenAI-compatible inference servers.

Parity with reference src/utils/local-detect.ts:1-134: probe LM Studio
(localhost:1234) and Ollama (localhost:11434) `/v1/models` in parallel,
filter non-chat models, prettify ids, with an `ollama list` CLI fallback.
The TPU build adds detection of an in-process `tpu-llm` engine (JAX devices
present) so `init` can seat TPU knights automatically.
"""

from __future__ import annotations

import json
import re
import subprocess
import urllib.request
from concurrent.futures import ThreadPoolExecutor
from dataclasses import dataclass

LM_STUDIO_ENDPOINT = "http://localhost:1234"
OLLAMA_ENDPOINT = "http://localhost:11434"
PROBE_TIMEOUT_SECONDS = 3

# Models that are not chat models (reference local-detect.ts:35-38).
_NON_CHAT_RE = re.compile(r"embed|embedding|tts|whisper|rerank|bge-|e5-",
                          re.IGNORECASE)


@dataclass
class LocalModel:
    id: str
    name: str       # prettified display name
    endpoint: str
    source: str     # "Ollama" | "LM Studio" | "tpu"


def prettify_model_id(model_id: str) -> str:
    """qwen/qwen2.5-coder-14b → Qwen2.5 Coder 14b (reference :23-30)."""
    base = model_id.split("/")[-1]
    base = re.sub(r":latest$", "", base)
    words = re.split(r"[-_]", base)
    return " ".join(w.capitalize() if w and w[0].isalpha() else w
                    for w in words if w)


def _probe_endpoint(endpoint: str, source: str) -> list[LocalModel]:
    try:
        with urllib.request.urlopen(f"{endpoint}/v1/models",
                                    timeout=PROBE_TIMEOUT_SECONDS) as resp:
            data = json.loads(resp.read().decode("utf-8"))
    except Exception:
        return []
    models = []
    for m in data.get("data", []):
        mid = m.get("id", "")
        if not mid or _NON_CHAT_RE.search(mid):
            continue
        models.append(LocalModel(id=mid, name=prettify_model_id(mid),
                                 endpoint=endpoint, source=source))
    return models


def _ollama_cli_fallback() -> list[LocalModel]:
    """`ollama list` when the HTTP endpoint is down (reference :77-97)."""
    try:
        proc = subprocess.run(["ollama", "list"], capture_output=True,
                              text=True, timeout=PROBE_TIMEOUT_SECONDS)
    except (OSError, subprocess.TimeoutExpired):
        return []
    if proc.returncode != 0:
        return []
    models = []
    for line in proc.stdout.splitlines()[1:]:  # skip header row
        parts = line.split()
        if not parts:
            continue
        mid = parts[0]
        if _NON_CHAT_RE.search(mid):
            continue
        models.append(LocalModel(id=mid, name=prettify_model_id(mid),
                                 endpoint=OLLAMA_ENDPOINT, source="Ollama"))
    return models


def detect_tpu_engine(timeout_s: float = 5.0) -> list[LocalModel]:
    """Report the in-tree TPU engine as a seat-able backend when JAX sees
    an accelerator (no reference counterpart — TPU-build addition).

    jax.devices() can block indefinitely when another process holds the TPU
    client, so the probe runs in a daemon thread under a timeout; on timeout
    or CPU-only hosts nothing is reported. ROUNDTABLE_DISABLE_TPU_DETECT=1
    skips the probe entirely (tests, CI)."""
    import os
    import threading

    if os.environ.get("ROUNDTABLE_DISABLE_TPU_DETECT"):
        return []

    result: list[LocalModel] = []

    def probe() -> None:
        try:
            import jax
            devices = jax.devices()
        except Exception:
            return
        if not devices:
            return
        platform = getattr(devices[0], "platform", "cpu")
        if platform == "cpu" and not os.environ.get(
                "ROUNDTABLE_FORCE_TPU_DETECT"):
            return  # the engine runs on CPU too, but don't auto-seat there
        kind = getattr(devices[0], "device_kind", "device")
        result.append(LocalModel(
            id="tpu-llm",
            name=f"In-tree TPU engine ({kind} ×{len(devices)})",
            endpoint="in-process", source="tpu"))

    t = threading.Thread(target=probe, daemon=True)
    t.start()
    t.join(timeout=timeout_s)
    return list(result)


def detect_local_models(include_tpu: bool = True) -> list[LocalModel]:
    """Parallel probe of both endpoints (reference :103-134)."""
    with ThreadPoolExecutor(max_workers=3) as pool:
        lm_f = pool.submit(_probe_endpoint, LM_STUDIO_ENDPOINT, "LM Studio")
        ol_f = pool.submit(_probe_endpoint, OLLAMA_ENDPOINT, "Ollama")
        tpu_f = pool.submit(detect_tpu_engine) if include_tpu else None
        lm = lm_f.result()
        ol = ol_f.result()
        tpu = tpu_f.result() if tpu_f else []
    if not ol:
        ol = _ollama_cli_fallback()
    return tpu + lm + ol
