"""Secure API key storage — `~/.theroundtaible/keys.json`, chmod 600.

Parity with reference src/utils/keys.ts:1-69. Lookup order: env var first,
then keystore.
"""

from __future__ import annotations

import json
import os
from pathlib import Path
from typing import Optional


def keys_dir() -> Path:
    return Path(os.environ.get("ROUNDTABLE_KEYS_DIR",
                               Path.home() / ".theroundtaible"))


def keys_file() -> Path:
    return keys_dir() / "keys.json"


def load_keys() -> dict[str, str]:
    f = keys_file()
    if not f.exists():
        return {}
    try:
        parsed = json.loads(f.read_text(encoding="utf-8"))
        return {k: v for k, v in parsed.items() if isinstance(v, str)}
    except (json.JSONDecodeError, OSError):
        return {}


def save_key(name: str, value: str) -> None:
    d = keys_dir()
    d.mkdir(parents=True, exist_ok=True)
    keys = load_keys()
    keys[name] = value
    # Create with 0600 atomically — never let the secret exist world-readable,
    # even for an instant (os.open mode applies at creation, unlike chmod-after).
    fd = os.open(keys_file(), os.O_WRONLY | os.O_CREAT | os.O_TRUNC, 0o600)
    with os.fdopen(fd, "w", encoding="utf-8") as fh:
        fh.write(json.dumps(keys, indent=2))
    try:
        os.chmod(keys_file(), 0o600)  # tighten pre-existing files too
        os.chmod(d, 0o700)
    except OSError:
        pass  # non-POSIX filesystems


def get_key(env_var: str) -> Optional[str]:
    """Env var wins, else keystore (reference keys.ts:54-62)."""
    from_env = os.environ.get(env_var)
    if from_env:
        return from_env
    return load_keys().get(env_var)
