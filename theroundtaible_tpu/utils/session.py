"""Session store — `.roundtable/sessions/<date>-<time>-<slug>/`.

Byte-compatible with reference src/utils/session.ts:21-212: each session dir
holds topic.md, discussion.md (full rewrite per round), decisions.md (terminal
states), status.json (read-merge-write).
"""

from __future__ import annotations

import json
import os
import re
import tempfile
from dataclasses import dataclass
from datetime import datetime, timezone
from pathlib import Path
from typing import Any, Optional

from ..core.types import RoundEntry, SessionStatus, format_score

SESSIONS_SUBDIR = Path(".roundtable") / "sessions"


def atomic_write_text(path: str | Path, text: str) -> None:
    """Write-to-temp + os.replace so a crash mid-write can never leave a
    truncated file — crash resume (`discuss --continue`) reads these files,
    so in-place write_text would undercut the very thing it enables."""
    path = Path(path)
    fd, tmp = tempfile.mkstemp(dir=path.parent, prefix=path.name + ".")
    try:
        with os.fdopen(fd, "w", encoding="utf-8") as f:
            f.write(text)
        os.replace(tmp, path)
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def now_iso() -> str:
    """UTC ISO-8601 with milliseconds + Z, matching JS Date.toISOString()."""
    now = datetime.now(timezone.utc)
    return now.strftime("%Y-%m-%dT%H:%M:%S.") + f"{now.microsecond // 1000:03d}Z"


def slugify(text: str, max_len: int = 50) -> str:
    """Topic → folder slug (reference session.ts:9-15)."""
    slug = re.sub(r"[^a-z0-9]+", "-", text.lower()).strip("-")
    return slug[:max_len]


def create_session(project_root: str | Path, topic: str) -> Path:
    """Create a session dir with topic.md + initial status.json
    (reference session.ts:21-57)."""
    now = datetime.now(timezone.utc)
    name = f"{now.strftime('%Y-%m-%d')}-{now.strftime('%H%M')}-{slugify(topic)}"
    session_path = Path(project_root) / SESSIONS_SUBDIR / name
    session_path.mkdir(parents=True, exist_ok=True)

    (session_path / "topic.md").write_text(f"# Topic\n\n{topic}\n", encoding="utf-8")

    status = SessionStatus(
        phase="discussing",
        current_knight=None,
        round=0,
        consensus_reached=False,
        started_at=now_iso(),
        updated_at=now_iso(),
    )
    _write_status(session_path, status)
    return session_path


def _write_status(session_path: Path, status: SessionStatus) -> None:
    atomic_write_text(session_path / "status.json",
                      json.dumps(status.to_dict(), indent=2))


def write_transcript(session_path: str | Path,
                     rounds: list[RoundEntry]) -> None:
    """Machine-readable twin of discussion.md, rewritten per round.

    This is what makes crash resume (`discuss --continue`) possible — the
    reference persists only display markdown, so a dead process loses the
    structured transcript (TODO.md:179 future work). Schema: a JSON list
    of RoundEntry dicts with the consensus block inlined."""
    payload = []
    for e in rounds:
        payload.append({
            "knight": e.knight,
            "round": e.round,
            "response": e.response,
            "timestamp": e.timestamp,
            "consensus": e.consensus.to_dict() if e.consensus else None,
        })
    atomic_write_text(Path(session_path) / "transcript.json",
                      json.dumps(payload, indent=1))


def read_transcript(session_path: str | Path) -> list[RoundEntry]:
    """Rebuild RoundEntries from transcript.json (empty if absent)."""
    from ..core.types import ConsensusBlock

    path = Path(session_path) / "transcript.json"
    if not path.exists():
        return []
    try:
        payload = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return []
    entries = []
    for d in payload:
        block = None
        if d.get("consensus"):
            c = d["consensus"]
            block = ConsensusBlock(
                knight=c.get("knight", d.get("knight", "")),
                round=int(c.get("round", d.get("round", 1))),
                consensus_score=float(c.get("consensus_score", 0)),
                agrees_with=list(c.get("agrees_with", [])),
                pending_issues=list(c.get("pending_issues", [])),
                proposal=c.get("proposal"),
                files_to_modify=list(c.get("files_to_modify", [])),
                file_requests=list(c.get("file_requests", [])),
                verify_commands=list(c.get("verify_commands", [])),
            )
        entries.append(RoundEntry(
            knight=d.get("knight", ""), round=int(d.get("round", 1)),
            response=d.get("response", ""),
            timestamp=d.get("timestamp", ""), consensus=block))
    return entries


def write_discussion(session_path: str | Path, rounds: list[RoundEntry]) -> None:
    """Full rewrite of discussion.md (reference session.ts:62-89)."""
    lines: list[str] = ["# Discussion\n"]
    for entry in rounds:
        lines.append(f"## Round {entry.round} — {entry.knight}")
        lines.append(f"*{entry.timestamp}*\n")
        lines.append(entry.response)
        lines.append("")
        if entry.consensus:
            c = entry.consensus
            lines.append("**Consensus:**")
            lines.append(f"- Score: {format_score(c.consensus_score)}/10")
            if c.agrees_with:
                lines.append(f"- Agrees with: {', '.join(c.agrees_with)}")
            if c.pending_issues:
                lines.append(f"- Pending: {', '.join(c.pending_issues)}")
        lines.append("\n---\n")
    (Path(session_path) / "discussion.md").write_text(
        "\n".join(lines), encoding="utf-8"
    )


def write_decisions(session_path: str | Path, topic: str, decision: str,
                    rounds: list[RoundEntry]) -> None:
    """Write final decisions.md (reference session.ts:94-115)."""
    knights = list(dict.fromkeys(r.knight for r in rounds))
    # entries are per knight-turn; the header counts discussion rounds
    num_rounds = len({r.round for r in rounds})
    lines = [
        "# Decision\n",
        f"**Topic:** {topic}",
        f"**Knights:** {', '.join(knights)}",
        f"**Rounds:** {num_rounds}",
        f"**Date:** {datetime.now(timezone.utc).strftime('%Y-%m-%d')}",
        "",
        "---\n",
        decision,
        "",
    ]
    (Path(session_path) / "decisions.md").write_text(
        "\n".join(lines), encoding="utf-8"
    )


def update_status(session_path: str | Path, **updates: Any) -> None:
    """Read-merge-write status.json (reference session.ts:120-149).

    Keyword names match SessionStatus fields; updated_at always refreshed.
    """
    session_path = Path(session_path)
    status_path = session_path / "status.json"
    if status_path.exists():
        try:
            current = json.loads(status_path.read_text(encoding="utf-8"))
        except (json.JSONDecodeError, OSError):
            current = {}
    else:
        current = {
            "phase": "discussing",
            "current_knight": None,
            "round": 0,
            "consensus_reached": False,
            "started_at": now_iso(),
        }
    current.update({k: v for k, v in updates.items() if v is not ...})
    current["updated_at"] = now_iso()
    atomic_write_text(status_path, json.dumps(current, indent=2))


def read_status(session_path: str | Path) -> Optional[SessionStatus]:
    status_path = Path(session_path) / "status.json"
    if not status_path.exists():
        return None
    try:
        return SessionStatus.from_dict(
            json.loads(status_path.read_text(encoding="utf-8")))
    except (json.JSONDecodeError, OSError):
        return None


@dataclass
class SessionInfo:
    name: str
    path: str
    status: Optional[SessionStatus]
    topic: Optional[str]


_TOPIC_RE = re.compile(r"^# Topic\s*\n\n(.+)", re.MULTILINE)


def list_sessions(project_root: str | Path) -> list[SessionInfo]:
    """All sessions newest-first via name sort (reference session.ts:176-204)."""
    sessions_dir = Path(project_root) / SESSIONS_SUBDIR
    if not sessions_dir.exists():
        return []
    sessions: list[SessionInfo] = []
    for entry in sessions_dir.iterdir():
        if not entry.is_dir():
            continue
        topic: Optional[str] = None
        topic_path = entry / "topic.md"
        if topic_path.exists():
            raw = topic_path.read_text(encoding="utf-8")
            m = _TOPIC_RE.search(raw)
            topic = (m.group(1).strip() if m else raw.strip()) or None
        sessions.append(SessionInfo(
            name=entry.name, path=str(entry),
            status=read_status(entry), topic=topic,
        ))
    sessions.sort(key=lambda s: s.name, reverse=True)
    return sessions


def find_latest_session(project_root: str | Path) -> Optional[SessionInfo]:
    sessions = list_sessions(project_root)
    return sessions[0] if sessions else None
