"""Sandboxed read-only execution of knight `verify_commands`.

Parity with reference src/utils/verify.ts:1-174: a 14-command whitelist,
forbidden-pattern and forbidden-command checks, redirect checks after
stripping safe stderr redirects, escaped-pipe-aware pipe-segment validation,
sensitive-env stripping, `bash -c` execution with 5s timeout / 1MB buffer /
5000-char output truncation, max 4 commands per invocation.
"""

from __future__ import annotations

import os
import re
import subprocess
from typing import Callable, Optional

WHITELISTED_COMMANDS = frozenset({
    "ls", "cat", "head", "tail", "grep", "find", "wc",
    "file", "stat", "sort", "uniq", "basename", "dirname",
})

# (pattern, human label) — command chaining/substitution/write hazards.
# Tighter than the reference's list (verify.ts:18-28): we additionally reject
# lone '&' (background chaining), newlines/CR (bash command separators), and
# find's file-writing actions (-fprint/-fprintf/-fls) — all of which slip
# through the reference's checks but reach `bash -c`.
_FORBIDDEN_PATTERNS: list[tuple[re.Pattern, str]] = [
    (re.compile(r";"), ";"),
    (re.compile(r"[\n\r]"), "newline"),
    (re.compile(r"`"), "`"),
    (re.compile(r"\$\("), r"\$\("),
    (re.compile(r"\$\{"), r"\$\{"),
    (re.compile(r"&"), "&"),
    (re.compile(r"\|\|"), r"\|\|"),
    (re.compile(r"-exec\b"), r"-exec\b"),
    (re.compile(r"-execdir\b"), r"-execdir\b"),
    (re.compile(r"-delete\b"), r"-delete\b"),
    (re.compile(r"-ok\b"), r"-ok\b"),
    (re.compile(r"-okdir\b"), r"-okdir\b"),
    (re.compile(r"-fprint\w*\b"), r"-fprint*"),
    (re.compile(r"-fls\b"), r"-fls\b"),
]

FORBIDDEN_COMMANDS = frozenset({
    "rm", "mv", "cp", "chmod", "chown", "chgrp",
    "curl", "wget", "eval", "source", "node", "python",
    "python3", "ruby", "perl", "php", "bash", "sh", "zsh",
    "npm", "npx", "yarn", "pnpm", "pip", "apt", "brew",
    "dd", "mkfs", "mount", "umount", "kill", "pkill",
    "ssh", "scp", "rsync", "nc", "ncat", "telnet",
})

SENSITIVE_ENV_KEYS = (
    "OPENAI_API_KEY", "ANTHROPIC_API_KEY", "GEMINI_API_KEY",
    "GOOGLE_API_KEY", "AWS_SECRET_ACCESS_KEY", "AWS_ACCESS_KEY_ID",
    "GITHUB_TOKEN", "GH_TOKEN", "NPM_TOKEN", "CLAUDECODE",
)

MAX_COMMANDS = 4
TIMEOUT_SECONDS = 5
MAX_BUFFER_BYTES = 1024 * 1024
OUTPUT_TRUNCATE_CHARS = 5000

_ESCAPED_PIPE_SENTINEL = "\x00ESCAPED_PIPE\x00"


def validate_command(command: str) -> Optional[str]:
    """Return None if the command is allowed, else a rejection reason
    (reference verify.ts:55-101)."""
    trimmed = command.strip()
    if not trimmed:
        return "empty command"

    # Strip safe stderr redirects (2>/dev/null, 2>&1) BEFORE all pattern
    # checks so the '&' in 2>&1 and the '>' in both are not misflagged.
    without_safe = re.sub(r"2>\s*/dev/null", "", trimmed)
    without_safe = without_safe.replace("2>&1", "")

    for pattern, label in _FORBIDDEN_PATTERNS:
        if pattern.search(without_safe):
            return f"forbidden pattern: {label}"

    if ">>" in without_safe:
        return "forbidden pattern: append redirect (>>)"
    if ">" in without_safe:
        return "forbidden pattern: output redirect (>)"
    if "<" in without_safe:
        return "forbidden pattern: input redirect (<)"

    # Split on real pipes only — grep's escaped \| alternation is preserved.
    segments = [
        s.replace(_ESCAPED_PIPE_SENTINEL, r"\|").strip()
        for s in trimmed.replace(r"\|", _ESCAPED_PIPE_SENTINEL).split("|")
    ]
    for segment in segments:
        if not segment:
            return "empty pipe segment"
        base = segment.split()[0]
        if base in FORBIDDEN_COMMANDS:
            return f"forbidden command: {base}"
        if base not in WHITELISTED_COMMANDS:
            return f"command not whitelisted: {base}"
        # Per-command write-capable flags of otherwise read-only commands.
        if base == "sort" and re.search(r"(^|\s)(-o\b|--output)", segment):
            return "forbidden flag: sort -o/--output writes files"
    return None


def sanitized_env() -> dict[str, str]:
    env = dict(os.environ)
    for key in SENSITIVE_ENV_KEYS:
        env.pop(key, None)
    return env


def _execute_command(command: str, project_root: str, env: dict[str, str]) -> str:
    try:
        proc = subprocess.run(
            ["bash", "-c", command],
            cwd=project_root, env=env, capture_output=True, text=True,
            timeout=TIMEOUT_SECONDS, errors="replace",
        )
    except subprocess.TimeoutExpired:
        return f"### VERIFY: {command}\n```\n[TIMEOUT after {TIMEOUT_SECONDS}s]\n```"
    except OSError as e:
        return f"### VERIFY: {command}\n```\n[ERROR] {e}\n```"

    output = (proc.stdout or "")[:MAX_BUFFER_BYTES].strip()
    err_output = (proc.stderr or "")[:MAX_BUFFER_BYTES].strip()
    truncated = (output[:OUTPUT_TRUNCATE_CHARS] + "\n...(truncated)"
                 if len(output) > OUTPUT_TRUNCATE_CHARS else output)
    if proc.returncode != 0:
        # Show output even on non-zero exit (e.g. grep with no match).
        combined = truncated or err_output or f"exit code {proc.returncode}"
        return f"### VERIFY: {command}\n```\n{combined}\n```"
    return f"### VERIFY: {command}\n```\n{truncated or '(empty output)'}\n```"


def resolve_verify_commands(
    commands: list[str], project_root: str,
    on_event: Optional[Callable[[str, str], None]] = None,
) -> str:
    """Validate + execute up to 4 commands, return the combined report
    (reference verify.ts:148-174). ``on_event(kind, message)`` receives
    "denied"/"running" notifications for the CLI layer to display.
    """
    results: list[str] = []
    env = sanitized_env()
    for command in commands[:MAX_COMMANDS]:
        error = validate_command(command)
        if error:
            results.append(f"### VERIFY: {command}\n```\n[DENIED] {error}\n```")
            if on_event:
                on_event("denied", f"[DENIED] {command} — {error}")
            continue
        if on_event:
            on_event("running", f"Running: {command}")
        results.append(_execute_command(command, project_root, env))
    return "\n\n".join(results)
