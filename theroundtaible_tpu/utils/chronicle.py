"""Chronicle — the project's append-only decision log (markdown).

Parity with reference src/utils/chronicle.ts:1-54.
"""

from __future__ import annotations

from pathlib import Path

CHRONICLE_HEADER = (
    "# Chronicle - TheRoundtAIble\n\nBeslissingen log van dit project.\n\n---\n\n"
)


def read_chronicle(project_root: str | Path, chronicle_path: str) -> str:
    full_path = Path(project_root) / chronicle_path
    if not full_path.exists():
        return ""
    return full_path.read_text(encoding="utf-8")


def append_to_chronicle(project_root: str | Path, chronicle_path: str, *,
                        topic: str, outcome: str, knights: list[str],
                        date: str) -> None:
    """Append a `## <date> — <topic>` entry (reference chronicle.ts:21-54).

    The read-modify-write runs under a PID-stale-aware lock: the
    reference interleaves concurrent appends (its acknowledged race,
    SURVEY.md §5.2 / reference TODO.md:188)."""
    from .lock import FileLock

    full_path = Path(project_root) / chronicle_path
    full_path.parent.mkdir(parents=True, exist_ok=True)
    entry = "\n".join([
        f"## {date} — {topic}",
        "",
        f"**Knights:** {', '.join(knights)}",
        "",
        outcome,
        "",
        "---",
        "",
    ])
    from .session import atomic_write_text

    with FileLock(full_path):
        if full_path.exists():
            content = full_path.read_text(encoding="utf-8")
        else:
            content = CHRONICLE_HEADER
        # atomic replace: a crash mid-write must not truncate the history
        atomic_write_text(full_path, content + entry)
