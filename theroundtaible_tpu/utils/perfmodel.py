"""Shared roofline & performance-attribution model (ISSUE 6).

Roofline math lived twice in bench scripts with copy-pasted constants
(bench.py's `V5E_HBM_GBPS` / ceiling formulas, bench_microquant's 819
GB/s literal) and nowhere in the serving path — a number could be slow
in production with no live gauge saying how far from the hardware
ceiling it was, or why. This module is the ONE definition:

- **Chip specs** — per-chip HBM bandwidth and bf16 peak FLOP/s from
  public TPU specs, keyed by `device_kind` (the string the runtime
  reports) and by short name (`ROUNDTABLE_PERF_CHIP=v5e` overrides
  detection — CPU smoke runs and unknown plugin device_kinds still get
  a ceiling, explicitly marked as assumed).
- **Ceiling math** — decode is weight-streaming bound at low batch, so
  `decode_ceiling_tps = n_devices * HBM / streamed_param_bytes`
  (measured from the ACTUAL quantized tree, so int8/int4 automatically
  get their smaller-bytes ceilings); prefill is compute bound,
  `prefill_peak_tps = n_devices * peak_flops / (2 * params)`.
  `roofline_block()` packages both the way bench records carry them —
  bench.py embeds this dict verbatim, and the drift test pins its keys
  here so the bench schema and the live gauges can never fork again.
- **EnginePerf** — a per-engine instance built once at engine
  construction (param bytes + ceilings + KV bytes/token). Serving
  publishes through it at EVENT rate: per generate call
  (`publish_call` → `roundtable_bw_utilization{phase=decode}` /
  `roundtable_mfu{phase=prefill}` gauges) and per scheduler decode
  segment (`publish_decode_sample`), plus per-session KV-footprint
  gauges (`publish_session_kv`).
- **Span overheads** — `span_overheads()` folds the PR-5 span tree
  into a per-rung breakdown: how much of a decode/prefill/segment
  span's wall was inside device dispatches, host syncs, or the
  unaccounted dispatch gap between them — the "where did the
  milliseconds go" table `status --perf` renders.
- **attribution_snapshot()** — the perf block embedded in bench
  records and flight-recorder dumps: perf/compile/memory registry
  series + span overheads + the compile observatory's summary.

Host-only by design: no jax import at module load (the lazy imports in
`streamed_param_bytes`/`detect_chip` are the only backend touches), so
bench parents, tests and the telemetry spine can import this freely.
"""

from __future__ import annotations

import os
import threading
from dataclasses import dataclass
from typing import Any, Optional

from . import telemetry


@dataclass(frozen=True)
class ChipSpec:
    """Per-chip roofline constants (public TPU specs)."""

    name: str                 # short name (env-override key)
    hbm_gbps: float           # HBM bandwidth, GB/s per chip
    bf16_peak_tflops: float   # peak bf16 TFLOP/s per chip


# Keyed by the runtime's device_kind string. Sources: public TPU specs
# (the v5e row is the pair bench.py carried since round 1).
CHIP_SPECS: dict[str, ChipSpec] = {
    "TPU v5 lite": ChipSpec("v5e", 819.0, 197.0),
    "TPU v5e": ChipSpec("v5e", 819.0, 197.0),
    "TPU v5": ChipSpec("v5p", 2765.0, 459.0),
    "TPU v5p": ChipSpec("v5p", 2765.0, 459.0),
    "TPU v4": ChipSpec("v4", 1228.0, 275.0),
    "TPU v6 lite": ChipSpec("v6e", 1640.0, 918.0),
    "TPU v6e": ChipSpec("v6e", 1640.0, 918.0),
    "TPU v3": ChipSpec("v3", 900.0, 123.0),
    "TPU v2": ChipSpec("v2", 700.0, 46.0),
}

_BY_SHORT_NAME: dict[str, ChipSpec] = {}
for _spec in CHIP_SPECS.values():
    _BY_SHORT_NAME.setdefault(_spec.name, _spec)

V5E = CHIP_SPECS["TPU v5e"]
# Back-compat names (bench.py re-exports these — ONE definition now).
V5E_HBM_GBPS = V5E.hbm_gbps
V5E_BF16_PEAK_TFLOPS = V5E.bf16_peak_tflops

CHIP_ENV = "ROUNDTABLE_PERF_CHIP"


def chip_spec(device_kind: Optional[str] = None) -> Optional[ChipSpec]:
    """The ChipSpec for a device_kind (or the env override), else None.

    ROUNDTABLE_PERF_CHIP (short name like "v5e", or a device_kind)
    wins over the argument — it is how CPU smoke runs and tests force
    a known roofline."""
    forced = os.environ.get(CHIP_ENV)
    if forced:
        return _BY_SHORT_NAME.get(forced) or CHIP_SPECS.get(forced)
    if not device_kind:
        return None
    spec = CHIP_SPECS.get(device_kind)
    if spec is not None:
        return spec
    # Prefix match: plugins append steppings ("TPU v5 lite chip" etc.).
    for kind, spec in CHIP_SPECS.items():
        if device_kind.startswith(kind):
            return spec
    return None


def detect_chip() -> tuple[Optional[ChipSpec], str]:
    """(spec, source) for the local device 0. source is one of
    "env" | "detected" | "none" — callers that refuse to run
    ceiling-less (bench on hardware) fall back to V5E and mark the
    block "assumed-v5e"."""
    if os.environ.get(CHIP_ENV):
        return chip_spec(), "env"
    try:
        import jax
        kind = getattr(jax.devices()[0], "device_kind", "")
    except Exception:  # noqa: BLE001 — no backend is a valid state
        return None, "none"
    spec = chip_spec(kind)
    return spec, ("detected" if spec else "none")


def streamed_param_bytes(params: Any) -> int:
    """Bytes decode streams from HBM per token: the summed on-device
    size of the ACTUAL (possibly quantized) param tree — Int4Leaf's
    packed q4 bytes and its scales count as stored, which is exactly
    what the memory bus sees."""
    import jax
    return sum(int(x.size) * int(x.dtype.itemsize)
               for x in jax.tree_util.tree_leaves(params))


def flops_per_token(num_params: int) -> float:
    """Dense-decoder forward FLOPs per token ≈ 2 · params (the
    standard roofline approximation both bench scripts used)."""
    return 2.0 * num_params


def decode_ceiling_tps(param_bytes: int, chip: ChipSpec,
                       n_devices: int = 1,
                       kv_stream_bytes: int = 0) -> float:
    """Weight-streaming decode ceiling: with TP over n chips each chip
    streams param_bytes/n per token. `kv_stream_bytes` (ISSUE 11) is
    the per-token KV read — context_tokens x resident cell bytes (data
    + scales on a quantized pool) — folded into the streamed term;
    0 keeps the historical weights-only ceiling (MQA at short serving
    context reads <1% of the weight bytes, but long contexts and batch
    don't, and quantized pages shrink exactly this term)."""
    return (n_devices * chip.hbm_gbps * 1e9
            / max(param_bytes + kv_stream_bytes, 1))


def prefill_peak_tps(num_params: int, chip: ChipSpec,
                     n_devices: int = 1) -> float:
    """Compute-bound prefill ceiling: peak bf16 FLOP/s over
    2·params FLOPs/token, scaled by the mesh size."""
    return (n_devices * chip.bf16_peak_tflops * 1e12
            / max(flops_per_token(num_params), 1.0))


def _assumptions(chip: ChipSpec) -> str:
    return (f"decode: HBM {chip.hbm_gbps:g} GB/s / streamed param "
            "bytes (KV traffic excluded); prefill: 2·params "
            f"FLOPs/token vs {chip.bf16_peak_tflops:g} bf16 TFLOP/s")


def roofline_block(*, param_bytes: int, num_params: int,
                   n_devices: int = 1,
                   decode_tps: Optional[float] = None,
                   prefill_tps: Optional[float] = None,
                   chip: Optional[ChipSpec] = None,
                   int4_fallbacks: Optional[int] = None,
                   kv_stream_bytes: int = 0,
                   kv_dtype: Optional[str] = None) -> dict:
    """The bench-record `roofline` dict — produced HERE and only here
    (bench.py embeds it verbatim; the drift test pins these keys).

    When no chip is given or detectable, the block assumes v5e and
    says so in `chip_source` — a hardware-window record must never
    silently drop its ceiling because a plugin renamed device_kind.

    `kv_stream_bytes`/`kv_dtype` (ISSUE 11): per-token KV bytes the
    decode step streams on top of the weights (context x resident cell
    bytes — data + scales on a quantized pool). Nonzero folds into the
    ceiling and rides the block as explicit keys, so an int8-KV record
    carries its own higher ceiling next to the dtype that earned it;
    0 keeps the historical weights-only block byte-identical."""
    source = "given"
    if chip is None:
        chip, source = detect_chip()
        if chip is None:
            chip, source = V5E, "assumed-v5e"
    ceiling = decode_ceiling_tps(param_bytes, chip, n_devices,
                                 kv_stream_bytes)
    peak = prefill_peak_tps(num_params, chip, n_devices)
    block = {
        "chip": chip.name,
        "chip_source": source,
        "decode_ceiling_tps": round(ceiling, 1),
        "decode_frac": (round(decode_tps / ceiling, 3)
                        if decode_tps is not None else None),
        "prefill_mfu": (round(prefill_tps / peak, 3)
                        if prefill_tps is not None else None),
        "assumptions": _assumptions(chip),
    }
    if int4_fallbacks:
        # XLA-dequant fallbacks materialize bf16 weights per token, so
        # the packed-bytes ceiling above is optimistic for that share
        # of dispatches — the count rides along so the reader knows.
        block["int4_fallback_dispatches"] = int(int4_fallbacks)
    if kv_stream_bytes:
        block["kv_stream_bytes_per_token"] = int(kv_stream_bytes)
        block["kv_dtype"] = kv_dtype or "bf16"
    return block


def kv_bytes_per_token(cfg: Any, dtype_bytes: int = 2,
                       quant_spec: Any = None) -> int:
    """Resident KV bytes one cached token costs this model:
    layers × (K + V) × kv_heads × head_dim × dtype. `quant_spec`
    (ISSUE 11, a kv_quant.KVQuantSpec) switches the cell to the
    quantized layout — int8/int4 payload PLUS the per-cell scale
    arrays, the closed form engine/kv_quant.cell_bytes_per_token owns
    (lazy import keeps this module host-only at load)."""
    if quant_spec is not None:
        from ..engine.kv_quant import cell_bytes_per_token
        return int(cell_bytes_per_token(cfg, quant_spec, dtype_bytes))
    return int(cfg.num_layers * 2 * cfg.num_kv_heads * cfg.head_dim
               * dtype_bytes)


# --- gauge-publication counter (tests/conftest.py `perf_obs` guard) ---

_published = 0
_published_lock = threading.Lock()


def note_published(n: int = 1) -> None:
    global _published
    with _published_lock:
        _published += n


def gauges_published() -> int:
    return _published


class EnginePerf:
    """One engine's live roofline model: built once at construction,
    published through at event rate (per call / per segment), embedded
    in describe(). `chip` may be None (CPU, unknown plugin) — ceilings
    are then None and publish_* become no-ops for the roofline gauges
    (memory/session gauges don't need a chip and publish elsewhere)."""

    def __init__(self, engine_name: str, *, param_bytes: int,
                 num_params: int, n_devices: int = 1,
                 chip: Optional[ChipSpec] = None,
                 chip_source: str = "given",
                 kv_token_bytes: int = 0):
        self.engine_name = engine_name
        self.param_bytes = param_bytes
        self.num_params = num_params
        self.n_devices = n_devices
        self.chip = chip
        self.chip_source = chip_source
        self.kv_token_bytes = kv_token_bytes
        # Multi-LoRA streamed-bytes overhead (ISSUE 10): a persona row
        # streams its adapter's A/B bytes on top of the base weights
        # every decode token, so the weight-streaming ceiling drops.
        # The engine's LoraStore keeps this at the per-adapter cost
        # while any adapter is resident (a conservative default for
        # call-level gauges); the scheduler passes the exact per-
        # sample mix to publish_decode_sample/publish_mixed_sample.
        self.lora_row_bytes = 0.0
        # Quantized-KV streamed term (ISSUE 11): decode streams each
        # row's whole context from the page pool every token on top of
        # the weights. kv_token_bytes is already the RESIDENT cell cost
        # (data + scales on a quantized pool — from_engine resolves the
        # spec), so set_kv_decode_context(mean context tokens) is all
        # the ceiling needs to price the pool dtype; 0 (the default)
        # keeps the historical weights-only ceiling.
        self.kv_decode_context = 0
        self.decode_ceiling = (decode_ceiling_tps(param_bytes, chip,
                                                  n_devices)
                               if chip else None)
        self.prefill_peak = (prefill_peak_tps(num_params, chip,
                                              n_devices)
                             if chip else None)
        if self.decode_ceiling:
            telemetry.set_gauge("roundtable_decode_ceiling_tps",
                                self.decode_ceiling,
                                engine=engine_name)
            telemetry.set_gauge("roundtable_prefill_peak_tps",
                                self.prefill_peak, engine=engine_name)
            note_published(2)

    @classmethod
    def from_engine(cls, engine, params: Any = None,
                    kv_itemsize: Optional[int] = None) -> "EnginePerf":
        """Build from a live engine: streamed bytes from its ACTUAL
        (quantized) tree, chip from its mesh's device 0. ONE
        definition for both engine families — `params` overrides for
        engines whose tree isn't at `.params` (PPEngine's stage-stacked
        shared/staged pair), `kv_itemsize` for caches that don't hang
        pools/layers off `.kv`."""
        kind = ""
        try:
            kind = getattr(engine.mesh.devices.flatten()[0],
                           "device_kind", "")
        except Exception:  # noqa: BLE001 — spec detection best-effort
            pass
        chip = chip_spec(kind)
        source = ("env" if os.environ.get(CHIP_ENV)
                  else "detected" if chip else "none")
        quant_spec = getattr(engine, "kv_quant_spec", None)
        if kv_itemsize is None:
            kv_itemsize = 2
            kv = getattr(engine, "kv", None)
            pools = getattr(kv, "pools", None)
            layers = getattr(kv, "layers", None)
            if pools and quant_spec is None:
                kv_itemsize = pools[0][0].dtype.itemsize
            elif pools:
                # Quantized pools store int8 payload — itemsize 1 would
                # miss the scales; the spec's closed cell form below
                # charges both, against the engine's LOGICAL kv dtype
                # (the allocator records it — quantize-off round-trips
                # to exactly that width).
                kv_itemsize = getattr(kv, "_kv_dtype_bytes", 2)
            elif layers:
                kv_itemsize = layers[0][0].dtype.itemsize
        return cls(
            engine.cfg.name,
            param_bytes=streamed_param_bytes(
                params if params is not None else engine.params),
            num_params=engine.num_params,
            n_devices=int(engine.mesh.devices.size),
            chip=chip, chip_source=source,
            kv_token_bytes=kv_bytes_per_token(engine.cfg, kv_itemsize,
                                              quant_spec=quant_spec))

    def set_lora_row_bytes(self, n: float) -> None:
        self.lora_row_bytes = float(max(n, 0.0))

    def set_kv_decode_context(self, tokens: int) -> None:
        """Mean per-row context length the decode ceiling should charge
        KV streaming for (ISSUE 11) — tokens x kv_token_bytes joins the
        streamed term. 0 restores the weights-only ceiling."""
        self.kv_decode_context = int(max(tokens, 0))

    def _decode_ceiling(self, lora_bytes_per_token=None) -> float:
        """The weight-streaming ceiling with LoRA bytes folded in
        (ISSUE 10): a K-adapter batch streams base + adapter bytes per
        token, so judging it against the base-only ceiling would
        overreport bw_utilization exactly when personas are active.
        The quantized-KV streamed term (ISSUE 11) folds in the same
        way: context x resident cell bytes per decoded token — int8
        pages halve it, which RAISES the ceiling this gauge divides by
        (the explicit decode-ceiling correction the bench A/B prices)."""
        extra = (self.lora_row_bytes if lora_bytes_per_token is None
                 else lora_bytes_per_token)
        kv_extra = self.kv_decode_context * self.kv_token_bytes
        if not extra and not kv_extra:
            return self.decode_ceiling
        return decode_ceiling_tps(self.param_bytes + int(extra),
                                  self.chip, self.n_devices,
                                  kv_stream_bytes=int(kv_extra))

    # --- live publication seams ---

    def publish_call(self, stats) -> None:
        """Per-generate-call roofline gauges from a GenStats: decode
        bandwidth utilization and prefill MFU, per engine per phase."""
        if self.decode_ceiling is None:
            return
        n = 0
        if stats.decode_seconds and stats.decode_tokens:
            # bw_utilization/mfu only — roundtable_decode_tps is
            # publish_gen_stats' series (one writer per series).
            telemetry.set_gauge(
                "roundtable_bw_utilization",
                stats.decode_tps / self._decode_ceiling(),
                engine=self.engine_name, phase="decode")
            n += 1
        if stats.prefill_seconds and stats.prefill_tokens:
            telemetry.set_gauge(
                "roundtable_mfu",
                stats.prefill_tps / self.prefill_peak,
                engine=self.engine_name, phase="prefill")
            n += 1
        if n:
            note_published(n)

    def publish_decode_sample(self, tokens: int, seconds: float,
                              lora_bytes_per_token=None) -> None:
        """Per-decode-segment utilization sample (the scheduler's
        segment boundary): tokens is the segment's attributed count
        (steps × live rows — rows finishing mid-segment emit filler,
        so this is a slight over-attribution, stated here once).
        `lora_bytes_per_token` (ISSUE 10): the sample's actual mean
        adapter bytes streamed per token (None = the store-level
        default)."""
        if self.decode_ceiling is None or seconds <= 0 or tokens <= 0:
            return
        ceiling = self._decode_ceiling(lora_bytes_per_token)
        telemetry.set_gauge("roundtable_bw_utilization",
                            (tokens / seconds) / ceiling,
                            engine=self.engine_name, phase="decode")
        note_published(1)

    def publish_mixed_sample(self, prefill_tokens: int,
                             decode_tokens: int,
                             seconds: float,
                             decode_dispatch_tokens: Optional[int] = None,
                             lora_bytes_per_token=None,
                             ) -> None:
        """Per-RAGGED-segment attribution (ISSUE 8): a mixed dispatch
        carries both prefill chunks and decode tokens, so the roofline
        gauges split by per-row token counts instead of classifying the
        whole dispatch as one phase — decode_tokens/wall against the
        weight-streaming ceiling, prefill_tokens/wall against the
        compute peak. Both rates run over the FULL wall (the phases
        genuinely shared it), so each gauge is a conservative
        lower-bound utilization and their information adds up to the
        real mix — a pure-decode segment degenerates to exactly
        publish_decode_sample.

        `decode_dispatch_tokens` (ISSUE 9): a SPECULATIVE verify
        dispatch commits more decode tokens than it streamed weights
        for — the forward reads the weight tree once per ROW, not once
        per accepted token. The roofline gauge must use the dispatch
        count (1 per row per forward, what a 1-token decode would have
        produced) or a 3x-accepting run reports 300% bandwidth
        utilization; the ACCEPTED rate publishes separately as the
        user-visible `roundtable_spec_accepted_tps`. None (the plain
        ragged path) means the two counts coincide.

        `lora_bytes_per_token` (ISSUE 10): the sample's mean adapter
        bytes streamed per token — folds into the decode ceiling so a
        K-adapter batch doesn't overreport bw_utilization."""
        if self.decode_ceiling is None or seconds <= 0:
            return
        n = 0
        if decode_tokens > 0:
            roofline_tokens = (decode_tokens
                               if decode_dispatch_tokens is None
                               else decode_dispatch_tokens)
            telemetry.set_gauge(
                "roundtable_bw_utilization",
                (roofline_tokens / seconds)
                / self._decode_ceiling(lora_bytes_per_token),
                engine=self.engine_name, phase="decode")
            n += 1
            if decode_dispatch_tokens is not None:
                # Published on EVERY speculative sample, including the
                # zero-accept case where the two counts coincide — a
                # gauge updated only on acceptance would stay frozen at
                # the last good rate exactly when acceptance collapses.
                telemetry.set_gauge(
                    "roundtable_spec_accepted_tps",
                    decode_tokens / seconds, engine=self.engine_name)
                n += 1
        if prefill_tokens > 0:
            telemetry.set_gauge(
                "roundtable_mfu",
                (prefill_tokens / seconds) / self.prefill_peak,
                engine=self.engine_name, phase="prefill")
            n += 1
        if n:
            note_published(n)

    def publish_session_kv(self, session: str, cached_tokens: int) -> None:
        """Per-session KV-footprint gauge (the memory ledger's
        per-session series). Retirement passes 0, which REMOVES the
        series: session ids are uuid-tagged per serve call, so a
        zeroed-but-kept series per session ever served would grow the
        registry (and every metrics.prom export) without bound in a
        long-lived serving process."""
        if cached_tokens <= 0:
            telemetry.REGISTRY.remove_gauge(
                "roundtable_session_kv_bytes",
                engine=self.engine_name, session=session)
            return
        telemetry.set_gauge("roundtable_session_kv_bytes",
                            cached_tokens * self.kv_token_bytes,
                            engine=self.engine_name, session=session)
        note_published(1)

    def describe(self) -> dict[str, Any]:
        return {
            "chip": self.chip.name if self.chip else None,
            "chip_source": self.chip_source,
            "param_bytes": self.param_bytes,
            "n_devices": self.n_devices,
            "decode_ceiling_tps": (round(self.decode_ceiling, 1)
                                   if self.decode_ceiling else None),
            "prefill_peak_tps": (round(self.prefill_peak, 1)
                                 if self.prefill_peak else None),
            "kv_bytes_per_token": self.kv_token_bytes,
            "kv_decode_context": self.kv_decode_context,
            "lora_row_bytes": int(self.lora_row_bytes),
        }


# --- span-tree overhead attribution ---


def _span_attr(rec: dict, key: str):
    """Span records come in two shapes: the flight-recorder ring
    flattens attrs into the record, spans.jsonl nests them."""
    if key in rec:
        return rec[key]
    return rec.get("attrs", {}).get(key)


def span_overheads(spans: list[dict]) -> dict[str, dict]:
    """Per-rung overhead breakdown from finished-span records (the
    PR-5 ring or spans.jsonl): for every parent rung, what fraction of
    its wall sat inside device dispatches, host syncs, or the
    unaccounted dispatch GAP between children — the host-overhead
    number the hardware-window tok/s needs an explanation from.

    Returns {rung: {total_s, dispatch_s, host_sync_s, gap_s,
    dispatch_frac, host_sync_frac, gap_frac, count}} for rungs that
    have children, plus a "queue_wait_s" roll-up from turn spans."""
    children: dict[str, list[dict]] = {}
    for s in spans:
        pid = s.get("parent_id")
        if pid:
            children.setdefault(pid, []).append(s)
    agg: dict[str, dict] = {}
    queue_wait = 0.0
    for s in spans:
        qw = _span_attr(s, "queue_wait_s")
        if s.get("rung") == "turn" and qw:
            queue_wait += float(qw)
        kids = children.get(s.get("span_id") or "", ())
        if not kids:
            continue
        rung = s.get("rung", "?")
        a = agg.setdefault(rung, {"total_s": 0.0, "dispatch_s": 0.0,
                                  "host_sync_s": 0.0, "gap_s": 0.0,
                                  "count": 0})
        dur = float(s.get("dur_s", 0.0))
        child_total = 0.0
        for k in kids:
            kdur = float(k.get("dur_s", 0.0))
            child_total += kdur
            if k.get("rung") == "dispatch":
                if _span_attr(k, "op") == "host_sync":
                    a["host_sync_s"] += kdur
                else:
                    a["dispatch_s"] += kdur
        a["total_s"] += dur
        a["gap_s"] += max(dur - child_total, 0.0)
        a["count"] += 1
    for a in agg.values():
        total = a["total_s"] or 1.0
        a["dispatch_frac"] = round(a["dispatch_s"] / total, 3)
        a["host_sync_frac"] = round(a["host_sync_s"] / total, 3)
        a["gap_frac"] = round(a["gap_s"] / total, 3)
        for key in ("total_s", "dispatch_s", "host_sync_s", "gap_s"):
            a[key] = round(a[key], 4)
    if queue_wait:
        agg["queue_wait_s"] = round(queue_wait, 4)
    return agg


# --- the embedded perf-attribution block ---

# Registry series the perf block collects (prefix match on the series
# name): roofline gauges, compile observatory, memory ledger.
PERF_SERIES_PREFIXES = (
    "roundtable_bw_utilization", "roundtable_mfu",
    "roundtable_decode_ceiling_tps", "roundtable_prefill_peak_tps",
    "roundtable_decode_tps",
    "roundtable_compile", "roundtable_steady_state",
    "roundtable_kv_", "roundtable_hbm_", "roundtable_session_kv_",
    "roundtable_prefix_",   # ISSUE 7: prefix-cache hit/miss/size series
    "roundtable_spec_",     # ISSUE 9: speculation accept/rate series
    "roundtable_lora_",     # ISSUE 10: multi-LoRA residency/apply series
)


def perf_series(snapshot: Optional[dict] = None) -> dict[str, float]:
    """The perf slice of a compact registry snapshot."""
    snap = snapshot if snapshot is not None \
        else telemetry.REGISTRY.snapshot_compact()
    return {k: v for k, v in snap.items()
            if k.split("{", 1)[0].startswith(PERF_SERIES_PREFIXES)}


def attribution_snapshot() -> dict[str, Any]:
    """The perf-attribution block bench records and flight dumps embed:
    perf registry series + span-tree overheads (from the flight ring)
    + the compile observatory's summary. Never raises — an attribution
    block must not add a failure to the record it explains."""
    out: dict[str, Any] = {"series": perf_series()}
    try:
        out["overheads"] = span_overheads(
            telemetry.recorder().span_events())
    except Exception:  # noqa: BLE001 — best-effort block
        pass
    try:
        from ..engine import compile_watch
        out["compiles"] = compile_watch.summary(recent=8)
    except Exception:  # noqa: BLE001 — engine layer may be absent
        pass
    return out
