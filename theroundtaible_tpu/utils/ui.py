"""Terminal UI helpers — colors, spinners, knight theming.

Covers the reference's chalk/ora usage (src/orchestrator.ts:225-265, 428-491):
personality round headers, per-knight colors and thinking messages, score
bars. ANSI codes are emitted only when stdout is a TTY (or FORCE_COLOR is
set), so logs and tests stay clean.
"""

from __future__ import annotations

import os
import random
import sys
import threading
import time
from typing import Optional


def _want_color() -> bool:
    if os.environ.get("NO_COLOR"):
        return False
    if os.environ.get("FORCE_COLOR"):
        return True
    return sys.stdout.isatty()


class _Style:
    def __init__(self, enabled: bool):
        self.enabled = enabled

    def _wrap(self, code: str, text: str) -> str:
        if not self.enabled:
            return text
        return f"\x1b[{code}m{text}\x1b[0m"

    def bold(self, t: str) -> str: return self._wrap("1", t)
    def dim(self, t: str) -> str: return self._wrap("2", t)
    def red(self, t: str) -> str: return self._wrap("31", t)
    def green(self, t: str) -> str: return self._wrap("32", t)
    def yellow(self, t: str) -> str: return self._wrap("33", t)
    def blue(self, t: str) -> str: return self._wrap("34", t)
    def cyan(self, t: str) -> str: return self._wrap("36", t)
    def white(self, t: str) -> str: return self._wrap("37", t)

    def rgb(self, hexcode: str, t: str) -> str:
        if not self.enabled:
            return t
        r, g, b = (int(hexcode[i:i + 2], 16) for i in (1, 3, 5))
        return f"\x1b[38;2;{r};{g};{b}m{t}\x1b[0m"


style = _Style(_want_color())

# Per-knight theming (reference orchestrator.ts:428-434).
KNIGHT_COLORS = {"Claude": "#D97706", "Gemini": "#3B82F6", "GPT": "#10B981"}

# Thinking messages (reference orchestrator.ts:225-252) — my own phrasing.
THINKING_MESSAGES: dict[str, list[str]] = {
    "Claude": [
        "polishes an elegant rebuttal...",
        "is refactoring the argument itself...",
        "sighs at the proposed shortcut...",
        "sketches the clean abstraction...",
    ],
    "Gemini": [
        "zooms out to the bigger picture...",
        "drafts a roadmap for the roadmap...",
        "aligns the strategy...",
        "plans three moves ahead...",
    ],
    "GPT": [
        "wants to ship it already...",
        "trims the fat off the plan...",
        "is losing patience gracefully...",
        "reaches for the deploy button...",
    ],
}
DEFAULT_THINKING = ["is thinking...", "prepares a response..."]

ROUND_HEADERS = [
    "ROUND {n} — KNIGHTS, DRAW YOUR KEYBOARDS!",
    "ROUND {n} — SPEAK NOW, OR THE CODE SUFFERS!",
    "ROUND {n} — EGOS CLASH, COMPILERS WEEP!",
    "ROUND {n} — ONE MORE PLEA FOR SANITY!",
    "ROUND {n} — SPEAK NOW OR FOREVER HOLD YOUR MERGE CONFLICTS!",
]


def warn(text: str) -> None:
    """Styled degrade/advisory line on stderr — the ONE warning surface
    for opt-in features that must not kill a run (profiling, telemetry):
    bare print() would interleave with round output and lose the
    styling contract every other surface honors."""
    print(style.yellow(text), file=sys.stderr)


def knight_color(name: str, text: str) -> str:
    hexcode = KNIGHT_COLORS.get(name)
    return style.rgb(hexcode, text) if hexcode else style.white(text)


def thinking_message(name: str) -> str:
    msgs = THINKING_MESSAGES.get(name, DEFAULT_THINKING)
    return random.choice(msgs)


def round_header(round_num: int) -> str:
    if round_num <= len(ROUND_HEADERS):
        return ROUND_HEADERS[round_num - 1].format(n=round_num)
    return f"ROUND {round_num} — FOR KING AND CODE!"


def score_bar(score: float) -> str:
    """██████░░░░ 6/10 with traffic-light coloring (reference :475-485)."""
    filled = max(0, min(10, int(score)))
    bar = "█" * filled + "░" * (10 - filled)
    from ..core.types import format_score
    text = f"{bar} {format_score(score)}/10"
    if score >= 9:
        return style.green(text)
    if score >= 6:
        return style.yellow(text)
    return style.red(text)


class Spinner:
    """Minimal ora-equivalent: animated only on TTY, silent otherwise."""

    FRAMES = "⠋⠙⠹⠸⠼⠴⠦⠧⠇⠏"

    def __init__(self, text: str, stream=None):
        self.text = text
        self.stream = stream or sys.stdout
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._animated = self.stream.isatty()

    def __enter__(self) -> "Spinner":
        self.start()
        return self

    def __exit__(self, *exc) -> None:
        self.stop()

    def start(self) -> "Spinner":
        if self._animated:
            self._thread = threading.Thread(target=self._spin, daemon=True)
            self._thread.start()
        return self

    def _spin(self) -> None:
        i = 0
        while not self._stop.is_set():
            frame = self.FRAMES[i % len(self.FRAMES)]
            self.stream.write(f"\r{frame} {self.text}\x1b[K")
            self.stream.flush()
            i += 1
            time.sleep(0.08)

    def _clear_line(self) -> None:
        if self._animated:
            self.stream.write("\r\x1b[K")
            self.stream.flush()

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=1)
        self._clear_line()

    def succeed(self, text: str) -> None:
        self.stop()
        print(style.green("✔") + f" {text}")

    def fail(self, text: str) -> None:
        self.stop()
        print(style.red("✖") + f" {text}")


def ask(prompt_text: str, default: str = "") -> str:
    """Blocking stdin prompt (readline equivalent)."""
    try:
        answer = input(prompt_text).strip()
    except EOFError:
        return default
    return answer or default


def ask_yes_no(prompt_text: str, default: bool = True) -> bool:
    suffix = " [Y/n] " if default else " [y/N] "
    answer = ask(prompt_text + suffix).lower()
    if not answer:
        return default
    return answer in ("y", "yes", "j", "ja")


def ask_secret(prompt_text: str) -> str:
    """Masked secret input (reference init.ts:49-91)."""
    import getpass
    try:
        return getpass.getpass(prompt_text).strip()
    except EOFError:
        return ""
