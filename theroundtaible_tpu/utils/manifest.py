"""Implementation manifest — what has actually been built.

Parity with reference src/utils/manifest.ts:1-183. The manifest summary is
injected into knight prompts ("don't re-propose what exists").
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Optional

from ..core.types import Manifest, ManifestEntry
from .session import now_iso

MANIFEST_RELPATH = Path(".roundtable") / "manifest.json"

_STATUS_ICONS = {"implemented": "+", "partial": "~", "deprecated": "x"}


def read_manifest(project_root: str | Path) -> Manifest:
    full_path = Path(project_root) / MANIFEST_RELPATH
    if not full_path.exists():
        return Manifest(last_updated=now_iso())
    try:
        return Manifest.from_dict(json.loads(full_path.read_text(encoding="utf-8")))
    except (json.JSONDecodeError, OSError):
        return Manifest(last_updated=now_iso())


def write_manifest(project_root: str | Path, manifest: Manifest) -> None:
    full_path = Path(project_root) / MANIFEST_RELPATH
    full_path.parent.mkdir(parents=True, exist_ok=True)
    manifest.last_updated = now_iso()
    full_path.write_text(json.dumps(manifest.to_dict(), indent=2),
                         encoding="utf-8")


def add_manifest_entry(project_root: str | Path, entry: ManifestEntry) -> None:
    """Add, or update by id (reference manifest.ts:57-72)."""
    manifest = read_manifest(project_root)
    for i, f in enumerate(manifest.features):
        if f.id == entry.id:
            manifest.features[i] = entry
            break
    else:
        manifest.features.append(entry)
    write_manifest(project_root, manifest)


def deprecate_feature(project_root: str | Path, feature_id: str,
                      replaced_by: Optional[str] = None) -> bool:
    manifest = read_manifest(project_root)
    for f in manifest.features:
        if f.id == feature_id:
            f.status = "deprecated"
            if replaced_by:
                f.replaced_by = replaced_by
            write_manifest(project_root, manifest)
            return True
    return False


def check_manifest(project_root: str | Path) -> list[str]:
    """Stale-file warnings (reference manifest.ts:98-118)."""
    manifest = read_manifest(project_root)
    warnings: list[str] = []
    for feature in manifest.features:
        if feature.status == "deprecated":
            continue
        for file in feature.files:
            if not (Path(project_root) / file).exists():
                warnings.append(
                    f'{feature.id}: "{file}" no longer exists on disk '
                    f"(stale entry)")
    return warnings


def get_manifest_summary(manifest: Manifest, language: str = "en") -> str:
    """Compact prompt summary: last 15 features, newest first
    (reference manifest.ts:124-144). The empty-history fallback is
    localized with the prompt scaffolding (an nl session must not get
    an English IMPLEMENTATIESTATUS body)."""
    if not manifest.features:
        from ..core.prompt import scaffold_strings
        return scaffold_strings(language)["no_manifest"]
    recent = list(reversed(manifest.features[-15:]))
    lines = []
    for f in recent:
        icon = _STATUS_ICONS.get(f.status, "?")
        files_short = ", ".join(f.files[:3])
        more = f" +{len(f.files) - 3} more" if len(f.files) > 3 else ""
        lines.append(f"- [{icon}] {f.id} — {f.summary} ({files_short}{more})")
    return "\n".join(lines)


def topic_to_feature_id(topic: str) -> str:
    """Kebab-case feature id, max 40 chars (reference manifest.ts:150-158)."""
    s = re.sub(r"[^a-z0-9\s-]", "", topic.lower()).strip()
    s = re.sub(r"\s+", "-", s)[:40]
    return s.rstrip("-")


def get_feature_summary(session_path: str | Path, topic: str) -> str:
    """decisions.md first meaningful paragraph, else topic; 140-char cap
    (reference manifest.ts:164-183)."""
    decisions_path = Path(session_path) / "decisions.md"
    try:
        content = decisions_path.read_text(encoding="utf-8")
        lines = [l for l in content.split("\n")
                 if l.strip() and not l.startswith("#") and not l.startswith("---")]
        first = lines[0].strip() if lines else ""
        if len(first) > 10:
            return first[:137] + "..." if len(first) > 140 else first
    except OSError:
        pass
    return topic[:137] + "..." if len(topic) > 140 else topic
