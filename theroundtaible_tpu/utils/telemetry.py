"""Unified telemetry — span tracer, metrics registry, flight recorder.

The stack grew four private observability surfaces (PRs 1–4):
`describe()["int4_paths"]`, the scheduler's event log + occupancy
history, `fleet_health()`'s hang/breaker counts, and per-session
`metrics.json` — four formats an operator stitches by hand during an
incident. Production TPU serving engines treat tracing/metrics as ONE
first-class subsystem feeding live dashboards and postmortems alike
(RTP-LLM, arxiv 2605.29639), and TPU perf work is only credible with
xprof-aligned annotations (arxiv 2605.25645). This module is that
spine; the existing surfaces publish through it and become views.

Three pieces:

- **Span tracer** — explicit spans mirroring the PR-2 Budget tree
  (`discussion → round → turn → prefill|decode → segment → dispatch`)
  carrying session/knight/engine attributes. Spans nest via a
  thread-local stack; cross-thread hops (orchestrator batch pools, the
  scheduler thread) hand a `current_context()` dict across and attach
  it with `attached(ctx)`. Finished spans append to the per-session
  JSONL sink riding the span tree (root spans carry it; children
  inherit) and into the flight recorder; while a jax profiler trace is
  armed (`maybe_profile` → `set_profiling`), each span also opens a
  `jax.profiler.TraceAnnotation` so xprof timelines and JSONL spans
  line up on the same names. Disarmed, `span()` returns a no-op
  singleton behind the same module-flag pattern as `deadlines.ACTIVE`
  / `faults.ARMED` — hot call sites additionally pre-guard with
  `if telemetry.ACTIVE:`.
- **Metrics registry** — process-wide counters/gauges/histograms
  (decode tok/s, queue wait, batch occupancy, pages held, breaker
  state, hang/fault/fallback counts) with `snapshot()` for embedding
  in bench/flight records and `prometheus_text()` for the
  `<session>/telemetry/metrics.prom` file `roundtable status
  --telemetry` renders. Counters are cheap (one lock + dict add) and
  stay on regardless of ACTIVE: they fire per EVENT (admission, trip,
  hang), never per token.
- **Flight recorder** — a bounded ring of recent spans/events per
  named recorder. `flight_dump(trigger)` writes ring + registry
  snapshot to a JSON file and returns its path; deadlines (hang),
  faults (breaker trip), tpu_llm (ladder escalation) and fleet (drain)
  call it automatically, so every failure ships its own postmortem.

Host-only by design (no jax import at module load): deadlines/faults
import this without touching a backend, and the types stay usable in
pure-unit tests. Arming: `arm()` in-process or `ROUNDTABLE_TELEMETRY=1`
in the environment; `ROUNDTABLE_TELEMETRY_DIR` overrides where flight
dumps land.
"""

from __future__ import annotations

import json
import os
import tempfile
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

# Module-level guard — the ONLY thing unarmed hot paths touch (one
# attribute load + branch, same contract as deadlines.ACTIVE).
ACTIVE = False

# True while a jax profiler trace is running (utils/metrics.maybe_profile
# flips it): armed spans then mirror into jax.profiler.TraceAnnotation.
_PROFILING = False

# The span rungs, outermost first — the Budget tree (deadlines.RUNGS)
# plus the two sub-turn seams budgets don't name ("segment" sits between
# decode and dispatch; "profile" is maybe_profile's root). ISSUE 20
# adds the serving layer above the engine tree: "request" roots a
# gateway stream leg, "resume" roots a reconnect/restore leg joined to
# the original trace id (utils/tracing.py).
TRACE_RUNGS = ("profile", "request", "resume", "discussion", "round",
               "turn", "prefill", "decode", "segment", "dispatch")

_INF = float("inf")


def arm() -> None:
    global ACTIVE
    ACTIVE = True


def disarm() -> None:
    global ACTIVE
    ACTIVE = False


def set_profiling(on: bool) -> None:
    """maybe_profile's seam: while True, armed spans mirror into
    jax.profiler.TraceAnnotation so xprof and the JSONL tree share
    names (and, via the root span maybe_profile opens, one trace id)."""
    global _PROFILING
    _PROFILING = bool(on)


# ---------------------------------------------------------------------------
# metrics registry
# ---------------------------------------------------------------------------

# Wall-clock-ish histogram buckets (seconds): sub-10ms dispatches up to
# multi-minute turns. Fixed buckets keep observe() one bisect + add.
HIST_BUCKETS = (0.005, 0.01, 0.05, 0.1, 0.5, 1.0, 5.0, 10.0, 30.0,
                60.0, 120.0, 300.0)


def _label_key(labels: dict[str, Any]) -> tuple:
    return tuple(sorted((k, str(v)) for k, v in labels.items()))


class MetricsRegistry:
    """Process-wide counters/gauges/histograms, label-aware.

    One instance (`REGISTRY`) serves the whole process: schedulers,
    engines and adapters label their series (engine=..., point=...,
    rung=...) instead of owning private stores — the single-source-of-
    truth migration the four PR-1..4 surfaces converge on."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: dict[tuple[str, tuple], float] = {}
        self._gauges: dict[tuple[str, tuple], float] = {}
        self._hists: dict[tuple[str, tuple], dict] = {}

    # --- writes ---

    def inc(self, name: str, value: float = 1.0, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._counters[key] = self._counters.get(key, 0.0) + value

    def set_gauge(self, name: str, value: float, **labels) -> None:
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges[key] = float(value)

    def remove_gauge(self, name: str, **labels) -> None:
        """Drop one labeled gauge series. For per-entity series whose
        entities RETIRE (per-session KV footprints): a long-lived
        serving process must not accumulate one dead series per
        session ever served — zeroing would keep the label set (and
        the metrics.prom export) growing without bound."""
        key = (name, _label_key(labels))
        with self._lock:
            self._gauges.pop(key, None)

    def observe(self, name: str, value: float,
                exemplar: Optional[str] = None, **labels) -> None:
        """One histogram sample. `exemplar` (ISSUE 20) attaches a
        trace id to the bucket the sample lands in — last writer wins
        per bucket — so a bad p95/p99 bucket links to a CONCRETE trace
        instead of an anonymous count. Exemplars ride snapshot() and
        the exposition's bucket lines (OpenMetrics `# {...}` syntax,
        which the metrics.prom overlay parser already skips)."""
        key = (name, _label_key(labels))
        with self._lock:
            h = self._hists.get(key)
            if h is None:
                h = self._hists[key] = {
                    "counts": [0] * (len(HIST_BUCKETS) + 1),
                    "sum": 0.0, "count": 0}
            for i, b in enumerate(HIST_BUCKETS):
                if value <= b:
                    h["counts"][i] += 1
                    bucket = i
                    break
            else:
                h["counts"][-1] += 1
                bucket = len(HIST_BUCKETS)
            h["sum"] += value
            h["count"] += 1
            if exemplar:
                ex = h.get("exemplars")
                if ex is None:
                    ex = h["exemplars"] = {}
                ex[bucket] = {"trace_id": str(exemplar),
                              "value": round(float(value), 6)}

    # --- reads ---

    def counter_total(self, name: str, **labels) -> float:
        """Sum of a counter across label sets (or the one labeled set
        when labels are given)."""
        with self._lock:
            if labels:
                return self._counters.get((name, _label_key(labels)), 0.0)
            return sum(v for (n, _l), v in self._counters.items()
                       if n == name)

    def gauge_value(self, name: str, **labels) -> Optional[float]:
        with self._lock:
            return self._gauges.get((name, _label_key(labels)))

    def exemplars(self, name: str, **labels) -> dict[int, dict]:
        """bucket index → {"trace_id", "value"} for one histogram
        series (the trace-exemplar read side: `roundtable trace`
        links a slow bucket to its retained trace)."""
        with self._lock:
            h = self._hists.get((name, _label_key(labels)))
            if h is None:
                return {}
            return {int(k): dict(v)
                    for k, v in h.get("exemplars", {}).items()}

    def snapshot(self) -> dict[str, Any]:
        """Full structured snapshot (flight dumps, tests)."""

        def flat(store):
            out = {}
            for (name, lkey), v in sorted(store.items()):
                label = ",".join(f"{k}={val}" for k, val in lkey)
                out[f"{name}{{{label}}}" if label else name] = v
            return out

        with self._lock:
            return {
                "counters": flat(self._counters),
                "gauges": flat(self._gauges),
                "histograms": {
                    key: {
                        "sum": round(h["sum"], 6), "count": h["count"],
                        **({"exemplars": {
                            str(b): dict(e)
                            for b, e in h["exemplars"].items()}}
                           if h.get("exemplars") else {}),
                    }
                    for key, h in flat(self._hists).items()},
            }

    def snapshot_compact(self) -> dict[str, float]:
        """Counters + gauges as one flat dict — the bench-record embed
        (BENCH_r*.json carries occupancy/fallback/hang counters the way
        int4_paths rides today)."""
        snap = self.snapshot()
        out = dict(snap["counters"])
        out.update(snap["gauges"])
        return out

    def prometheus_text(self) -> str:
        """Prometheus exposition-format snapshot (the metrics.prom
        writer behind `roundtable status --telemetry`)."""
        lines: list[str] = []

        def fmt_labels(lkey):
            if not lkey:
                return ""
            body = ",".join(f'{k}="{v}"' for k, v in lkey)
            return "{" + body + "}"

        with self._lock:
            counters = sorted(self._counters.items())
            gauges = sorted(self._gauges.items())
            hists = sorted(self._hists.items())
        seen: set[str] = set()
        for (name, lkey), v in counters:
            if name not in seen:
                lines.append(f"# TYPE {name} counter")
                seen.add(name)
            lines.append(f"{name}{fmt_labels(lkey)} {v:g}")
        for (name, lkey), v in gauges:
            if name not in seen:
                lines.append(f"# TYPE {name} gauge")
                seen.add(name)
            lines.append(f"{name}{fmt_labels(lkey)} {v:g}")
        for (name, lkey), h in hists:
            if name not in seen:
                lines.append(f"# TYPE {name} histogram")
                seen.add(name)
            def ex_suffix(bucket: int) -> str:
                # OpenMetrics exemplar on the bucket line; the
                # metrics.prom overlay parser skips _bucket lines, so
                # this never perturbs `status --perf/--kv` series.
                e = h.get("exemplars", {}).get(bucket)
                if not e:
                    return ""
                return (f' # {{trace_id="{e["trace_id"]}"}} '
                        f'{e["value"]:g}')

            cum = 0
            for i, b in enumerate(HIST_BUCKETS):
                cum += h["counts"][i]
                le = (("le", f"{b:g}"),)
                lines.append(
                    f"{name}_bucket{fmt_labels(lkey + le)} {cum}"
                    f"{ex_suffix(i)}")
            cum += h["counts"][-1]
            lines.append(
                f'{name}_bucket{fmt_labels(lkey + (("le", "+Inf"),))} '
                f"{cum}{ex_suffix(len(HIST_BUCKETS))}")
            lines.append(f"{name}_sum{fmt_labels(lkey)} {h['sum']:g}")
            lines.append(f"{name}_count{fmt_labels(lkey)} {h['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._hists.clear()


REGISTRY = MetricsRegistry()

# Module-level shorthands (call sites read better; one shared registry).
inc = REGISTRY.inc
set_gauge = REGISTRY.set_gauge
remove_gauge = REGISTRY.remove_gauge
observe = REGISTRY.observe
counter_total = REGISTRY.counter_total


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------

_FLIGHT_CAPACITY = int(os.environ.get("ROUNDTABLE_FLIGHT_CAPACITY",
                                      "512"))


# Dumps kept on disk per process lifetime of pruning calls: each dump()
# trims the dump dir to this many newest files, so a crash-looping
# serve can't fill the disk with postmortems of the same incident.
_DUMP_KEEP = int(os.environ.get("ROUNDTABLE_FLIGHT_DUMPS_KEEP", "64"))


class FlightRecorder:
    """Bounded rings of recent events and spans; `dump()` ships both +
    a registry snapshot to disk so a hang/trip/drain carries its own
    postmortem. Recording is a lock + deque append — cheap enough to
    stay on for EVENT-rate callers (admissions, trips, retirements);
    per-token paths never record. Spans ride a SEPARATE ring from
    decision events: an armed long decode emits hundreds of span
    records, and they must not evict the sched_admit/preempt/breaker
    history the dump exists to preserve."""

    def __init__(self, name: str = "process",
                 capacity: int = _FLIGHT_CAPACITY):
        self.name = name
        self._ring: deque[dict] = deque(maxlen=max(capacity, 8))
        self._spans: deque[dict] = deque(maxlen=max(capacity, 8))
        self._lock = threading.Lock()
        self.dumps = 0          # SUCCESSFUL dumps only (health surfaces)
        self._seq = 0           # filename counter (attempts, unique)
        self.last_dump_path: str = ""

    def record(self, kind: str, **fields) -> None:
        entry = {"kind": kind, "at": round(time.time(), 3)}
        entry.update(fields)
        with self._lock:
            if kind == "span":
                self._spans.append(entry)
            else:
                self._ring.append(entry)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._ring)

    def span_events(self) -> list[dict]:
        with self._lock:
            return list(self._spans)

    def clear(self) -> None:
        with self._lock:
            self._ring.clear()
            self._spans.clear()

    def dump(self, trigger: str,
             extra: Optional[dict] = None) -> str:
        """Write both rings + a registry snapshot to the dump dir;
        returns the file path ('' when the write itself fails — a
        postmortem must never add a second failure on top of the
        first, and a failed write is NOT counted in `dumps`)."""
        payload = {
            "trigger": trigger,
            "recorder": self.name,
            "at": time.time(),
            "pid": os.getpid(),
            "events": self.events(),
            "spans": self.span_events(),
            "metrics": REGISTRY.snapshot(),
        }
        if extra:
            payload["extra"] = extra
        try:
            # Perf-attribution block (ISSUE 6): roofline/memory series,
            # span overheads, compile-observatory summary. Lazy import —
            # telemetry stays importable standalone, and an attribution
            # failure must never cost the postmortem its write.
            from . import perfmodel
            payload["perf"] = perfmodel.attribution_snapshot()
        except Exception:  # noqa: BLE001 — the dump itself comes first
            pass
        with self._lock:
            self._seq += 1
            seq = self._seq
        try:
            d = dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"flight-{trigger}-{os.getpid()}-{seq:03d}.json")
            with open(path, "w", encoding="utf-8") as f:
                json.dump(payload, f, indent=2, default=str)
            _prune_dumps(d)
        except OSError:
            return ""
        with self._lock:
            self.dumps += 1
            self.last_dump_path = path
        inc("roundtable_flight_dumps_total", trigger=trigger)
        return path


def _prune_dumps(d: str) -> None:
    """Keep only the newest _DUMP_KEEP flight dumps in `d` — every dump
    call pays one listdir so the dir can never grow without bound."""
    try:
        files = sorted(
            (p for p in os.listdir(d)
             if p.startswith("flight-") and p.endswith(".json")),
            key=lambda p: os.path.getmtime(os.path.join(d, p)))
        for p in files[:-_DUMP_KEEP] if _DUMP_KEEP > 0 else []:
            os.unlink(os.path.join(d, p))
    except OSError:
        pass  # pruning is best-effort; the dump already landed


_recorders: dict[str, FlightRecorder] = {}
_recorders_lock = threading.Lock()


def recorder(name: str = "process") -> FlightRecorder:
    """Get-or-create a named flight recorder ("process" is the shared
    default; engines may key their own by engine name)."""
    with _recorders_lock:
        rec = _recorders.get(name)
        if rec is None:
            rec = _recorders[name] = FlightRecorder(name)
        return rec


def flight_dump(trigger: str, name: str = "process",
                extra: Optional[dict] = None) -> str:
    """Dump a named recorder (default the process one); returns path."""
    return recorder(name).dump(trigger, extra=extra)


def last_dump_path() -> str:
    return recorder().last_dump_path


def dump_dir() -> str:
    """Where flight dumps land: ROUNDTABLE_TELEMETRY_DIR, else a
    uid-suffixed dir under the system tempdir (a hang must produce a
    dump even with no session directory in sight; the uid suffix keeps
    two users on one host from fighting over directory ownership —
    without it the second user's every dump would die on
    PermissionError and be silently swallowed)."""
    configured = os.environ.get("ROUNDTABLE_TELEMETRY_DIR")
    if configured:
        return configured
    uid = os.getuid() if hasattr(os, "getuid") else "na"
    return os.path.join(tempfile.gettempdir(),
                        f"roundtable-telemetry-{uid}")


# ---------------------------------------------------------------------------
# span tracer
# ---------------------------------------------------------------------------

_tls = threading.local()

# Emitted-span counter (tests/conftest.py `telemetry` marker guard: a
# marked test that claims span coverage must actually emit spans).
_spans_emitted = 0
_spans_lock = threading.Lock()


def spans_emitted() -> int:
    return _spans_emitted


def reset_spans_emitted() -> None:
    global _spans_emitted
    with _spans_lock:
        _spans_emitted = 0


def _stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


class SpanSink:
    """Append-only JSONL span sink (one per session: the root span
    carries it and children inherit — per-session files work across the
    thread hops the serving stack makes)."""

    def __init__(self, path: str):
        self.path = str(path)
        self._lock = threading.Lock()

    def write(self, record: dict) -> None:
        try:
            with self._lock:
                os.makedirs(os.path.dirname(self.path), exist_ok=True)
                with open(self.path, "a", encoding="utf-8") as f:
                    f.write(json.dumps(record, default=str) + "\n")
        except OSError:
            pass  # telemetry must never kill serving


def session_sink(session_path) -> SpanSink:
    """The per-session spans file: <session>/telemetry/spans.jsonl."""
    return SpanSink(os.path.join(str(session_path), "telemetry",
                                 "spans.jsonl"))


class Span:
    """One span of the trace tree. Context manager for the common
    same-thread case; `start_span()`/`.end()` for holders that outlive
    a lexical scope (the scheduler's per-request turn spans)."""

    __slots__ = ("rung", "trace_id", "span_id", "parent_id", "attrs",
                 "sink", "t0", "_wall0", "status", "_annotation",
                 "_on_stack")

    def __init__(self, rung: str, trace_id: str, parent_id: str,
                 sink: Optional[SpanSink], attrs: dict[str, Any]):
        self.rung = rung
        self.trace_id = trace_id
        self.span_id = uuid.uuid4().hex[:12]
        self.parent_id = parent_id
        self.attrs = attrs
        self.sink = sink
        self.t0 = time.monotonic()
        self._wall0 = time.time()
        self.status = "ok"
        self._annotation = None
        self._on_stack = False
        if _PROFILING:
            # Mirror into the device profile: xprof rows named like the
            # JSONL rungs. Lazy import; any failure silently drops the
            # mirror (profiling is best-effort by standing contract).
            try:
                import jax
                self._annotation = jax.profiler.TraceAnnotation(
                    f"rt:{rung}")
                self._annotation.__enter__()
            except Exception:  # noqa: BLE001 — mirror is best-effort
                self._annotation = None

    def set_attr(self, key: str, value: Any) -> None:
        self.attrs[key] = value

    # --- context-manager protocol (same-thread nesting) ---

    def __enter__(self) -> "Span":
        _stack().append(self)
        self._on_stack = True
        return self

    def __exit__(self, exc_type, exc, tb) -> bool:
        if exc is not None:
            self.status = f"error:{type(exc).__name__}"
        self.end()
        return False

    def end(self, status: Optional[str] = None) -> None:
        if status is not None:
            self.status = status
        if self._on_stack:
            stack = _stack()
            if stack and stack[-1] is self:
                stack.pop()
            elif self in stack:  # unbalanced exit: drop it anyway
                stack.remove(self)
            self._on_stack = False
        if self._annotation is not None:
            try:
                self._annotation.__exit__(None, None, None)
            except Exception:  # noqa: BLE001
                pass
            self._annotation = None
        record = {
            "trace_id": self.trace_id,
            "span_id": self.span_id,
            "parent_id": self.parent_id,
            "rung": self.rung,
            "start": round(self._wall0, 6),
            "dur_s": round(time.monotonic() - self.t0, 6),
            "status": self.status,
        }
        if self.attrs:
            record["attrs"] = self.attrs
        if self.sink is not None:
            self.sink.write(record)
        ring = {"rung": self.rung, "trace_id": self.trace_id,
                "span_id": self.span_id, "parent_id": self.parent_id,
                "dur_s": record["dur_s"], "status": self.status}
        for k, v in self.attrs.items():
            if k not in ("kind", "at") and isinstance(
                    v, (str, int, float, bool)):
                ring.setdefault(k, v)
        recorder().record("span", **ring)
        global _spans_emitted
        with _spans_lock:
            _spans_emitted += 1


class _NullSpan:
    """The disarmed singleton: every operation a no-op, reentrant and
    thread-safe because it holds no state."""

    __slots__ = ()
    rung = ""
    trace_id = span_id = parent_id = ""
    sink = None

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False

    def set_attr(self, key, value):
        pass

    def end(self, status=None):
        pass


_NULL_SPAN = _NullSpan()


class _AttachedContext:
    """A foreign span context installed on this thread's stack so spans
    opened here parent correctly across a thread hop (orchestrator
    batch pools, scheduler submitters). Not emitted on exit — the real
    span lives on its own thread."""

    __slots__ = ("trace_id", "span_id", "sink", "rung")

    def __init__(self, ctx: dict):
        self.trace_id = ctx.get("trace_id", "")
        self.span_id = ctx.get("span_id", "")
        self.rung = ctx.get("rung", "")
        sink = ctx.get("sink")
        self.sink = sink if isinstance(sink, SpanSink) else None


def current_context() -> Optional[dict]:
    """A picklable-ish handle to the innermost span, for handing across
    threads: `ctx = telemetry.current_context()` on the parent thread,
    `with telemetry.attached(ctx):` on the worker."""
    stack = _stack()
    if not stack:
        return None
    top = stack[-1]
    return {"trace_id": top.trace_id, "span_id": top.span_id,
            "rung": top.rung, "sink": top.sink}


class attached:
    """Context manager installing a foreign span context as this
    thread's parent. A None ctx is a no-op (callers pass
    current_context()'s result straight through)."""

    def __init__(self, ctx: Optional[dict]):
        self._ctx = ctx
        self._pushed = None

    def __enter__(self):
        if ACTIVE and self._ctx:
            self._pushed = _AttachedContext(self._ctx)
            _stack().append(self._pushed)
        return self

    def __exit__(self, *exc):
        if self._pushed is not None:
            stack = _stack()
            if stack and stack[-1] is self._pushed:
                stack.pop()
            elif self._pushed in stack:
                stack.remove(self._pushed)
            self._pushed = None
        return False


def span(rung: str, sink: Optional[SpanSink] = None,
         parent: Optional[dict] = None, **attrs):
    """Open a span at `rung`. Disarmed: the no-op singleton (call sites
    on hot paths additionally pre-guard with `if telemetry.ACTIVE:`).
    Armed: parented to `parent` (a current_context() dict) when given,
    else this thread's innermost span; roots mint a fresh trace id.
    `sink` overrides the inherited JSONL sink (roots set it)."""
    if not ACTIVE:
        return _NULL_SPAN
    return start_span(rung, sink=sink, parent=parent, **attrs)


def start_span(rung: str, sink: Optional[SpanSink] = None,
               parent: Optional[dict] = None, **attrs) -> Span:
    """Like span() but always real (callers that hold a span across
    ticks and end() it manually — check ACTIVE yourself)."""
    if parent is not None:
        trace_id = parent.get("trace_id") or uuid.uuid4().hex[:16]
        parent_id = parent.get("span_id", "")
        psink = parent.get("sink")
        inherited = psink if isinstance(psink, SpanSink) else None
    else:
        stack = _stack()
        top = stack[-1] if stack else None
        trace_id = top.trace_id if top else uuid.uuid4().hex[:16]
        parent_id = top.span_id if top else ""
        inherited = top.sink if top else None
    return Span(rung, trace_id, parent_id,
                sink if sink is not None else inherited, attrs)


# ---------------------------------------------------------------------------
# observability-surface bindings (single-source-of-truth drift lint)
# ---------------------------------------------------------------------------

# Every key an observability surface exposes maps to the registry
# series (or derivation) that backs it. The drift test
# (tests/test_telemetry.py) asserts the ACTUAL keys of fleet_health()
# and SessionScheduler.describe() are a subset of these — adding a new
# surface key without declaring how the registry sees it fails CI, so
# the four stores can never quietly fork again. The static analyzer
# enforces the same contract at parse time with file/line findings
# (`roundtable lint`, rule RT-SURFACE-DRIFT — it reads this dict
# LITERAL, so keep it a plain literal of string keys).
SURFACE_BINDINGS: dict[str, dict[str, str]] = {
    "fleet_health": {
        "engines": "roundtable_breaker_failures_total{engine=...} "
                   "(per-breaker snapshots; trips under "
                   "roundtable_breaker_trips_total)",
        "total": "len(engines)",
        "open": "roundtable_breaker_open{engine=...} gauge",
        "degraded": "derived from breaker snapshots",
        "draining": "roundtable_draining gauge",
        "hangs": "roundtable_hangs_total",
        "schedulers": "roundtable_sched_* series, engine-labeled",
        "queued_sessions": "roundtable_sched_queue_depth gauge sum",
        "telemetry": "registry snapshot view (this module)",
        "perf": "roundtable_compiles_total / "
                "roundtable_steady_state_compiles_total series "
                "(engine/compile_watch summary roll-up)",
        # ISSUE 12: the supervisor's restart history roll-up —
        # counters move in lockstep with EngineSupervisor._finish /
        # _mark_dead (the single writers for both stores).
        "supervisor": "roundtable_engine_restarts_total{reason=...} / "
                      "roundtable_engine_restart_seconds / "
                      "roundtable_sessions_recovered_total / "
                      "roundtable_sessions_lost_total / "
                      "roundtable_engine_dead gauge "
                      "(engine/supervisor snapshot)",
        # ISSUE 17: the session router's fleet view (None without a
        # router) — assignment counts + migration/failover/roll
        # counters, replica-labeled, dropped at retire.
        "router": "roundtable_router_sessions{replica=...} gauge / "
                  "roundtable_router_migrations_total / "
                  "roundtable_router_failovers_total / "
                  "roundtable_router_rolls_total "
                  "(router/core SessionRouter.describe)",
    },
    "scheduler_describe": {
        "admitted": "roundtable_sched_admitted_total",
        "refused": "roundtable_sched_refused_total",
        "completed": "roundtable_sched_completed_total",
        "failed": "roundtable_sched_failed_total",
        "rejected_draining": "roundtable_sched_rejected_draining_total",
        "rejected_other": "roundtable_sched_rejected_other_total",
        "deadline_expired": "roundtable_sched_deadline_expired_total",
        "preemptions": "roundtable_sched_preemptions_total",
        "segments": "roundtable_sched_segments_total",
        "ragged_segments": "roundtable_sched_ragged_segments_total",
        "ragged_joins": "roundtable_sched_ragged_joins_total",
        "spec_segments": "roundtable_sched_spec_segments_total",
        "segment_prefill_tokens":
            "roundtable_segment_prefill_tokens_total",
        "segment_decode_tokens":
            "roundtable_segment_decode_tokens_total",
        "requeues": "roundtable_sched_requeues_total",
        "queued": "roundtable_sched_queue_depth gauge",
        "queued_peak": "max over roundtable_sched_queue_depth",
        "active_rows": "roundtable_sched_active_rows gauge",
        "max_occupancy": "max over roundtable_sched_occupancy gauge",
        "occupancy_mean": "mean over roundtable_sched_occupancy gauge",
        "occupancy_recent": "ring view (flight recorder carries events)",
        "spills": "roundtable_sched_spills_total",
        "spilled_sessions": "roundtable_kv_spilled_sessions gauge "
                            "(kv_offload tier)",
        # ISSUE 12: admission-gate + durable-journal provenance.
        "paused": "pause_admission/reopen_admission flight events "
                  "(gate reason string; None = open)",
        # ISSUE 16: machine-readable admission state for the gateway's
        # shed ladder (nested dict; the pause reason + queue depth).
        "admission": "derived (paused reason + "
                     "roundtable_sched_queue_depth gauge)",
        "journal_turns": "roundtable_journal_turns_total "
                         "(counter is fleet-wide; the describe key is "
                         "THIS scheduler's share)",
        "journal_errors": "roundtable_journal_errors_total "
                          "(same per-scheduler split)",
        "events": "flight recorder ring (sched_* kinds)",
    },
    # engine.describe()["spec_decode"] (ISSUE 9 + 13): the speculation
    # provenance sink's registry bindings — drafted/accepted/rejected
    # counters move in lockstep with the describe() totals
    # (engine.note_spec_dispatch is the one writer for both). ISSUE 13:
    # every counter/gauge carries a `drafter` label (ngram|model|lora)
    # so dashboards attribute an acceptance collapse to the PROPOSER,
    # not the throttle; the active drafter + tree shape ride describe()
    # so a snapshot says which proposer produced the numbers.
    "engine_spec_decode": {
        "drafter": "label value on every roundtable_spec_* series",
        "drafter_reason": "derived (drafter-availability fallback; "
                          "describe-only)",
        "tree": "static config (branch x depth); labels "
                "roundtable_spec_tree_nodes_total",
        "drafted_tokens": "roundtable_spec_drafted_tokens_total"
                          "{drafter=...}",
        "accepted_tokens": "roundtable_spec_accepted_tokens_total"
                           "{drafter=...}",
        "rejected_tokens": "roundtable_spec_rejected_tokens_total"
                           "{drafter=...}",
        "acceptance_rate": "roundtable_spec_acceptance_rate gauge "
                           "(per-drafter: labeled with the drafter "
                           "whose dispatches moved it)",
        "by_drafter": "per-drafter split of the drafted/accepted "
                      "counters (same writer)",
        "throttled_rows": "spec_throttle flight events (one per trip)",
        "tree_nodes": "roundtable_spec_tree_nodes_total{drafter=...}",
        "tree_rows": "derived (tree-row share of verify dispatches)",
        "draft_dispatches": "ragged provenance ring entries with "
                            "draft=True (DeviceDrafter counter)",
        "verify_dispatches": "roundtable_sched_spec_segments_total "
                             "(+ warmup dispatches)",
    },
    # engine.describe()["lora"] (ISSUE 10): the multi-LoRA persona
    # provenance sink's registry bindings — residency/swap counters
    # move in lockstep with the store's describe() totals (LoraStore
    # load/evict and engine.note_lora_tokens are the single writers).
    "engine_lora": {
        "apply_tokens": "roundtable_lora_apply_tokens_total",
        "swaps": "roundtable_lora_swaps_total",
        "resident": "roundtable_lora_resident_adapters gauge",
        "adapter_bytes": "roundtable_lora_adapter_bytes{adapter=...} "
                         "gauge (REMOVED at evict)",
        "stack_bytes": "roundtable_lora_stack_bytes gauge "
                       "(memory-ledger publish)",
        "share_suppressed": "derived (engine counter; lora_describe)",
    },
    # Gateway.describe() (ISSUE 16): the HTTP front door's admission /
    # shed / stream provenance — counters move in lockstep with the
    # registry series (AdmissionController._count is the one writer).
    "gateway": {
        "admitted": "roundtable_gateway_admitted_total{reason=...}",
        "shed": "roundtable_gateway_shed_total{reason=...}",
        "queued": "roundtable_gateway_queued_total{reason=...}",
        "expired": "roundtable_gateway_expired_total{reason=...}",
        "inflight": "roundtable_gateway_inflight_streams gauge "
                    "(request-labeled; REMOVED per-stream at close)",
        "draining": "roundtable_draining gauge (fleet drain state "
                    "mirrored at the HTTP boundary)",
        "resumed_streams": "roundtable_gateway_resumed_streams_total",
        "dropped_events": "roundtable_gateway_dropped_events_total "
                          "(slow-consumer drop-to-summary)",
        "sessions": "derived (live stream table size)",
        "host": "static config (bind address)",
        "port": "static config (bind port)",
        # ISSUE 17: router fleets only — per-replica roll-up; the
        # underlying series carry a `replica=` label and are REMOVED
        # when SessionRouter.retire drops the replica.
        "replicas": "roundtable_router_sessions{replica=...} gauge / "
                    "roundtable_router_migrations_total / "
                    "roundtable_router_failovers_total / "
                    "roundtable_router_rolls_total{replica=...}",
        # ISSUE 20: the SLO burn-rate monitor's live state — the gauge
        # moves in lockstep with SloBurnMonitor._note (one writer).
        "slo": "roundtable_slo_burn_rate{window=fast|slow} gauge / "
               "roundtable_slo_breaches_total "
               "(utils/tracing SloBurnMonitor.describe)",
        # ISSUE 20: end-to-end tracing provenance — retained-trace
        # counter plus the TTFT stage decomposition the traces carry.
        "tracing": "roundtable_traces_retained_total{outcome=...} / "
                   "roundtable_gateway_ttft_seconds histogram "
                   "(trace-id exemplars; utils/tracing store)",
    },
    # `roundtable status --capacity` (ISSUE 19): the measured
    # capacity frontier (CAPACITY_r19.json / the record behind
    # ROUNDTABLE_GATEWAY_CAPACITY_FILE) joined with the live gateway
    # ledger — commands/status.py capacity_surface() is the one
    # builder of this shape.
    "capacity_status": {
        "record_path": "static (which frontier record was loaded)",
        "knee_rate": "frontier record knee.rate (file-based; the "
                     "sweep that produced it ran the registry live)",
        "knee_ttft_p95_s": "frontier record knee.ttft_p95_s",
        "measured_tok_s": "frontier record knee.accepted_tok_s",
        "predicted_tok_s": "frontier record predicted."
                           "decode_ceiling_tps (perfmodel roofline; "
                           "roundtable_decode_ceiling_tps gauge when "
                           "serving live)",
        "gap_frac": "frontier record gap.gap_frac (span_overheads "
                    "attribution rides gap.overheads)",
        "derived_thresholds": "frontier record derived_thresholds "
                              "(what admission loads through "
                              "ROUNDTABLE_GATEWAY_CAPACITY_FILE)",
        "points": "len(frontier record points)",
        "live_inflight": "roundtable_gateway_inflight_streams gauge "
                         "(series count)",
        "live_admitted": "roundtable_gateway_admitted_total"
                         "{reason=...} sum",
        "live_shed": "roundtable_gateway_shed_total{reason=...} sum",
        "record_errors":
            "roundtable_gateway_capacity_record_errors_total "
            "(malformed-record loud-degrade counter)",
    },
    # `roundtable status --slo` (ISSUE 20): the burn-rate monitor's
    # machine shape — capacity-record SLO baseline joined with the
    # live burn gauges; commands/status.py slo_surface() is the one
    # builder (statically drift-bound like capacity_status).
    "slo_status": {
        "armed": "derived (p95_slo_s > 0)",
        "p95_slo_s": "capacity record derived_thresholds.p95_slo_s "
                     "(the admission SLO baseline)",
        "source": "static (default | capacity_record)",
        "record_path": "static (which frontier record was loaded)",
        "error_budget": "static config "
                        "(ROUNDTABLE_SLO_ERROR_BUDGET)",
        "threshold": "static config "
                     "(ROUNDTABLE_SLO_BURN_THRESHOLD)",
        "burn_fast": "roundtable_slo_burn_rate{window=fast} gauge",
        "burn_slow": "roundtable_slo_burn_rate{window=slow} gauge",
        "breaches": "roundtable_slo_breaches_total",
        "slo_dumps": "roundtable_flight_dumps_total"
                     "{trigger=slo_burn}",
        "traces_retained": "roundtable_traces_retained_total"
                           "{outcome=...} sum",
    },
}


def registry_view() -> dict[str, Any]:
    """The roll-up fleet_health()/describe() embed: counters + gauges
    plus flight-recorder state, so the one store is visible from the
    surfaces operators already poll."""
    rec = recorder()
    return {
        "metrics": REGISTRY.snapshot_compact(),
        "flight_dumps": rec.dumps,
        "last_flight_dump": rec.last_dump_path,
        "spans_emitted": spans_emitted(),
        "armed": ACTIVE,
    }


if os.environ.get("ROUNDTABLE_TELEMETRY"):
    arm()
