"""Non-blocking package-update check, silent on any failure.

Parity with reference src/utils/update-check.ts:8-51 (npm registry check with
a 3s abort): we query PyPI for the latest published version and compare.
Runs in a daemon thread so CLI startup is never delayed; result is delivered
via callback only if a newer version exists.
"""

from __future__ import annotations

import json
import threading
import urllib.request
from typing import Callable, Optional

from .. import __version__

CHECK_TIMEOUT_SECONDS = 3
PYPI_URL = "https://pypi.org/pypi/theroundtaible-tpu/json"


def _parse_version(v: str) -> tuple[int, ...]:
    parts = []
    for piece in v.split("."):
        digits = "".join(ch for ch in piece if ch.isdigit())
        parts.append(int(digits) if digits else 0)
    return tuple(parts)


def _is_newer(latest: str, current: str) -> bool:
    return _parse_version(latest) > _parse_version(current)


def _check(on_update: Callable[[str, str], None]) -> None:
    try:
        with urllib.request.urlopen(PYPI_URL,
                                    timeout=CHECK_TIMEOUT_SECONDS) as resp:
            data = json.loads(resp.read().decode("utf-8"))
        latest = data.get("info", {}).get("version", "")
        if latest and _is_newer(latest, __version__):
            on_update(__version__, latest)
    except Exception:
        pass  # silent by design — never disturb the CLI


def check_for_update(on_update: Callable[[str, str], None]
                     ) -> Optional[threading.Thread]:
    """Fire-and-forget update check (reference update-check.ts:8-39)."""
    t = threading.Thread(target=_check, args=(on_update,), daemon=True)
    t.start()
    return t
