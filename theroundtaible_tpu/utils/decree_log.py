"""King's Decree Log — append-only record of rejected/deferred decisions.

Parity with reference src/utils/decree-log.ts:1-103. Decrees are injected into
knight prompts so rejected ideas are not re-proposed without addressing the
rejection reason.
"""

from __future__ import annotations

import json
import re
from pathlib import Path
from typing import Optional

from ..core.types import DecreeEntry, DecreeLog
from .session import now_iso

DECREE_LOG_RELPATH = Path(".roundtable") / "decree-log.json"

_ID_RE = re.compile(r"^decree-(\d+)$")


def read_decree_log(project_root: str | Path) -> DecreeLog:
    log_path = Path(project_root) / DECREE_LOG_RELPATH
    if not log_path.exists():
        return DecreeLog()
    try:
        parsed = json.loads(log_path.read_text(encoding="utf-8"))
        if parsed.get("version") == "1.0" and isinstance(parsed.get("entries"), list):
            return DecreeLog.from_dict(parsed)
    except (json.JSONDecodeError, OSError):
        pass
    return DecreeLog()


def _next_decree_id(log: DecreeLog) -> str:
    max_num = 0
    for e in log.entries:
        m = _ID_RE.match(e.id)
        if m:
            max_num = max(max_num, int(m.group(1)))
    return f"decree-{max_num + 1:03d}"


def add_decree_entry(project_root: str | Path, type_: str, session: str,
                     topic: str, reason: Optional[str] = None) -> DecreeEntry:
    """Append one decree (reference decree-log.ts:48-73). The read-
    modify-write runs under a PID-stale-aware lock (utils/lock.py) and
    the write is atomic (a crash mid-write must not truncate the log)."""
    from .lock import FileLock
    from .session import atomic_write_text

    log_path = Path(project_root) / DECREE_LOG_RELPATH
    log_path.parent.mkdir(parents=True, exist_ok=True)
    with FileLock(log_path):
        log = read_decree_log(project_root)
        entry = DecreeEntry(
            id=_next_decree_id(log),
            type=type_,
            session=session,
            topic=topic,
            reason=(reason or "").strip() or "No reason provided",
            revoked=False,
            date=now_iso(),
        )
        log.entries.append(entry)
        atomic_write_text(log_path,
                          json.dumps(log.to_dict(), indent=2) + "\n")
    return entry


def revoke_decree(project_root: str | Path, decree_id: str) -> bool:
    """Mark a decree revoked so it stops being injected into prompts.
    Same lock as add_decree_entry — an advisory lock only serializes
    writers that all take it."""
    from .lock import FileLock
    from .session import atomic_write_text

    log_path = Path(project_root) / DECREE_LOG_RELPATH
    with FileLock(log_path):
        log = read_decree_log(project_root)
        for e in log.entries:
            if e.id == decree_id:
                e.revoked = True
                atomic_write_text(
                    log_path, json.dumps(log.to_dict(), indent=2) + "\n")
                return True
    return False


def get_active_decrees(log: DecreeLog, max_entries: int = 5) -> list[DecreeEntry]:
    """Last `max_entries` non-revoked decrees (reference decree-log.ts:79-83)."""
    active = [e for e in log.entries if not e.revoked]
    return active[-max_entries:]


def format_decrees_for_prompt(decrees: list[DecreeEntry],
                              language: str = "en") -> str:
    """Prompt injection block (reference decree-log.ts:89-103; its
    banner is Dutch — ours localizes with the session language)."""
    if not decrees:
        return ""
    from ..core.prompt import scaffold_strings
    lines = []
    for d in decrees:
        date_short = d.date[:10]
        topic_short = d.topic[:47] + "..." if len(d.topic) > 50 else d.topic
        lines.append(f'- [{d.id}] {d.type.upper()} — "{topic_short}": '
                     f'"{d.reason}" ({date_short})')
    return "\n".join([scaffold_strings(language)["decrees_banner"],
                      *lines])
