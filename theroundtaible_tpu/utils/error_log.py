"""Code-red error log — `.roundtable/error-log.md` with CR-XXX entries.

Reference behavior: "Error log management (CR-XXX, OPEN/RESOLVED/PARKED)"
(reference TODO.md:60; README.md:159-175). Markdown, append-plus-status-
update: new incidents append an OPEN entry; outcomes flip the status line.
"""

from __future__ import annotations

import re
from datetime import datetime, timezone
from pathlib import Path
from typing import Optional

ERROR_LOG_RELPATH = Path(".roundtable") / "error-log.md"

_HEADER = "# Code-Red Error Log\n\n"
_ID_RE = re.compile(r"^## (CR-\d{3})\b", re.MULTILINE)
_STATUS_VALUES = ("OPEN", "RESOLVED", "PARKED")


def _log_path(project_root: str | Path) -> Path:
    return Path(project_root) / ERROR_LOG_RELPATH


def read_error_log(project_root: str | Path) -> str:
    p = _log_path(project_root)
    return p.read_text(encoding="utf-8") if p.exists() else ""


def next_cr_id(project_root: str | Path) -> str:
    content = read_error_log(project_root)
    nums = [int(m[3:]) for m in _ID_RE.findall(content)]
    return f"CR-{(max(nums) + 1 if nums else 1):03d}"


def add_error_entry(project_root: str | Path, symptoms: str,
                    diagnosis: Optional[str], status: str = "OPEN",
                    session: str = "") -> str:
    """Append one CR entry; returns its id."""
    assert status in _STATUS_VALUES
    cr_id = next_cr_id(project_root)
    date = datetime.now(timezone.utc).strftime("%Y-%m-%d")
    lines = [
        f"## {cr_id} — {date}",
        f"**Status:** {status}",
        f"**Symptoms:** {symptoms}",
    ]
    if session:
        lines.append(f"**Session:** {session}")
    if diagnosis:
        lines.append(f"\n{diagnosis}")
    lines.append("\n---\n")
    p = _log_path(project_root)
    p.parent.mkdir(parents=True, exist_ok=True)
    existing = read_error_log(project_root) or _HEADER
    p.write_text(existing + "\n".join(lines) + "\n", encoding="utf-8")
    return cr_id


def set_entry_status(project_root: str | Path, cr_id: str,
                     status: str) -> bool:
    """Flip one entry's **Status:** line (OPEN → RESOLVED/PARKED)."""
    assert status in _STATUS_VALUES
    content = read_error_log(project_root)
    if f"## {cr_id}" not in content:
        return False
    pattern = re.compile(
        rf"(## {re.escape(cr_id)}[^\n]*\n\*\*Status:\*\* )\w+")
    new = pattern.sub(rf"\g<1>{status}", content)
    _log_path(project_root).write_text(new, encoding="utf-8")
    return True


def count_by_status(project_root: str | Path) -> dict[str, int]:
    content = read_error_log(project_root)
    counts = {s: 0 for s in _STATUS_VALUES}
    for m in re.finditer(r"^\*\*Status:\*\* (\w+)", content, re.MULTILINE):
        if m.group(1) in counts:
            counts[m.group(1)] += 1
    return counts
