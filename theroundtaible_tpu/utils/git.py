"""Git context helpers — branch, diff, recent commits.

Parity with reference src/utils/git.ts:1-41: every helper is failure-tolerant
and returns None when git is absent or the cwd is not a repository.
"""

from __future__ import annotations

import subprocess
from typing import Optional


def _run_git(args: list[str], cwd: Optional[str] = None) -> Optional[str]:
    try:
        proc = subprocess.run(
            ["git", *args], capture_output=True, text=True, timeout=15, cwd=cwd,
        )
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return proc.stdout


def get_git_branch(cwd: Optional[str] = None) -> Optional[str]:
    out = _run_git(["rev-parse", "--abbrev-ref", "HEAD"], cwd)
    return out.strip() if out else None


def get_git_diff(cwd: Optional[str] = None) -> Optional[str]:
    """Staged + unstaged diff concatenated (reference git.ts:18-27)."""
    staged = _run_git(["diff", "--cached"], cwd)
    unstaged = _run_git(["diff"], cwd)
    parts = [p for p in (staged, unstaged) if p]
    combined = "\n".join(parts)
    return combined or None


def get_recent_commits(n: int = 5, cwd: Optional[str] = None) -> Optional[str]:
    out = _run_git(["log", "--oneline", f"-{n}"], cwd)
    if out is None:
        return None
    return out.strip() or None
