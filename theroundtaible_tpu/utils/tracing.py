"""End-to-end request tracing (ISSUE 20): trace-context propagation,
critical-path TTFT attribution, tail-based retention, SLO burn rate.

The PR-5 span tracer stops at the orchestrator/engine boundary — the
serving layers above it (gateway, admission, router, loadgen) emit
counters but no per-request causality. This module is the glue that
threads ONE trace id from the HTTP header down to the dispatch spans
and back out on every SSE event:

- **Trace context** — a W3C-`traceparent`-style header parsed/minted
  at the gateway (`parse_traceparent`/`format_traceparent`). The
  16-hex trace ids the span tracer already mints ride zero-padded in
  the 32-hex header field, so external ids and internal ids join
  without a second id space.
- **RequestTrace** — the per-request critical-path clock. Contiguous
  `stage()` marks decompose TTFT and turn latency into the named,
  non-overlapping stages in `STAGES`; the stage sum equals the leg
  wall by construction, and `finish()` records both so the invariant
  is checkable, not assumed. TTFT histograms gain trace-id exemplars
  (telemetry.observe(..., exemplar=)) so a bad bucket links to a
  concrete trace.
- **Tail-based retention** — ordinary traces head-sample at
  ROUNDTABLE_TRACE_SAMPLE (deterministic on the trace id, so every
  leg of one trace samples the same way); traces that shed, failed,
  hung, crossed a replica, or violated the SLO are ALWAYS retained.
  Retained legs append JSONL to one file per trace id under
  ROUNDTABLE_TRACE_DIR — append-mode, so the legs of a trace that
  crossed a kill -9 stitch on disk across process generations.
- **SloBurnMonitor** — the PR-19 capacity frontier as a live alerting
  baseline: fast/slow windows of per-request good/bad events against
  the record's p95 SLO, `roundtable_slo_burn_rate{window=}` gauges,
  and a `slo_burn` flight dump when both windows burn hot.

Always-on by design (trace ids, stage clocks, retention, burn rate
are event-rate bookkeeping); SPANS still gate on telemetry.ACTIVE —
armed, every gateway leg opens a real span the scheduler's turn span
parents under, which is what the `tracing` test marker asserts.
"""

from __future__ import annotations

import json
import os
import re
import threading
import time
import uuid
from collections import deque
from typing import Any, Optional

from . import telemetry

# The critical-path stages, in serving order. Non-overlapping and
# collectively exhaustive per leg: every stage() mark attributes the
# time since the previous mark, so the sum telescopes to the leg wall.
STAGES = ("admission", "queue_wait", "placement", "prefill",
          "first_flush", "decode_stream", "resume_replay")

# Serving-layer span rungs (extends telemetry.TRACE_RUNGS, which names
# the engine-side tree): "request" roots a gateway leg, "resume" roots
# a reconnect/restore leg joined to the original trace.
SERVING_RUNGS = ("request", "resume")

# Engine-side rungs whose presence under a serving-rung trace proves a
# CROSS-LAYER trace (the conftest `tracing` marker guard's criterion).
ENGINE_RUNGS = ("turn", "prefill", "decode", "segment", "dispatch")

_TRACEPARENT = re.compile(
    r"^([0-9a-f]{2})-([0-9a-f]{32})-([0-9a-f]{16})-([0-9a-f]{2})$")


# ---------------------------------------------------------------------------
# trace context (the W3C-style header)
# ---------------------------------------------------------------------------

def mint_trace_id() -> str:
    return uuid.uuid4().hex[:16]


def parse_traceparent(header: Optional[str]
                      ) -> Optional[tuple[str, str]]:
    """(trace_id, parent_span_id) from a `traceparent` header, or None
    when absent/malformed (the gateway then mints a fresh root). The
    internal id space is 16-hex trace / 12-hex span (the PR-5 tracer's
    widths); a full-width external id keeps its LOW bytes, which is
    also exactly what round-trips through format_traceparent."""
    if not header:
        return None
    m = _TRACEPARENT.match(header.strip().lower())
    if m is None or m.group(1) == "ff":
        return None
    trace, span = m.group(2), m.group(3)
    if set(trace) == {"0"} or set(span) == {"0"}:
        return None
    return trace[-16:], span[-12:]


def format_traceparent(trace_id: str, span_id: str = "") -> str:
    """The echo header: internal ids zero-padded to W3C widths."""
    t = (trace_id or mint_trace_id())[-32:].rjust(32, "0")
    s = (span_id or "0" * 12)[-16:].rjust(16, "0")
    return f"00-{t}-{s}-01"


# ---------------------------------------------------------------------------
# head sampling + env knobs
# ---------------------------------------------------------------------------

def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


def sample_rate() -> float:
    return max(0.0, min(1.0, _env_float("ROUNDTABLE_TRACE_SAMPLE",
                                        1.0)))


def head_sampled(trace_id: str) -> bool:
    """Deterministic head-sampling decision: a hash of the trace id,
    not a coin flip, so every leg of one trace (including post-crash
    resume legs in a NEW process) decides identically."""
    rate = sample_rate()
    if rate >= 1.0:
        return True
    if rate <= 0.0:
        return False
    try:
        frac = int(trace_id[-8:], 16) / float(0xFFFFFFFF)
    except ValueError:
        return True
    return frac < rate


def trace_dir() -> str:
    """Where retained traces land: ROUNDTABLE_TRACE_DIR, else a
    `traces/` subdir of the flight-dump dir (one knob usually moves
    both — the bench sets ROUNDTABLE_TELEMETRY_DIR for the child)."""
    configured = os.environ.get("ROUNDTABLE_TRACE_DIR")
    if configured:
        return configured
    return os.path.join(telemetry.dump_dir(), "traces")


def _keep() -> int:
    return max(int(_env_float("ROUNDTABLE_TRACE_KEEP", 256)), 8)


# ---------------------------------------------------------------------------
# the per-request critical-path clock
# ---------------------------------------------------------------------------

class RequestTrace:
    """One serving LEG of a client request: the initial admission+
    stream, or a resume/restore leg joined to the same trace id after
    a reconnect, kill -9, or failover.

    Usage (the gateway's shape):

        trace = RequestTrace(trace_id, stream=..., session=...)
        ... admission decision ...
        trace.stage("admission")
        ... placement + submit ...
        trace.stage("placement")
        ... first committed tokens arrive ...
        trace.stage("prefill")
        trace.carve("prefill", "queue_wait", reported_queue_wait_s)
        ... first event handed to consumers ...
        trace.stage("first_flush")        # trace.ttft() is now final
        ... stream retires ...
        trace.finish("ok")                # rest lands in decode_stream

    `stage(name)` attributes everything since the previous mark to
    `name` (accumulating — a stage may be marked more than once);
    `carve()` re-attributes an externally measured share of one stage
    to another (the scheduler reports queue_wait_s; the gateway only
    observes the submit→first-token lump). The stage sum therefore
    telescopes to the leg wall by construction, and finish() records
    both plus their gap so the invariant is CHECKED downstream
    (bench --trace, tests), never assumed."""

    __slots__ = ("trace_id", "parent_span_id", "kind", "stream_id",
                 "session", "attrs", "stages", "flags", "outcome",
                 "span", "t0", "_last", "_wall0", "_finished",
                 "replica", "reconnects")

    def __init__(self, trace_id: Optional[str] = None, *,
                 parent_span_id: str = "", kind: str = "request",
                 stream: str = "", session: str = "",
                 **attrs) -> None:
        self.trace_id = trace_id or mint_trace_id()
        self.parent_span_id = parent_span_id
        self.kind = kind            # "request" | "resume"
        self.stream_id = stream
        self.session = session
        self.attrs = dict(attrs)
        self.stages: dict[str, float] = {}
        self.flags: list[str] = []
        self.outcome = ""
        self.replica: Optional[str] = None
        self.reconnects = 0
        self.t0 = time.monotonic()
        self._last = self.t0
        self._wall0 = time.time()
        self._finished = False
        # A REAL span only when telemetry is armed: the scheduler's
        # turn span parents under it (tele_ctx captured inside
        # telemetry.attached(trace.context())), which is the
        # cross-layer link the `tracing` marker guard asserts.
        self.span = None
        if telemetry.ACTIVE:
            self.span = telemetry.start_span(
                kind, parent={"trace_id": self.trace_id,
                              "span_id": parent_span_id},
                stream=stream, session=session, **attrs)

    # -- stage marks --

    def stage(self, name: str) -> float:
        """Attribute the time since the previous mark to `name`;
        returns the increment."""
        now = time.monotonic()
        dt = max(now - self._last, 0.0)
        self._last = now
        self.stages[name] = self.stages.get(name, 0.0) + dt
        return dt

    def carve(self, src: str, dst: str,
              seconds: Optional[float]) -> None:
        """Move an externally measured `seconds` share of stage `src`
        into stage `dst` (clamped — the split can never create time
        the lump didn't contain, so the stage sum stays telescoped)."""
        if not seconds or seconds <= 0.0:
            return
        have = self.stages.get(src, 0.0)
        moved = min(float(seconds), have)
        if moved <= 0.0:
            return
        self.stages[src] = have - moved
        self.stages[dst] = self.stages.get(dst, 0.0) + moved

    def flag(self, reason: str) -> None:
        """Mark a tail-retention trigger (shed/failed/hung/
        replica_crossed/slo_violation/...): flagged traces are always
        retained regardless of the head-sampling rate."""
        if reason not in self.flags:
            self.flags.append(reason)

    def ttft(self) -> float:
        """TTFT as the STAGE SUM up through first_flush — the same
        number the waterfall shows, so the admission SLO signal and
        the trace can never disagree (the app.py:484 lump fix)."""
        return sum(self.stages.get(s, 0.0) for s in
                   ("resume_replay", "admission", "queue_wait",
                    "placement", "prefill", "first_flush"))

    def context(self) -> dict:
        """A telemetry.attached()-compatible parent context: spans
        opened under it (the scheduler's turn span) join this trace."""
        span_id = self.span.span_id if self.span is not None else ""
        return {"trace_id": self.trace_id, "span_id": span_id,
                "rung": self.kind, "sink": None}

    # -- completion --

    def finish(self, outcome: str = "ok",
               tail_stage: str = "decode_stream") -> dict:
        """Close the leg: attribute the remaining time to `tail_stage`,
        compute wall vs stage sum, end the span, hand the record to
        the store (head-sample or tail-retain), and return it.
        Idempotent — double-finish returns the first record."""
        if self._finished:
            return self._record
        self._finished = True
        self.stage(tail_stage)
        self.outcome = outcome or "ok"
        wall = time.monotonic() - self.t0
        stage_sum = sum(self.stages.values())
        record = {
            "trace_id": self.trace_id,
            "kind": self.kind,
            "stream": self.stream_id,
            "session": self.session,
            "outcome": self.outcome,
            "start": round(self._wall0, 6),
            "wall_s": round(wall, 6),
            "stage_sum_s": round(stage_sum, 6),
            "stage_gap_s": round(wall - stage_sum, 6),
            "ttft_s": round(self.ttft(), 6),
            "stages": {k: round(v, 6)
                       for k, v in self.stages.items() if v > 0.0},
            "flags": list(self.flags),
            "reconnects": self.reconnects,
            "pid": os.getpid(),
        }
        if self.replica is not None:
            record["replica"] = self.replica
        if self.attrs:
            record["attrs"] = {k: v for k, v in self.attrs.items()
                               if isinstance(v, (str, int, float,
                                                 bool))}
        if self.span is not None:
            for name, secs in record["stages"].items():
                self.span.set_attr(f"stage_{name}_s", round(secs, 6))
            self.span.set_attr("outcome", self.outcome)
            self.span.end("ok" if outcome == "ok"
                          else f"error:{outcome}")
            record["span_id"] = self.span.span_id
            self.span = None
        self._record = record
        store().note(record)
        return record

    # finish() stashes its record here for idempotence; a slot can't
    # default, so read through a property with a safe fallback.
    @property
    def _record(self) -> dict:
        return self.attrs.get("_final_record", {})

    @_record.setter
    def _record(self, value: dict) -> None:
        self.attrs["_final_record"] = value


# ---------------------------------------------------------------------------
# retention store
# ---------------------------------------------------------------------------

class TraceStore:
    """Finished legs: a bounded in-process ring (the `roundtable trace`
    CLI's live view) plus the on-disk retained set — one JSONL file
    per trace id, append-mode, so the legs of one trace written by
    DIFFERENT process generations (kill -9 + --resume) stitch on disk
    without any coordination."""

    def __init__(self) -> None:
        self._ring: deque[dict] = deque(maxlen=_keep())
        self._lock = threading.Lock()
        self.retained = 0

    def note(self, record: dict) -> None:
        with self._lock:
            self._ring.append(record)
        if self._should_retain(record):
            self._write(record)

    def _should_retain(self, record: dict) -> bool:
        if record.get("flags"):
            return True        # tail-based: anomalies always survive
        return head_sampled(record.get("trace_id", ""))

    def _write(self, record: dict) -> None:
        try:
            d = trace_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(
                d, f"trace-{record.get('trace_id', 'unknown')}.jsonl")
            is_new = not os.path.exists(path)
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(record, default=str) + "\n")
            with self._lock:
                self.retained += 1
            telemetry.inc("roundtable_traces_retained_total",
                          outcome=record.get("outcome", "ok"))
            if is_new:
                _prune_traces(d)
        except OSError:
            pass  # retention must never kill serving

    def recent(self, n: int = 50) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        return out[-n:]

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self.retained = 0


def _prune_traces(d: str) -> None:
    """Cap the retained-trace dir at ROUNDTABLE_TRACE_KEEP files
    (oldest-mtime first) — the flight-dump pruning rule applied to
    traces, so a long overload can't fill the disk with sheds."""
    keep = _keep()
    try:
        files = sorted(
            (p for p in os.listdir(d)
             if p.startswith("trace-") and p.endswith(".jsonl")),
            key=lambda p: os.path.getmtime(os.path.join(d, p)))
        for p in files[:-keep]:
            os.unlink(os.path.join(d, p))
    except OSError:
        pass


_store: Optional[TraceStore] = None
_store_lock = threading.Lock()


def store() -> TraceStore:
    global _store
    with _store_lock:
        if _store is None:
            _store = TraceStore()
        return _store


def load_traces(directory: Optional[str] = None
                ) -> dict[str, list[dict]]:
    """trace_id → legs (start-ordered) from the retained-trace dir —
    the `roundtable trace` CLI's and bench --trace's read side. Torn
    tails (a leg mid-write at kill -9) are skipped, not fatal."""
    d = directory or trace_dir()
    out: dict[str, list[dict]] = {}
    try:
        names = [p for p in os.listdir(d)
                 if p.startswith("trace-") and p.endswith(".jsonl")]
    except OSError:
        return out
    for name in names:
        legs = []
        try:
            with open(os.path.join(d, name), encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break
                    if isinstance(rec, dict) and rec.get("trace_id"):
                        legs.append(rec)
        except OSError:
            continue
        if legs:
            legs.sort(key=lambda r: r.get("start", 0.0))
            out[legs[0]["trace_id"]] = legs
    return out


def stitch(legs: list[dict]) -> dict:
    """One client request's stitched view across its legs: aggregate
    stages, total wall vs stage sum, flags union. The chaos
    acceptance (TRACE_r20.json) checks the stitched stage sum against
    client-measured wall."""
    stages: dict[str, float] = {}
    flags: list[str] = []
    wall = stage_sum = 0.0
    for leg in legs:
        for k, v in leg.get("stages", {}).items():
            stages[k] = stages.get(k, 0.0) + float(v)
        for fl in leg.get("flags", []):
            if fl not in flags:
                flags.append(fl)
        wall += float(leg.get("wall_s", 0.0))
        stage_sum += float(leg.get("stage_sum_s", 0.0))
    first = legs[0] if legs else {}
    return {
        "trace_id": first.get("trace_id", ""),
        "session": first.get("session", ""),
        "legs": len(legs),
        "pids": sorted({leg.get("pid") for leg in legs
                        if leg.get("pid") is not None}),
        "outcome": legs[-1].get("outcome", "") if legs else "",
        "wall_s": round(wall, 6),
        "stage_sum_s": round(stage_sum, 6),
        "stages": {k: round(v, 6) for k, v in sorted(stages.items())},
        "flags": flags,
        "ttft_s": first.get("ttft_s"),
    }


def cross_layer_count(spans: list[dict]) -> int:
    """How many traces in `spans` (flight-ring span records) link a
    serving-layer root (rung "request"/"resume") to an engine-side
    span (turn/segment/dispatch/...) — the `tracing` marker guard's
    proof that propagation crossed the gateway→scheduler seam."""
    serving = {s.get("trace_id") for s in spans
               if s.get("rung") in SERVING_RUNGS}
    engine = {s.get("trace_id") for s in spans
              if s.get("rung") in ENGINE_RUNGS}
    return len(serving & engine)


# ---------------------------------------------------------------------------
# SLO burn-rate monitor
# ---------------------------------------------------------------------------

class SloBurnMonitor:
    """The PR-19 capacity frontier as a live alerting baseline.

    Each finished request lands as one good/bad event (bad = shed, or
    TTFT over the record's p95 SLO). Two sliding windows — fast
    (ROUNDTABLE_SLO_FAST_WINDOW_S, 60 s) and slow
    (ROUNDTABLE_SLO_SLOW_WINDOW_S, 600 s) — each compute

        burn = bad_fraction / error_budget

    (error budget = ROUNDTABLE_SLO_ERROR_BUDGET, default 0.05 — the
    shed-rate bound the knee fit used). Burn 1.0 = consuming budget
    exactly as fast as the frontier allows; the classic multiwindow
    rule fires only when BOTH windows exceed
    ROUNDTABLE_SLO_BURN_THRESHOLD (fast = it's happening now, slow =
    it's not a blip), which publishes roundtable_slo_burn_rate{window=}
    gauges continuously and ships one `slo_burn` flight dump per fast
    window (cooldown — a sustained breach must not dump in a loop)."""

    MIN_SAMPLES = 8

    def __init__(self, p95_slo_s: float = 0.0, *,
                 error_budget: Optional[float] = None,
                 fast_window_s: Optional[float] = None,
                 slow_window_s: Optional[float] = None,
                 source: str = "default") -> None:
        self.p95_slo_s = float(p95_slo_s or 0.0)
        self.error_budget = max(
            error_budget if error_budget is not None
            else _env_float("ROUNDTABLE_SLO_ERROR_BUDGET", 0.05),
            1e-6)
        self.fast_window_s = (
            fast_window_s if fast_window_s is not None
            else _env_float("ROUNDTABLE_SLO_FAST_WINDOW_S", 60.0))
        self.slow_window_s = (
            slow_window_s if slow_window_s is not None
            else _env_float("ROUNDTABLE_SLO_SLOW_WINDOW_S", 600.0))
        self.threshold = _env_float("ROUNDTABLE_SLO_BURN_THRESHOLD",
                                    1.0)
        self.source = source
        self._events: deque[tuple[float, bool]] = deque(maxlen=4096)
        self._lock = threading.Lock()
        self.breaches = 0
        self._last_dump_at = 0.0
        self.last_dump_path = ""

    @property
    def armed(self) -> bool:
        """No SLO baseline → nothing to burn against; the monitor
        idles (gauges 0, never fires)."""
        return self.p95_slo_s > 0.0

    # -- event intake (one per finished admission decision) --

    def note_ttft(self, ttft_s: float,
                  trace_id: str = "") -> None:
        bad = self.armed and ttft_s > self.p95_slo_s
        self._note(bad, trace_id=trace_id if bad else "")

    def note_shed(self) -> None:
        self._note(True)

    def _note(self, bad: bool, trace_id: str = "") -> None:
        now = time.monotonic()
        with self._lock:
            self._events.append((now, bad))
        if not self.armed:
            return
        fast = self._burn(now, self.fast_window_s)
        slow = self._burn(now, self.slow_window_s)
        telemetry.set_gauge("roundtable_slo_burn_rate", round(fast, 4),
                            window="fast")
        telemetry.set_gauge("roundtable_slo_burn_rate", round(slow, 4),
                            window="slow")
        if (fast > self.threshold and slow > self.threshold
                and self._count(now, self.fast_window_s)
                >= self.MIN_SAMPLES):
            self._fire(now, fast, slow, trace_id)

    def _count(self, now: float, window_s: float) -> int:
        with self._lock:
            return sum(1 for t, _bad in self._events
                       if now - t <= window_s)

    def _burn(self, now: float, window_s: float) -> float:
        with self._lock:
            total = bad = 0
            for t, is_bad in self._events:
                if now - t <= window_s:
                    total += 1
                    bad += is_bad
        if total == 0:
            return 0.0
        return (bad / total) / self.error_budget

    def _fire(self, now: float, fast: float, slow: float,
              trace_id: str) -> None:
        with self._lock:
            if now - self._last_dump_at < self.fast_window_s:
                return
            self._last_dump_at = now
            self.breaches += 1
        telemetry.inc("roundtable_slo_breaches_total")
        extra = {"burn_fast": round(fast, 4),
                 "burn_slow": round(slow, 4),
                 "p95_slo_s": self.p95_slo_s,
                 "error_budget": self.error_budget,
                 "threshold": self.threshold}
        if trace_id:
            extra["exemplar_trace_id"] = trace_id
        self.last_dump_path = telemetry.flight_dump("slo_burn",
                                                    extra=extra)

    # -- reads --

    def burn_rates(self) -> dict[str, float]:
        now = time.monotonic()
        return {"fast": round(self._burn(now, self.fast_window_s), 4),
                "slow": round(self._burn(now, self.slow_window_s), 4)}

    def describe(self) -> dict[str, Any]:
        rates = self.burn_rates()
        now = time.monotonic()
        return {
            "armed": self.armed,
            "p95_slo_s": self.p95_slo_s,
            "source": self.source,
            "error_budget": self.error_budget,
            "threshold": self.threshold,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "burn_fast": rates["fast"],
            "burn_slow": rates["slow"],
            "samples_fast": self._count(now, self.fast_window_s),
            "samples_slow": self._count(now, self.slow_window_s),
            "breaches": self.breaches,
            "last_dump": self.last_dump_path,
        }
