"""Hostname:PID-stamped advisory file locks for shared .roundtable files.

The reference has NO locking: concurrent `roundtable` invocations in one
project interleave read-modify-write cycles on chronicle.md / decree-log
/ manifest (SURVEY.md §5.2), and its own TODO acknowledges the gap as
future work ("stale lock detection — PID-based check ... so crashed
sessions don't lock", reference TODO.md:188). This implements exactly
that: O_CREAT|O_EXCL lock files stamped with the holder's PID; a lock
whose holder is no longer alive is stale and silently reclaimed, so a
crashed run can never deadlock the next one.
"""

from __future__ import annotations

import os
import socket
import threading
import time
from pathlib import Path


class LockTimeout(RuntimeError):
    pass


# A lock from ANOTHER host cannot be PID-checked; it is presumed crashed
# (and reclaimed) once its file is this old. Roundtable store writes hold
# locks for milliseconds, so minutes of age means a dead holder — this
# keeps the module's no-deadlock guarantee in the multi-host case at the
# cost of a cross-host reclaim being slow instead of instant. Live
# holders are protected past this ceiling by the heartbeat below
# (advisor r3: a generic utility must not lose mutual exclusion just
# because one call site holds long).
CROSS_HOST_STALE_S = 300.0


def _stamp() -> str:
    return f"{socket.gethostname()}:{os.getpid()}"


def _parse_stamp(text: str) -> tuple[str | None, int]:
    """(hostname|None, pid) from a lock file's content. Legacy pid-only
    stamps (pre-multi-host) parse as (None, pid)."""
    text = text.strip()
    if ":" in text:
        host, _, pid_s = text.rpartition(":")
    else:
        host, pid_s = None, text
    try:
        return host or None, int(pid_s or "0")
    except ValueError:
        return host or None, 0


# Heartbeat: ONE shared daemon thread (started lazily on the first
# acquire in the process) touches every currently-held lock file's mtime
# every CROSS_HOST_STALE_S/3, so a LIVE holder is never mistaken for a
# crashed one by the age-gated cross-host reclaim — without paying a
# thread spawn on the millisecond-hold hot path. Before each touch the
# stamp is re-read: if it is no longer ours (another host age-reclaimed
# while this whole process was stalled), the entry is dropped so we never
# keep refreshing a lock that now belongs — or belonged — to someone
# else. Transient I/O errors (NFS hiccups) skip one beat and retry.
_hb_mutex = threading.Lock()
_hb_held: dict[str, str] = {}  # lock-file path -> stamp we wrote
_hb_started = False
_hb_wake = threading.Event()


def _hb_register(path: Path, stamp: str) -> None:
    global _hb_started
    with _hb_mutex:
        _hb_held[str(path)] = stamp
        if not _hb_started:
            _hb_started = True
            threading.Thread(target=_hb_loop, daemon=True).start()
    _hb_wake.set()  # interrupt a possibly-long wait so the new interval
    #                 (tests patch CROSS_HOST_STALE_S) takes effect now


def _hb_unregister(path: Path) -> None:
    with _hb_mutex:
        _hb_held.pop(str(path), None)


def _hb_loop() -> None:
    while True:
        _hb_wake.wait(CROSS_HOST_STALE_S / 3.0)
        _hb_wake.clear()
        with _hb_mutex:
            items = list(_hb_held.items())
        for path, stamp in items:
            try:
                content = Path(path).read_text().strip()
            except FileNotFoundError:
                _hb_unregister(Path(path))  # released/reclaimed
                continue
            except OSError:
                continue  # transient: retry next beat
            if content != stamp:
                _hb_unregister(Path(path))  # not ours anymore
                continue
            try:
                os.utime(path)
            except OSError:
                pass  # transient: retry next beat


class FileLock:
    """`with FileLock(path):` — advisory lock at `<path>.lock`.

    Holds of any length are safe: while held, the module's shared
    heartbeat keeps the lock file's mtime fresh, so a LIVE holder on
    another host is never mistaken for a crashed one by the age-gated
    cross-host reclaim (roundtable's millisecond store writes release
    long before the first beat ever fires)."""

    def __init__(self, target: str | Path, timeout_s: float = 10.0,
                 poll_s: float = 0.05):
        self.lock_path = Path(str(target) + ".lock")
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self._held = False

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists, owned by someone else
        return True

    def _try_reclaim_stale(self) -> None:
        """Reclaim a lock whose holder died — without the check-then-unlink
        race: the file is CLAIMED first (atomic rename to a name only we
        use), then its content re-verified. If the rename grabbed a fresh
        lock that appeared between our read and the rename, it is restored
        via os.link (which refuses if a newer lock already took the slot).
        The remaining window needs three processes interleaving within the
        same few microseconds twice in a row — vanishingly small next to
        the 50ms poll cadence this lock operates at.

        Multi-host (shared filesystem): a PID is only meaningful on the
        host that wrote it — a live holder on another host would look
        dead to our local process table. Stamps carry hostname:pid; a
        stamp from a DIFFERENT hostname is reclaimed only once the lock
        file is CROSS_HOST_STALE_S old (age replaces the PID liveness
        check), so a crashed remote holder cannot deadlock this host
        forever and a live one is never raced."""
        try:
            host, pid = _parse_stamp(self.lock_path.read_text())
        except OSError:
            return  # holder is mid-write or lock vanished; just retry
        if host is not None and host != socket.gethostname():
            try:
                age = time.time() - self.lock_path.stat().st_mtime
            except OSError:
                return  # vanished between read and stat; just retry
            if age < CROSS_HOST_STALE_S:
                return  # possibly-live cross-host holder: wait it out
        elif not pid or self._pid_alive(pid):
            return
        claimed = Path(f"{self.lock_path}.reap.{os.getpid()}")
        try:
            os.rename(self.lock_path, claimed)
        except OSError:
            return  # someone else reclaimed (or released) first
        try:
            host2, pid2 = _parse_stamp(claimed.read_text())
        except OSError:
            host2, pid2 = None, 0
        fresh = (host2, pid2) != (host, pid) or (
            # same stamp, but the holder may have released and re-acquired
            # between our read and the rename — alive means fresh (only
            # checkable locally; the cross-host case was age-gated above)
            (host2 is None or host2 == socket.gethostname())
            and pid2 and self._pid_alive(pid2))
        if fresh:
            # We renamed a FRESH lock — put it back unless a newer lock
            # already occupied the slot.
            try:
                os.link(claimed, self.lock_path)
            except OSError:
                pass
        try:
            claimed.unlink()
        except OSError:
            pass

    def acquire(self) -> None:
        self.lock_path.parent.mkdir(parents=True, exist_ok=True)
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fd = os.open(self.lock_path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                stamp = _stamp()
                with os.fdopen(fd, "w") as f:
                    f.write(stamp)
                self._held = True
                _hb_register(self.lock_path, stamp)
                return
            except FileExistsError:
                self._try_reclaim_stale()
                if time.monotonic() > deadline:
                    raise LockTimeout(
                        f"Could not acquire {self.lock_path} within "
                        f"{self.timeout_s:.0f}s — another roundtable "
                        "process is writing; retry, or remove the lock "
                        "file if no other process is running")
                time.sleep(self.poll_s)

    def release(self) -> None:
        if self._held:
            self._held = False
            _hb_unregister(self.lock_path)
            try:
                self.lock_path.unlink()
            except OSError:
                pass

    def __enter__(self) -> "FileLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
