"""PID-stamped advisory file locks for shared .roundtable files.

The reference has NO locking: concurrent `roundtable` invocations in one
project interleave read-modify-write cycles on chronicle.md / decree-log
/ manifest (SURVEY.md §5.2), and its own TODO acknowledges the gap as
future work ("stale lock detection — PID-based check ... so crashed
sessions don't lock", reference TODO.md:188). This implements exactly
that: O_CREAT|O_EXCL lock files stamped with the holder's PID; a lock
whose holder is no longer alive is stale and silently reclaimed, so a
crashed run can never deadlock the next one.
"""

from __future__ import annotations

import os
import time
from pathlib import Path


class LockTimeout(RuntimeError):
    pass


class FileLock:
    """`with FileLock(path):` — advisory lock at `<path>.lock`."""

    def __init__(self, target: str | Path, timeout_s: float = 10.0,
                 poll_s: float = 0.05):
        self.lock_path = Path(str(target) + ".lock")
        self.timeout_s = timeout_s
        self.poll_s = poll_s
        self._held = False

    @staticmethod
    def _pid_alive(pid: int) -> bool:
        try:
            os.kill(pid, 0)
        except ProcessLookupError:
            return False
        except PermissionError:
            return True  # exists, owned by someone else
        return True

    def _try_reclaim_stale(self) -> None:
        """Reclaim a lock whose holder died — without the check-then-unlink
        race: the file is CLAIMED first (atomic rename to a name only we
        use), then its content re-verified. If the rename grabbed a fresh
        lock that appeared between our read and the rename, it is restored
        via os.link (which refuses if a newer lock already took the slot).
        The remaining window needs three processes interleaving within the
        same few microseconds twice in a row — vanishingly small next to
        the 50ms poll cadence this lock operates at."""
        try:
            pid = int(self.lock_path.read_text().strip() or "0")
        except (OSError, ValueError):
            return  # holder is mid-write or lock vanished; just retry
        if not pid or self._pid_alive(pid):
            return
        claimed = Path(f"{self.lock_path}.reap.{os.getpid()}")
        try:
            os.rename(self.lock_path, claimed)
        except OSError:
            return  # someone else reclaimed (or released) first
        try:
            pid2 = int(claimed.read_text().strip() or "0")
        except (OSError, ValueError):
            pid2 = 0
        if pid2 and self._pid_alive(pid2):
            # We renamed a FRESH lock — put it back unless a newer lock
            # already occupied the slot.
            try:
                os.link(claimed, self.lock_path)
            except OSError:
                pass
        try:
            claimed.unlink()
        except OSError:
            pass

    def acquire(self) -> None:
        self.lock_path.parent.mkdir(parents=True, exist_ok=True)
        deadline = time.monotonic() + self.timeout_s
        while True:
            try:
                fd = os.open(self.lock_path,
                             os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                with os.fdopen(fd, "w") as f:
                    f.write(str(os.getpid()))
                self._held = True
                return
            except FileExistsError:
                self._try_reclaim_stale()
                if time.monotonic() > deadline:
                    raise LockTimeout(
                        f"Could not acquire {self.lock_path} within "
                        f"{self.timeout_s:.0f}s — another roundtable "
                        "process is writing; retry, or remove the lock "
                        "file if no other process is running")
                time.sleep(self.poll_s)

    def release(self) -> None:
        if self._held:
            self._held = False
            try:
                self.lock_path.unlink()
            except OSError:
                pass

    def __enter__(self) -> "FileLock":
        self.acquire()
        return self

    def __exit__(self, *exc) -> bool:
        self.release()
        return False
