"""Per-session metrics + optional device profiling.

The reference has no metrics at all — console chalk output only
(SURVEY.md §5.1/§5.5: "no structured logs, no metrics files"). This module
adds the quantities BASELINE.md measures: per-round wall-clock, per-knight
turn latency, and the engine's token counts and prefill/decode throughput,
written crash-safe to `<session>/metrics.json` after every round.

Profiling: set ROUNDTABLE_PROFILE=1 (trace into `<session>/profile/`) or
ROUNDTABLE_PROFILE=/some/dir to capture a jax.profiler device+host trace of
the whole discussion, viewable in XProf/Perfetto (SURVEY.md §5.1).
"""

from __future__ import annotations

import json
import os
import time
from contextlib import contextmanager
from dataclasses import asdict, dataclass, field
from pathlib import Path
from typing import Any, Optional


@dataclass
class TurnMetric:
    knight: str
    round: int
    wall_s: float
    chars_in: int = 0
    chars_out: int = 0
    # engine-side numbers, present only for tpu-llm turns
    engine: Optional[dict[str, Any]] = None
    # Scheduler numbers (ISSUE 4), present only for turns served through
    # the continuous-batching session scheduler: how long the round sat
    # in the admission queue, and the mean decode-batch row count while
    # this round's rows were live (occupancy > len(own rows) means the
    # engine genuinely co-served other sessions during this turn).
    queue_wait_s: Optional[float] = None
    batch_occupancy: Optional[float] = None


@dataclass
class RoundMetric:
    round: int
    wall_s: float = 0.0
    turns: list[TurnMetric] = field(default_factory=list)


class SessionMetrics:
    """Collects and persists metrics.json; every mutation rewrites the file
    (same crash-safety stance as status.json, reference session.ts:120-149).

    Concurrency (ISSUE 4 satellite): each discussion session owns its OWN
    SessionMetrics over its OWN session directory — there is no shared
    mutable state between concurrent sessions — and WITHIN a session the
    orchestrator's batch-group thread pool records turns concurrently,
    so every mutation and the rewrite serialize on an instance lock.
    """

    def __init__(self, session_path: str | Path):
        self.path = Path(session_path) / "metrics.json"
        self.rounds: list[RoundMetric] = []
        self.outcome: Optional[str] = None
        self._started = time.monotonic()
        self._round_started = self._started
        self._prior_wall = 0.0
        import threading
        self._mu = threading.RLock()
        self._load_existing()

    def _load_existing(self) -> None:
        """A resumed session ("King sends back", ContinueOptions) reuses the
        session dir — earlier rounds' metrics must survive the rewrite."""
        try:
            data = json.loads(self.path.read_text(encoding="utf-8"))
            for r in data.get("rounds", []):
                self.rounds.append(RoundMetric(
                    round=r["round"], wall_s=r.get("wall_s", 0.0),
                    turns=[TurnMetric(**t) for t in r.get("turns", [])]))
            self._prior_wall = data.get("totals", {}).get("wall_s", 0.0)
        except (OSError, ValueError, KeyError, TypeError):
            pass

    # --- recording ---

    def start_round(self, round_num: int) -> None:
        with self._mu:
            self.rounds.append(RoundMetric(round=round_num))
            self._round_started = time.monotonic()

    def record_turn(self, knight: str, round_num: int, wall_s: float,
                    chars_in: int = 0, chars_out: int = 0,
                    engine: Optional[dict[str, Any]] = None,
                    queue_wait_s: Optional[float] = None,
                    batch_occupancy: Optional[float] = None) -> None:
        # Scheduler provenance defaults from the engine stats dict when
        # the caller doesn't pass it explicitly — every surface that
        # already forwards adapter last_stats() gets the fields free.
        sched = (engine or {}).get("sched") or {}
        if queue_wait_s is None:
            queue_wait_s = sched.get("queue_wait_s")
        if batch_occupancy is None:
            batch_occupancy = sched.get("occupancy_mean")
        with self._mu:
            if not self.rounds or self.rounds[-1].round != round_num:
                self.start_round(round_num)
            self.rounds[-1].turns.append(TurnMetric(
                knight=knight, round=round_num, wall_s=round(wall_s, 3),
                chars_in=chars_in, chars_out=chars_out, engine=engine,
                queue_wait_s=queue_wait_s,
                batch_occupancy=batch_occupancy))
        # Unified-registry publish (ISSUE 5): turn counts and latency
        # distributions land in the same store the engine/scheduler
        # series live in, so metrics.json is a per-session VIEW of it
        # rather than a fourth parallel truth. Token counters are NOT
        # re-published here — the engines already count them.
        from . import telemetry
        telemetry.inc("roundtable_turns_total", knight=knight)
        telemetry.observe("roundtable_turn_wall_seconds", wall_s)

    def end_round(self) -> None:
        with self._mu:
            if self.rounds:
                self.rounds[-1].wall_s = round(
                    time.monotonic() - self._round_started, 3)
        self.write()

    def finish(self, outcome: str) -> None:
        self.outcome = outcome
        self.write()

    # --- aggregation ---

    def totals(self) -> dict[str, Any]:
        agg = aggregate_engine_stats(
            t for r in self.rounds for t in r.turns)
        chars_in = sum(t.chars_in for r in self.rounds for t in r.turns)
        chars_out = sum(t.chars_out for r in self.rounds for t in r.turns)
        return {
            "wall_s": round(
                self._prior_wall + time.monotonic() - self._started, 3),
            "rounds": len(self.rounds),
            "turns": sum(len(r.turns) for r in self.rounds),
            "chars_in": chars_in,
            "chars_out": chars_out,
            "engine_prefill_tokens": agg["prefill_tokens"],
            "engine_reused_tokens": agg["reused_tokens"],
            "engine_decode_tokens": agg["decode_tokens"],
            "engine_decode_tps": agg["decode_tps"],
        }

    def write(self) -> None:
        with self._mu:
            payload = {
                "outcome": self.outcome,
                "totals": self.totals(),
                "rounds": [asdict(r) for r in self.rounds],
            }
        try:
            from .session import atomic_write_text
            atomic_write_text(self.path,
                              json.dumps(payload, indent=2, default=str))
        except (OSError, TypeError, ValueError):
            pass  # metrics must never kill a discussion
        # With telemetry armed, every metrics.json rewrite also drops a
        # Prometheus-text registry snapshot next to the spans file —
        # the store `roundtable status --telemetry` renders (a separate
        # process can't read this process's registry live; the per-round
        # rewrite cadence is the freshness contract).
        from . import telemetry
        if telemetry.ACTIVE:
            try:
                from .session import atomic_write_text
                tdir = self.path.parent / "telemetry"
                tdir.mkdir(parents=True, exist_ok=True)
                atomic_write_text(tdir / "metrics.prom",
                                  telemetry.REGISTRY.prometheus_text())
            except (OSError, TypeError, ValueError):
                pass


def aggregate_engine_stats(turns) -> dict[str, Any]:
    """Sum engine-side numbers over TurnMetrics (shared by totals() and the
    console round footer so the two can't drift)."""
    prefill = reused = decode = 0
    decode_time = 0.0
    for t in turns:
        if t.engine:
            prefill += t.engine.get("prefill_tokens", 0)
            reused += t.engine.get("reused_tokens", 0)
            decode += t.engine.get("decode_tokens", 0)
            decode_time += t.engine.get("decode_seconds", 0.0)
    return {
        "prefill_tokens": prefill,
        "reused_tokens": reused,
        "decode_tokens": decode,
        "decode_seconds": decode_time,
        "decode_tps": round(decode / decode_time, 2) if decode_time else 0.0,
    }


@contextmanager
def maybe_profile(session_path: str | Path):
    """jax.profiler trace of the block when ROUNDTABLE_PROFILE is set.

    Profiling must never kill a discussion: a missing jax install or a
    failed start_trace degrades to a styled ui.warn + no trace.

    Telemetry (ISSUE 5 satellite): while the device trace runs, span
    mirroring is armed (telemetry.set_profiling) and the block runs
    under a root "profile" span — the discussion span opened inside
    becomes its child, so the xprof timeline and the JSONL span tree
    share one trace id and one set of rung names.
    """
    from . import telemetry
    target = os.environ.get("ROUNDTABLE_PROFILE")
    if not target:
        yield
        return
    trace_dir = (Path(session_path) / "profile" if target == "1"
                 else Path(target))
    profiler = None
    try:
        import jax
        jax.profiler.start_trace(str(trace_dir))
        profiler = jax
    except Exception as e:  # noqa: BLE001 — opt-in feature, degrade loudly
        from .ui import warn
        warn(f"  (ROUNDTABLE_PROFILE set but tracing unavailable: {e})")
    telemetry.set_profiling(profiler is not None)
    try:
        # Root "profile" span over the whole traced block: the
        # discussion span opened inside becomes its child, so xprof and
        # the JSONL tree share ONE trace id. The sink rides the root.
        sink = (telemetry.session_sink(session_path)
                if telemetry.ACTIVE else None)
        with telemetry.span("profile", sink=sink,
                            trace_dir=str(trace_dir),
                            device_trace=profiler is not None):
            yield
    finally:
        telemetry.set_profiling(False)
        if profiler is not None:
            try:
                profiler.profiler.stop_trace()
            except Exception:  # noqa: BLE001
                pass
