"""Apply validation pipeline — blocks bad knight output before any write.

The reference's single most-tested subsystem ("157/157 — block-scanner 34,
diff-parser 66, validation 57", reference TODO.md:121; "bad output is
blocked by validation but nothing gets written", TODO.md:141-143).
Validation is all-or-nothing per apply run: a single hard issue anywhere
aborts the whole write set (single attempt, no retry loop — "hard fail >
infinite retry", reference TODO.md:144).
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from .blocks import TOP_ANCHOR, scan_blocks
from .rtdiff import FileEdit, ParsedApply

MAX_CONTENT_BYTES = 200_000  # per-file new-content cap


@dataclass(frozen=True)
class ValidationIssue:
    path: str
    message: str
    fatal: bool = True


def sha256_text(text: str) -> str:
    return hashlib.sha256(text.encode("utf-8")).hexdigest()


def _path_issues(path: str) -> Optional[str]:
    if not path or path.strip() != path:
        return "empty or whitespace-padded path"
    p = Path(path)
    if p.is_absolute():
        return "absolute paths are not allowed"
    if ".." in p.parts:
        return "path traversal ('..') is not allowed"
    if path.startswith("~"):
        return "home-relative paths are not allowed"
    return None


def validate_edits(
    parsed: ParsedApply,
    project_root: str | Path,
    allowed_files: Optional[list[str]],
    source_hashes: Optional[dict[str, str]] = None,
    override_scope: bool = False,
) -> list[ValidationIssue]:
    """Run every check; returns ALL issues (not just the first) so the
    King sees the complete damage report before deciding anything.

    - scope: every touched path must appear in allowed_files (NEW: entries
      match either form); skipped entirely when allowed_files is None
      (old sessions without scope data work normally, reference
      README.md:207) or override_scope is set
    - paths: relative, no traversal, inside the project
    - existence: block ops need an existing file; FILE_CREATE needs a
      NEW: path that does not exist yet
    - blocks: ids must exist in the CURRENT scan of the file; at most one
      op per block; deletes conflict with other ops on the same block
    - integrity: when the apply prompt embedded a sha256 per source file,
      the file on disk must still hash the same (someone edited it
      between context build and write)
    - size: new content capped at MAX_CONTENT_BYTES per file
    """
    root = Path(project_root).resolve()
    issues: list[ValidationIssue] = []

    allowed_lookup: Optional[set[str]] = None
    if allowed_files is not None and not override_scope:
        allowed_lookup = set()
        for f in allowed_files:
            clean = f[4:].strip() if f.upper().startswith("NEW:") else f
            allowed_lookup.add(clean)

    seen_paths: set[str] = set()
    for edit in parsed.edits:
        path = edit.clean_path
        perr = _path_issues(path)
        if perr:
            issues.append(ValidationIssue(edit.path, perr))
            continue
        full = (root / path).resolve()
        if root not in full.parents and full != root:
            issues.append(ValidationIssue(path, "escapes the project root"))
            continue
        if path in seen_paths:
            issues.append(ValidationIssue(
                path, "file appears in multiple FILE: sections"))
            continue
        seen_paths.add(path)

        if allowed_lookup is not None and path not in allowed_lookup:
            issues.append(ValidationIssue(
                path,
                "outside the agreed scope (files_to_modify) — "
                "use --override-scope to force", fatal=True))

        creates = [op for op in edit.ops if op.op == "FILE_CREATE"]
        block_ops = [op for op in edit.ops if op.op.startswith("BLOCK_")]
        legacy_ops = [op for op in edit.ops if op.op == "SEARCH_REPLACE"]

        if creates:
            if block_ops or legacy_ops or len(creates) > 1:
                issues.append(ValidationIssue(
                    path, "FILE_CREATE cannot be combined with other ops"))
            if not edit.is_new:
                issues.append(ValidationIssue(
                    path, "FILE_CREATE requires the NEW: path prefix"))
            if full.exists():
                issues.append(ValidationIssue(
                    path, "NEW: file already exists on disk"))
            content = creates[0].content or ""
            if not content.strip():
                issues.append(ValidationIssue(
                    path, "FILE_CREATE with empty content"))
            if len(content.encode("utf-8")) > MAX_CONTENT_BYTES:
                issues.append(ValidationIssue(
                    path, f"new file exceeds {MAX_CONTENT_BYTES} bytes"))
            continue

        if edit.is_new:
            issues.append(ValidationIssue(
                path, "NEW: path without a FILE_CREATE op"))
            continue
        if not full.is_file():
            issues.append(ValidationIssue(path, "file does not exist"))
            continue

        text = full.read_text(encoding="utf-8", errors="replace")
        if source_hashes and path in source_hashes:
            if sha256_text(text) != source_hashes[path]:
                issues.append(ValidationIssue(
                    path,
                    "file changed on disk since the apply context was "
                    "built (sha256 mismatch) — rerun apply"))

        if legacy_ops:
            for op in legacy_ops:
                if not (op.search or "").strip():
                    issues.append(ValidationIssue(
                        path, "EDIT: with empty SEARCH block"))
                elif text.count(op.search) == 0:
                    issues.append(ValidationIssue(
                        path, "EDIT: SEARCH text not found in file"))
                elif text.count(op.search) > 1:
                    issues.append(ValidationIssue(
                        path,
                        f"EDIT: SEARCH text matches "
                        f"{text.count(op.search)} times — ambiguous"))
            continue

        ids = {b.id for b in scan_blocks(text)}
        touched: set[str] = set()
        for op in block_ops:
            bid = op.block_id or ""
            if bid == TOP_ANCHOR:
                if op.op != "BLOCK_INSERT_AFTER":
                    issues.append(ValidationIssue(
                        path, f"{op.op} on the {TOP_ANCHOR} anchor "
                        "(only BLOCK_INSERT_AFTER is valid)"))
                    continue
            elif bid not in ids:
                issues.append(ValidationIssue(
                    path, f"{op.op} references unknown block {bid} "
                    "(ids come from the BLOCK_MAP of the current file)"))
                continue
            if bid in touched:
                issues.append(ValidationIssue(
                    path, f"multiple ops address block {bid}"))
                continue
            touched.add(bid)
            if op.op in ("BLOCK_REPLACE", "BLOCK_INSERT_AFTER"):
                if not (op.content or "").strip():
                    issues.append(ValidationIssue(
                        path, f"{op.op} {bid} with empty content"))
                elif len((op.content or "").encode("utf-8")) \
                        > MAX_CONTENT_BYTES:
                    issues.append(ValidationIssue(
                        path,
                        f"{op.op} {bid} exceeds {MAX_CONTENT_BYTES} bytes"))

    return issues
