"""RTDIFF/1 parser — the block-level edit format knights emit at apply time.

Format (our concrete spec for the reference's documented-but-absent RTDIFF/1
block system, TODO.md:88,130-137):

    RTDIFF/1
    FILE: src/auth.py
    BLOCK_REPLACE B004
    <<<
    def login(user):
        ...
    >>>
    BLOCK_INSERT_AFTER B007
    <<<
    def logout(user):
        ...
    >>>
    BLOCK_DELETE B009
    FILE: NEW:src/session.py
    FILE_CREATE
    <<<
    ...entire file...
    >>>

Rules: one header line `RTDIFF/1`; `FILE:` opens a per-file section; ops
address block ids from the BLOCK_MAP the knight was shown; content sits
between `<<<` and `>>>` fence lines. `BLOCK_INSERT_AFTER B000` inserts at
the top of the file. New files use the `NEW:` scope prefix and FILE_CREATE.
The parser tolerates surrounding prose and markdown code fences — LLM
output is never clean (cf. the consensus parser's repair ladder,
reference src/consensus.ts:118-145).

The legacy `EDIT:` search/replace format (reference TODO.md:138) is parsed
too, with a deprecation warning attached to the result.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Optional

OPS = ("BLOCK_REPLACE", "BLOCK_INSERT_AFTER", "BLOCK_DELETE", "FILE_CREATE")

_BLOCK_ID_RE = re.compile(r"^B\d{3,}$")
_OP_RE = re.compile(
    r"^(BLOCK_REPLACE|BLOCK_INSERT_AFTER|BLOCK_DELETE)\s+(\S+)\s*$")


class ParseError(Exception):
    """RTDIFF text was structurally unusable (nothing gets written)."""


@dataclass
class ApplyOp:
    op: str                       # one of OPS, or legacy SEARCH_REPLACE
    block_id: Optional[str] = None
    content: Optional[str] = None  # lines, no trailing newline
    search: Optional[str] = None   # legacy SEARCH_REPLACE only


@dataclass
class FileEdit:
    path: str                     # as emitted, may carry NEW: prefix
    ops: list[ApplyOp] = field(default_factory=list)

    @property
    def is_new(self) -> bool:
        return self.path.upper().startswith("NEW:")

    @property
    def clean_path(self) -> str:
        return self.path[4:].strip() if self.is_new else self.path


@dataclass
class ParsedApply:
    edits: list[FileEdit]
    legacy: bool = False          # parsed via deprecated EDIT: format
    warnings: list[str] = field(default_factory=list)


def _strip_md_fences(text: str) -> str:
    # Drop ``` fence lines wholesale; they never carry RTDIFF content.
    return "\n".join(l for l in text.splitlines()
                     if not l.strip().startswith("```"))


def _read_fenced(lines: list[str], i: int) -> tuple[str, int]:
    """Read a <<< ... >>> body starting at lines[i]. Returns (body, next)."""
    if i >= len(lines) or lines[i].strip() != "<<<":
        raise ParseError(f"expected '<<<' fence at line {i + 1}")
    body: list[str] = []
    i += 1
    while i < len(lines):
        if lines[i].strip() == ">>>":
            return "\n".join(body), i + 1
        body.append(lines[i])
        i += 1
    raise ParseError("unterminated '<<<' fence (no matching '>>>')")


def parse_rtdiff(text: str) -> ParsedApply:
    """Parse RTDIFF/1 output into FileEdits. Raises ParseError if the
    header is present but the structure is broken."""
    cleaned = _strip_md_fences(text)
    lines = cleaned.splitlines()
    try:
        start = next(i for i, l in enumerate(lines)
                     if l.strip() == "RTDIFF/1")
    except StopIteration:
        raise ParseError("no RTDIFF/1 header found")

    edits: list[FileEdit] = []
    warnings: list[str] = []
    current: Optional[FileEdit] = None
    i = start + 1
    while i < len(lines):
        line = lines[i].strip()
        if not line:
            i += 1
            continue
        if line.startswith("FILE:"):
            path = line[5:].strip()
            if not path:
                raise ParseError(f"empty FILE: path at line {i + 1}")
            current = FileEdit(path=path)
            edits.append(current)
            i += 1
            continue
        m = _OP_RE.match(line)
        if m:
            if current is None:
                raise ParseError(f"op before any FILE: at line {i + 1}")
            op, block_id = m.group(1), m.group(2)
            if not _BLOCK_ID_RE.match(block_id):
                raise ParseError(
                    f"bad block id {block_id!r} at line {i + 1}")
            if op == "BLOCK_DELETE":
                current.ops.append(ApplyOp(op=op, block_id=block_id))
                i += 1
            else:
                content, i = _read_fenced(lines, i + 1)
                current.ops.append(
                    ApplyOp(op=op, block_id=block_id, content=content))
            continue
        if line == "FILE_CREATE":
            if current is None:
                raise ParseError(f"FILE_CREATE before FILE: at line {i + 1}")
            content, i = _read_fenced(lines, i + 1)
            current.ops.append(ApplyOp(op="FILE_CREATE", content=content))
            continue
        # Prose around/inside the diff is tolerated but recorded, so
        # silently-dropped content is visible during parley.
        warnings.append(f"ignored non-RTDIFF line {i + 1}: {line[:60]}")
        i += 1

    edits = [e for e in edits if e.ops]
    if not edits:
        raise ParseError("RTDIFF/1 header present but no complete ops found")
    return ParsedApply(edits=edits, warnings=warnings)


# --- legacy EDIT: format (deprecated) ---

_EDIT_HEADER_RE = re.compile(r"^EDIT:\s*(\S+)\s*$")


def parse_legacy_edit(text: str) -> ParsedApply:
    """Parse the deprecated EDIT: search/replace format:

        EDIT: path/to/file.py
        SEARCH:
        <<<
        old lines
        >>>
        REPLACE:
        <<<
        new lines
        >>>
    """
    cleaned = _strip_md_fences(text)
    lines = cleaned.splitlines()
    edits: list[FileEdit] = []
    i = 0
    while i < len(lines):
        m = _EDIT_HEADER_RE.match(lines[i].strip())
        if not m:
            i += 1
            continue
        path = m.group(1)
        i += 1
        while i < len(lines) and not lines[i].strip():
            i += 1
        if i >= len(lines) or lines[i].strip() != "SEARCH:":
            raise ParseError(f"EDIT: {path} missing SEARCH: section")
        search, i = _read_fenced(lines, i + 1)
        while i < len(lines) and not lines[i].strip():
            i += 1
        if i >= len(lines) or lines[i].strip() != "REPLACE:":
            raise ParseError(f"EDIT: {path} missing REPLACE: section")
        replace, i = _read_fenced(lines, i + 1)
        edit = FileEdit(path=path)
        edit.ops.append(ApplyOp(op="SEARCH_REPLACE", content=replace,
                                search=search))
        edits.append(edit)
    if not edits:
        raise ParseError("no EDIT: sections found")
    return ParsedApply(
        edits=edits, legacy=True,
        warnings=["EDIT: format is deprecated — knights should emit "
                  "RTDIFF/1 block edits"])


def parse_knight_output(text: str) -> ParsedApply:
    """RTDIFF/1 first; fall back to legacy EDIT: with a deprecation
    warning (reference TODO.md:138)."""
    if "RTDIFF/1" in text:
        return parse_rtdiff(text)
    if re.search(r"^EDIT:\s*\S+", text, re.MULTILINE):
        return parse_legacy_edit(text)
    raise ParseError(
        "knight output contains neither RTDIFF/1 nor EDIT: sections")
