"""The apply subsystem — Lead Knight executes the consensus decision.

Reimplements the reference's documented-but-absent apply pipeline
(reference README.md:159-207, TODO.md:87-138, architecture-docs.md:215-219;
SURVEY.md §2.2): block-level RTDIFF/1 edits produced by an LLM against a
BLOCK_MAP of the target files, validated, scope-enforced, backed up, and
written with per-file parley approval.
"""

from .blocks import Block, scan_blocks, render_block_map
from .rtdiff import (
    ApplyOp,
    FileEdit,
    ParseError,
    parse_knight_output,
)
from .validate import ValidationIssue, validate_edits
from .executor import ApplyOutcome, apply_edits, materialize_edit
