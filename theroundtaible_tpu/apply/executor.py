"""Apply executor — materialize validated edits, with backups.

Backups before any write (reference TODO.md:137 "Backup system — creates
backups before any write"): every touched file's pre-image is copied to
`.roundtable/backups/<session>-<timestamp>/<relpath>` so a bad apply is a
`cp -r` away from undone.
"""

from __future__ import annotations

import shutil
from dataclasses import dataclass, field
from datetime import datetime, timezone
from pathlib import Path
from typing import Optional

from .blocks import TOP_ANCHOR, scan_blocks
from .rtdiff import FileEdit


@dataclass
class ApplyOutcome:
    written: list[str] = field(default_factory=list)
    skipped: list[str] = field(default_factory=list)   # parley rejections
    backup_dir: Optional[str] = None


def materialize_edit(edit: FileEdit, current_text: Optional[str]) -> str:
    """Produce the file's new full text from its validated ops."""
    creates = [op for op in edit.ops if op.op == "FILE_CREATE"]
    if creates:
        content = creates[0].content or ""
        return content if content.endswith("\n") else content + "\n"

    assert current_text is not None
    legacy = [op for op in edit.ops if op.op == "SEARCH_REPLACE"]
    if legacy:
        text = current_text
        for op in legacy:
            text = text.replace(op.search or "", op.content or "", 1)
        return text

    lines = current_text.splitlines()
    had_trailing_nl = current_text.endswith("\n")
    blocks = {b.id: b for b in scan_blocks(current_text)}

    # Apply bottom-up so earlier ops don't shift later line ranges.
    def sort_key(op):
        if op.block_id == TOP_ANCHOR:
            return 0
        return blocks[op.block_id].start

    for op in sorted(edit.ops, key=sort_key, reverse=True):
        if op.block_id == TOP_ANCHOR:
            lines[0:0] = (op.content or "").splitlines()
            continue
        b = blocks[op.block_id]
        if op.op == "BLOCK_REPLACE":
            lines[b.start - 1:b.end] = (op.content or "").splitlines()
        elif op.op == "BLOCK_DELETE":
            del lines[b.start - 1:b.end]
            # A block ends where the next begins; eat ONE leading blank
            # line left behind so deletes don't accumulate gaps.
            if b.start - 1 < len(lines) and not lines[b.start - 1].strip():
                del lines[b.start - 1]
        elif op.op == "BLOCK_INSERT_AFTER":
            # A blank separator keeps the inserted block from gluing onto
            # the previous one.
            lines[b.end:b.end] = [""] + (op.content or "").splitlines()
    out = "\n".join(lines)
    if had_trailing_nl and not out.endswith("\n"):
        out += "\n"
    return out


def apply_edits(
    edits: list[FileEdit],
    project_root: str | Path,
    session_name: str,
    approve=None,
    dry_run: bool = False,
) -> ApplyOutcome:
    """Write every edit (unless dry_run), backing up pre-images first.

    approve(path, new_text) -> bool is the parley hook; None approves all
    (--noparley). Skipped files land in outcome.skipped → manifest status
    "partial" (reference README.md:190-193).
    """
    root = Path(project_root)
    outcome = ApplyOutcome()
    stamp = datetime.now(timezone.utc).strftime("%Y%m%d-%H%M%S")
    backup_dir = root / ".roundtable" / "backups" / f"{session_name}-{stamp}"

    plans: list[tuple[FileEdit, str]] = []
    for edit in edits:
        path = root / edit.clean_path
        current = (path.read_text(encoding="utf-8", errors="replace")
                   if path.is_file() else None)
        plans.append((edit, materialize_edit(edit, current)))

    for edit, new_text in plans:
        rel = edit.clean_path
        if approve is not None and not approve(rel, new_text):
            outcome.skipped.append(rel)
            continue
        if dry_run:
            outcome.written.append(rel)
            continue
        target = root / rel
        if target.is_file():
            backup_target = backup_dir / rel
            backup_target.parent.mkdir(parents=True, exist_ok=True)
            shutil.copy2(target, backup_target)
            outcome.backup_dir = str(backup_dir)
        target.parent.mkdir(parents=True, exist_ok=True)
        target.write_text(new_text, encoding="utf-8")
        outcome.written.append(rel)
    return outcome
