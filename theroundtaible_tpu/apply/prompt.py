"""Apply prompt builder — decision + source context + BLOCK_MAPs + rules.

Source-context injection per the reference's documented pipeline
(TODO.md:89-93,122): every in-scope file's content with a sha256 integrity
hash, a 500KB total limit with actionable error, 80KB per-file truncation,
and the "EDIT, DON'T REWRITE" mandatory editing rules.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from pathlib import Path

from ..core.errors import FileWriteError
from .blocks import render_block_map, scan_blocks
from .validate import sha256_text

MAX_TOTAL_SOURCE = 500_000   # reference TODO.md:122 (150KB → 500KB)
MAX_PER_FILE = 80_000        # reference TODO.md:122 per-file truncation

EDITING_RULES = """MANDATORY EDITING RULES (violations are rejected by validation):
1. EDIT, DON'T REWRITE — change only the blocks the decision requires;
   never re-emit a whole file that already exists.
2. Address blocks ONLY by the ids in the BLOCK_MAP below. Never invent
   ids, never address line numbers.
3. Emit COMPLETE blocks — a BLOCK_REPLACE body replaces the entire block,
   so include every line the block should contain afterwards.
4. One op per block. Do not touch the same block twice.
5. Only files in the agreed scope. New files need the NEW: prefix and a
   FILE_CREATE op.
6. Match the file's existing style (indentation, quotes, naming).
7. Output ONLY the RTDIFF/1 document — no prose before the header, no
   commentary between ops.

OUTPUT FORMAT:
RTDIFF/1
FILE: path/to/existing.py
BLOCK_REPLACE B004
<<<
...new lines for the whole block...
>>>
BLOCK_INSERT_AFTER B007
<<<
...lines inserted after block B007...
>>>
BLOCK_DELETE B009
FILE: NEW:path/to/new_file.py
FILE_CREATE
<<<
...entire new file...
>>>

(BLOCK_INSERT_AFTER B000 inserts at the very top of a file.)"""


@dataclass
class ApplyContext:
    prompt: str
    source_hashes: dict[str, str] = field(default_factory=dict)
    truncated: list[str] = field(default_factory=list)


def build_apply_prompt(
    project_root: str | Path,
    topic: str,
    decision: str,
    allowed_files: list[str],
) -> ApplyContext:
    """Assemble the Lead Knight's apply prompt. Raises FileWriteError when
    the in-scope sources blow the 500KB limit (actionable: shrink scope)."""
    root = Path(project_root)
    hashes: dict[str, str] = {}
    truncated: list[str] = []
    sections: list[str] = []
    total = 0

    for raw in allowed_files:
        is_new = raw.upper().startswith("NEW:")
        rel = raw[4:].strip() if is_new else raw
        full = root / rel
        if is_new or not full.is_file():
            sections.append(f"FILE {raw} — does not exist yet "
                            "(create with FILE_CREATE)")
            continue
        text = full.read_text(encoding="utf-8", errors="replace")
        hashes[rel] = sha256_text(text)
        shown = text
        if len(shown) > MAX_PER_FILE:
            shown = shown[:MAX_PER_FILE]
            truncated.append(rel)
        total += len(shown)
        if total > MAX_TOTAL_SOURCE:
            raise FileWriteError(
                f"apply source context exceeds {MAX_TOTAL_SOURCE // 1000}KB "
                f"at {rel} — narrow files_to_modify or apply in stages",
                hint="re-run discuss with a smaller scope, or deprecate "
                     "files from the scope before applying")
        block_map = render_block_map(rel, scan_blocks(text))
        trunc_note = ("\n(TRUNCATED at 80KB — edit only blocks you can "
                      "see)" if rel in truncated else "")
        sections.append(
            f"FILE {rel} (sha256 {hashes[rel][:16]}…){trunc_note}\n"
            f"{block_map}\n"
            f"--- content ---\n{shown}\n--- end {rel} ---")

    prompt = "\n\n".join([
        "You are the Lead Knight of TheRoundtAIble. The council reached "
        "consensus; you now EXECUTE the decision by emitting RTDIFF/1 "
        "block edits.",
        f"TOPIC:\n{topic}",
        f"THE DECISION (from decisions.md):\n{decision}",
        f"AGREED SCOPE (the only files you may touch):\n"
        + "\n".join(f"- {f}" for f in allowed_files),
        EDITING_RULES,
        "SOURCE FILES:\n\n" + "\n\n".join(sections),
    ])
    return ApplyContext(prompt=prompt, source_hashes=hashes,
                        truncated=truncated)
