"""Block scanner — split source files into stable, addressable blocks.

The RTDIFF/1 system (reference TODO.md:88,130-137: "block-scanner +
diff-parser + BLOCK_MAP prompt") exists because search-and-replace editing
was unreliable on large files (reference TODO.md:126-128). Instead, the
knight is shown a BLOCK_MAP — every block's id, line range, and signature —
and addresses edits to block ids, never to line numbers or search strings.

The scanner is language-agnostic: a new block starts at every non-indented,
non-blank line that follows a blank line or closes a previous top-level
unit. Decorators/attributes/comments directly above a block attach to it.
Oversized blocks are split so a single BLOCK_REPLACE never forces the
knight to re-emit hundreds of lines (the failure mode block editing fixes).
"""

from __future__ import annotations

from dataclasses import dataclass

MAX_BLOCK_LINES = 60

# Lines that glue themselves to the NEXT block (decorators, comments).
_ATTACH_PREFIXES = ("@", "#", "//", "/*", "*", "--")

# The virtual anchor for BLOCK_INSERT_AFTER at the very top of a file.
TOP_ANCHOR = "B000"


@dataclass(frozen=True)
class Block:
    """One addressable unit of a file. Lines are 1-based inclusive."""

    id: str
    start: int
    end: int
    text: str

    @property
    def signature(self) -> str:
        for line in self.text.splitlines():
            if line.strip():
                return line.strip()[:80]
        return "(blank)"


def _is_boundary(line: str, prev_blank: bool) -> bool:
    if not line.strip():
        return False
    if line[0] in (" ", "\t"):
        return False
    return prev_blank


def scan_blocks(text: str) -> list[Block]:
    """Scan file text into blocks covering every line exactly once."""
    lines = text.splitlines()
    if not lines:
        return []

    starts: list[int] = [0]
    prev_blank = False
    for i, line in enumerate(lines):
        if i > 0 and _is_boundary(line, prev_blank):
            # Walk back over attached decorator/comment lines so they move
            # with the block they annotate.
            start = i
            j = i - 1
            while j > starts[-1] and lines[j].strip() and \
                    lines[j].lstrip().startswith(_ATTACH_PREFIXES) and \
                    lines[j][0] not in (" ", "\t"):
                start = j
                j -= 1
            if start > starts[-1]:
                starts.append(start)
        prev_blank = not line.strip()

    # Split oversized blocks at blank lines (or hard-chop as last resort).
    bounded: list[int] = []
    for idx, start in enumerate(starts):
        end = starts[idx + 1] if idx + 1 < len(starts) else len(lines)
        bounded.append(start)
        cursor = start
        while end - cursor > MAX_BLOCK_LINES:
            window = lines[cursor + MAX_BLOCK_LINES // 2:
                           cursor + MAX_BLOCK_LINES]
            split = None
            for off, line in enumerate(window):
                if not line.strip():
                    split = cursor + MAX_BLOCK_LINES // 2 + off + 1
            if split is None or split <= cursor:
                split = cursor + MAX_BLOCK_LINES
            if split >= end:
                break
            bounded.append(split)
            cursor = split

    blocks = []
    for idx, start in enumerate(bounded):
        end = bounded[idx + 1] if idx + 1 < len(bounded) else len(lines)
        blocks.append(Block(
            id=f"B{idx + 1:03d}",
            start=start + 1,
            end=end,
            text="\n".join(lines[start:end]),
        ))
    return blocks


def render_block_map(path: str, blocks: list[Block]) -> str:
    """The BLOCK_MAP section injected into the apply prompt."""
    lines = [f"BLOCK_MAP {path} ({len(blocks)} blocks)"]
    lines.append(f"  {TOP_ANCHOR} [top-of-file anchor — "
                 "BLOCK_INSERT_AFTER B000 inserts at line 1]")
    for b in blocks:
        lines.append(f"  {b.id} [L{b.start}-{b.end}] {b.signature}")
    return "\n".join(lines)
