from .loader import (  # noqa: F401
    lcp, native_available, read_safetensors)
