"""ctypes binding for the native runtime library (native/rt_native.cc).

Python parses the safetensors JSON header (bytes, not gigabytes); the C++
side mmaps the payload and does the multithreaded dtype conversion into
caller-owned numpy buffers. Everything degrades cleanly: when the library
is missing and can't be built, read_safetensors returns None and callers
fall back to the pure-Python `safetensors` package, and lcp falls back to
a Python loop.

The library self-builds on first use when g++ is available (a single
translation unit, ~1s) — same command as `make -C native`.
"""

from __future__ import annotations

import ctypes
import json
import struct
import subprocess
import threading
from pathlib import Path
from typing import Optional

import numpy as np

_PKG_DIR = Path(__file__).parent
_SO_PATH = _PKG_DIR / "librt_native.so"
_SRC_PATH = _PKG_DIR.parent.parent / "native" / "rt_native.cc"

_lib = None
_lib_tried = False
_lock = threading.Lock()

# Must match DType in rt_native.cc.
_DTYPES = {"F32": 0, "F16": 1, "BF16": 2, "F64": 3, "I64": 4, "I32": 5,
           "U8": 6, "I8": 7}


class _TensorJob(ctypes.Structure):
    _fields_ = [
        ("src_offset", ctypes.c_uint64),
        ("n_elems", ctypes.c_uint64),
        ("src_dtype", ctypes.c_int32),
        ("pad", ctypes.c_int32),
        ("dst", ctypes.c_void_p),
    ]


def _build() -> bool:
    if not _SRC_PATH.exists():
        return False
    # build to a temp path + atomic rename: another process racing this
    # build must never dlopen a half-written .so
    import os
    tmp = _SO_PATH.with_suffix(f".tmp{os.getpid()}.so")
    try:
        subprocess.run(
            ["g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
             "-o", str(tmp), str(_SRC_PATH)],
            check=True, capture_output=True, timeout=120)
        os.replace(tmp, _SO_PATH)
        return _SO_PATH.exists()
    except (OSError, subprocess.SubprocessError):
        tmp.unlink(missing_ok=True)
        return False


def _get_lib(build: bool = True):
    global _lib, _lib_tried
    with _lock:
        if _lib_tried:
            return _lib
        if not build:
            # latency-sensitive caller: load only if the .so already
            # exists; never shell out to g++ and never latch a negative
            # result (a later load path may still build it)
            if not _SO_PATH.exists():
                return None
        _lib_tried = True
        if not _SO_PATH.exists() and not _build():
            return None
        try:
            lib = ctypes.CDLL(str(_SO_PATH))
            lib.st_convert.restype = ctypes.c_int
            lib.st_convert.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(_TensorJob),
                ctypes.c_int64, ctypes.c_int32]
            lib.rt_lcp.restype = ctypes.c_int64
            lib.rt_lcp.argtypes = [
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64]
            _lib = lib
        except OSError:
            _lib = None
        return _lib


def native_available() -> bool:
    return _get_lib() is not None


def iter_safetensors(path: str | Path, n_threads: int = 0):
    """Yield (name, float32 array) one tensor at a time.

    Streaming contract: peak host memory is ONE tensor's f32 copy, not the
    whole shard (a consolidated Mixtral shard would not fit doubled). The
    mmap inside st_convert is per-call but lazy, so per-tensor calls cost
    only the pages actually read; big tensors still fan out across
    converter threads. Yields nothing (empty iterator) when the library is
    unavailable — callers then fall back to the `safetensors` package.
    """
    lib = _get_lib()
    if lib is None:
        return
    path = Path(path)
    with open(path, "rb") as f:
        (header_len,) = struct.unpack("<Q", f.read(8))
        header = json.loads(f.read(header_len))
    payload_base = 8 + header_len

    _ELEM_SIZE = {"F32": 4, "F16": 2, "BF16": 2, "F64": 8, "I64": 8,
                  "I32": 4, "U8": 1, "I8": 1}
    for name, meta in header.items():
        if name == "__metadata__":
            continue
        dtype = meta["dtype"]
        if dtype not in _DTYPES:
            raise ValueError(f"unsupported safetensors dtype {dtype}")
        begin, end = meta["data_offsets"]
        out = np.empty(meta["shape"], np.float32)
        # a shape/offsets mismatch must fail loudly, not read the next
        # tensor's bytes as this one's tail
        if (begin < 0 or end < begin
                or end - begin != out.size * _ELEM_SIZE[dtype]):
            raise ValueError(
                f"tensor {name}: data_offsets {begin}:{end} disagree "
                f"with shape {meta['shape']} ({dtype})")
        job = (_TensorJob * 1)()
        job[0].src_offset = payload_base + begin
        job[0].n_elems = out.size
        job[0].src_dtype = _DTYPES[dtype]
        job[0].dst = out.ctypes.data
        rc = lib.st_convert(str(path).encode(), job, 1, n_threads)
        if rc != 0:
            raise OSError(f"st_convert failed ({rc}) on {path}")
        yield name, out


def native_can_read(path: str | Path) -> bool:
    """Library built AND every tensor dtype in the file is convertible —
    checked up front so a stream never fails after partial yield."""
    if _get_lib() is None:
        return False
    try:
        with open(path, "rb") as f:
            (header_len,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(header_len))
        return all(meta.get("dtype") in _DTYPES
                   for name, meta in header.items()
                   if name != "__metadata__")
    except Exception:  # noqa: BLE001 — contract: malformed file → False,
        return False   # caller takes the safetensors-package fallback


def read_safetensors(path: str | Path,
                     n_threads: int = 0
                     ) -> Optional[dict[str, np.ndarray]]:
    """Read every tensor of a .safetensors file as float32 arrays at once.

    Convenience for small files/tests; checkpoint loading streams via
    iter_safetensors instead. Returns None when the native library is
    unavailable or a dtype is unsupported.
    """
    if _get_lib() is None:
        return None
    try:
        return dict(iter_safetensors(path, n_threads))
    except (ValueError, OSError) as e:
        if isinstance(e, FileNotFoundError):
            raise
        return None


def lcp(a: list[int], b: list[int]) -> int:
    """Longest common prefix of two token-id sequences (KV reuse).

    Serving hot path: consults only the already-loaded library handle (no
    lock, no filesystem stat, never the g++ self-build). Short inputs and
    early mismatches stay on the Python loop — it exits at the first
    differing token, cheaper than materializing int32 arrays."""
    n = min(len(a), len(b))
    if _lib is None or n < 1024 or a[0] != b[0]:
        i = 0
        while i < n and a[i] == b[i]:
            i += 1
        return i
    arr_a = np.asarray(a, np.int32)
    arr_b = np.asarray(b, np.int32)
    return int(_lib.rt_lcp(
        arr_a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(arr_a),
        arr_b.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)), len(arr_b)))
