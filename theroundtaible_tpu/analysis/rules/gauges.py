"""RT-GAUGE-LEAK — per-entity gauge series must have a reachable
remove_gauge (the PR-6 lesson PRs 9, 10 and 13 each re-fixed by hand).

A `set_gauge(name, ..., session=... | adapter=... | row=... |
drafter=...)` call creates one labeled series per ENTITY, and sessions/
adapters/rows are uuid-tagged per serve call: a long-lived serving
process grows the registry (and every metrics.prom export) one dead
series per entity ever served unless retirement removes the series.
The static check: for every gauge series name set with a per-entity
label key anywhere in the tree, a `remove_gauge` call naming the SAME
series literal must exist somewhere in the tree — set and remove are
allowed to live in different files (the scheduler removes what the
perfmodel publishes), but a series with no remove path at all is the
exact leak shipped three times already.

Bounded-domain labels (a `drafter` whose values are the closed set
ngram|model|lora) are real findings too: the boundedness is a fact
about TODAY's call sites, not the registry — such series are
allowlisted with the boundedness written down as the reason, so the
next person adding a drafter kind sees the contract.
"""

from __future__ import annotations

import ast

from ..astlint import Finding, ProjectIndex, Rule, call_name, str_const

# Label keys whose value domain is an open per-entity namespace (or a
# domain the registry cannot bound). `engine`/`phase`/`rung` label
# domains are config-bounded and excluded on purpose.
PER_ENTITY_KEYS = frozenset(
    {"session", "session_id", "adapter", "row", "request", "drafter"})


class GaugeLeakRule(Rule):
    id = "RT-GAUGE-LEAK"
    severity = "error"
    description = ("per-entity labeled gauge series set without any "
                   "reachable remove_gauge for the same series name")

    def run(self, index: ProjectIndex) -> list[Finding]:
        sets: list[tuple[str, int, str, str]] = []  # path,line,series,key
        removed: set[str] = set()
        for rel in index.files():
            for node in ast.walk(index.tree(rel)):
                if not isinstance(node, ast.Call):
                    continue
                name = call_name(node)
                series = str_const(node.args[0]) if node.args else None
                if series is None:
                    continue
                if name == "remove_gauge":
                    removed.add(series)
                elif name == "set_gauge":
                    for kw in node.keywords:
                        if kw.arg in PER_ENTITY_KEYS:
                            sets.append((rel, node.lineno, series,
                                         kw.arg))
                            break
        out = []
        for rel, line, series, key in sets:
            if series in removed:
                continue
            out.append(self.finding(
                rel, line,
                f"gauge series {series!r} is set with per-entity label "
                f"{key}= but no remove_gauge({series!r}, ...) exists "
                "anywhere in the tree — a long-lived serving process "
                "keeps one dead series per retired entity (the PR-6 "
                "gauge-leak lesson); remove the series at retirement, "
                "or allowlist with the label's boundedness written "
                "down"))
        return out
