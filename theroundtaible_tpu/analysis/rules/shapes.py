"""RT-SHAPE-VALUE — runtime state must not flow raw into static shape
arguments (the RECOMPILE_STRICT discipline, provable before a device
exists).

The repo's whole shape discipline is that compiled-program shapes are
functions of CONFIG alone: occupancy drift, acceptance drift and
adapter mixes are VALUES. The seams where that discipline is decided
are the static parameters of `build_ragged_batch` (t_budget / s_max /
score_width / copy_slots — each distinct value is one compiled ragged
program) and the static kwargs of the decode dispatch seams (max_new /
greedy). A `len(rows)`-shaped expression or a traced `.shape` read
flowing DIRECTLY into one of those is a mid-serve recompile per
occupancy value — the exact bug class ROUNDTABLE_RECOMPILE_STRICT=1
exists to catch at runtime, caught here at parse time instead.

Runtime-derived values are fine once laundered through the sanctioned
config-bounded resolvers (`pow2_bucket`, `ragged_pick_shape`,
`clamp_max_new`): those map unbounded runtime values onto the small
warmed grid, which is the discipline, not a violation of it.
"""

from __future__ import annotations

import ast

from ..astlint import Finding, ProjectIndex, Rule, call_name

# callee -> static parameter names whose value expression is audited.
STATIC_PARAMS: dict[str, frozenset[str]] = {
    "build_ragged_batch": frozenset(
        {"t_budget", "s_max", "score_width", "copy_slots",
         "propose_width"}),
    "_decode_dispatch_paged": frozenset({"max_new"}),
    "_decode_dispatch_slots": frozenset({"max_new"}),
    "_ragged_step": frozenset({"score_width", "propose_width"}),
}

# Bounded resolvers: an audited expression wrapped in one of these is
# the sanctioned runtime->grid mapping. Deliberately ONLY the grid
# resolvers — int()/min() are identities/clamps on runtime values, not
# grid-bounding maps, and sanctioning them would let `int(len(rows))`
# lint clean while still compiling one program per occupancy.
SANCTIONED = frozenset({"pow2_bucket", "ragged_pick_shape",
                        "clamp_max_new"})

# Attribute/name fragments that mark a value as runtime serving state.
_RUNTIME_ATTRS = frozenset({"shape", "occupancy", "free_pages",
                            "pages_held", "valid"})


def _violations(expr: ast.AST) -> list[tuple[int, str]]:
    """(line, what) for each raw runtime-state read inside `expr`,
    skipping subtrees wrapped in a sanctioned resolver."""
    out: list[tuple[int, str]] = []

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.Call):
            if call_name(node) in SANCTIONED:
                return      # laundered through the bounded grid
            if (isinstance(node.func, ast.Name)
                    and node.func.id == "len"):
                out.append((node.lineno, "len(...)"))
                return
            if call_name(node) in _RUNTIME_ATTRS:
                out.append((node.lineno, f"{call_name(node)}()"))
                return
        if (isinstance(node, ast.Attribute)
                and node.attr in _RUNTIME_ATTRS
                and not isinstance(node.ctx, ast.Store)):
            out.append((node.lineno, f".{node.attr}"))
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    walk(expr)
    return out


class ShapeValueRule(Rule):
    id = "RT-SHAPE-VALUE"
    severity = "error"
    description = ("runtime-derived value (len/.shape/occupancy) "
                   "flowing raw into a static shape argument — one "
                   "compile per runtime value")

    def run(self, index: ProjectIndex) -> list[Finding]:
        out: list[Finding] = []
        for rel in index.files():
            for node in ast.walk(index.tree(rel)):
                if not isinstance(node, ast.Call):
                    continue
                params = STATIC_PARAMS.get(call_name(node))
                if params is None:
                    continue
                for kw in node.keywords:
                    if kw.arg not in params:
                        continue
                    for line, what in _violations(kw.value):
                        out.append(self.finding(
                            rel, line,
                            f"{what} flows raw into static argument "
                            f"{kw.arg}= of {call_name(node)}() — every "
                            "distinct runtime value compiles a fresh "
                            "program mid-serve (RECOMPILE_STRICT "
                            "violation); route it through pow2_bucket/"
                            "ragged_pick_shape or derive it from "
                            "config"))
        return out
