"""Rule registry — one class per serving invariant (ISSUE 15).

Each rule encodes a lesson a previous PR paid for dynamically; the ids
are stable machine-readable handles the allowlist, --rules filter and
--json output key on.
"""

from __future__ import annotations

from typing import Iterable, Optional

from ..astlint import Rule
from .envdoc import EnvDocRule
from .error_kinds import ErrorKindRule
from .gauges import GaugeLeakRule
from .locking import LockBumpRule
from .markers import MarkerRegRule
from .shapes import ShapeValueRule
from .spans import SpanLeakRule
from .surface_drift import SurfaceDriftRule

ALL_RULES: tuple[type[Rule], ...] = (
    GaugeLeakRule,
    LockBumpRule,
    ErrorKindRule,
    ShapeValueRule,
    MarkerRegRule,
    EnvDocRule,
    SurfaceDriftRule,
    SpanLeakRule,
)

RULES_BY_ID = {cls.id: cls for cls in ALL_RULES}


def get_rules(ids: Optional[Iterable[str]] = None) -> list[Rule]:
    """Instantiate the requested rules (all by default). Unknown ids
    raise — a typo'd --rules filter must not silently lint nothing."""
    if ids is None:
        return [cls() for cls in ALL_RULES]
    out = []
    for rid in ids:
        cls = RULES_BY_ID.get(rid)
        if cls is None:
            raise ValueError(
                f"unknown rule id {rid!r} — known: "
                f"{', '.join(sorted(RULES_BY_ID))}")
        out.append(cls())
    return out
