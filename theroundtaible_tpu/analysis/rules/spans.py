"""RT-SPAN-LEAK — every `telemetry.start_span(...)` needs a reachable
`.end()` (the gauge-leak lesson applied to spans, ISSUE 20).

`start_span` is the explicit-lifecycle half of the span API: unlike
`with telemetry.span(...)`, nothing ends it when the holder forgets.
An unended span never emits its record (the duration the critical-path
analyzer attributes), never lands in the flight ring, and leaks its
thread-stack entry if it was entered — the trace it belongs to shows a
hole exactly where the interesting latency went.

The static check mirrors RT-GAUGE-LEAK's shape: a `start_span` call is
fine when its result provably reaches an `.end()` or a with-block —

- used as a context manager:   `with telemetry.start_span(...):`
- directly returned:            ownership transfers to the caller
  (the `telemetry.span()` wrapper itself does this)
- chained:                      `telemetry.start_span(...).end()`
- bound to a local name `x`:    some `x.end(...)` / `with x` /
  `return x` exists in the same enclosing function
- bound to an attribute `o.a`:  some `<anything>.a.end(...)` exists in
  the same FILE (the scheduler starts `req.tele` at submit and ends it
  in `_retire_finished` / `_fail_request`; `RequestTrace` starts
  `self.span` in __init__ and ends it in `finish()`)

Anything else — discarded result, name that is never ended — is a
finding.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..astlint import Finding, ProjectIndex, Rule, call_name


def _enclosing_fn(index: ProjectIndex, rel: str,
                  node: ast.AST) -> Optional[ast.AST]:
    fns = index.enclosing(
        rel, node, (ast.FunctionDef, ast.AsyncFunctionDef))
    return fns[0] if fns else index.tree(rel)


def _name_ended(scope: ast.AST, name: str) -> bool:
    """Does `name` reach an end within `scope`: `name.end(...)`,
    `with name ...`, or `return name` (ownership transfer)?"""
    for node in ast.walk(scope):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "end"
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == name):
            return True
        if isinstance(node, ast.withitem):
            ctx = node.context_expr
            if isinstance(ctx, ast.Name) and ctx.id == name:
                return True
        if (isinstance(node, ast.Return)
                and isinstance(node.value, ast.Name)
                and node.value.id == name):
            return True
    return False


def _attr_ended(tree: ast.Module, attr: str) -> bool:
    """Does any `<expr>.{attr}.end(...)` exist in the file?"""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "end"
                and isinstance(node.func.value, ast.Attribute)
                and node.func.value.attr == attr):
            return True
    return False


class SpanLeakRule(Rule):
    id = "RT-SPAN-LEAK"
    severity = "error"
    description = ("telemetry.start_span(...) whose span never "
                   "reaches .end() or a with-block")

    def run(self, index: ProjectIndex) -> list[Finding]:
        out: list[Finding] = []
        for rel in index.files():
            tree = index.tree(rel)
            parents = index.parents(rel)
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and call_name(node) == "start_span"):
                    continue
                parent = parents.get(node)
                if isinstance(parent, (ast.withitem, ast.Return)):
                    continue
                if (isinstance(parent, ast.Attribute)
                        and parent.attr == "end"):
                    continue  # start_span(...).end()
                if isinstance(parent, ast.Assign) \
                        and len(parent.targets) == 1:
                    target = parent.targets[0]
                    if isinstance(target, ast.Name) and _name_ended(
                            _enclosing_fn(index, rel, node), target.id):
                        continue
                    if isinstance(target, ast.Attribute) \
                            and _attr_ended(tree, target.attr):
                        continue
                out.append(self.finding(
                    rel, node.lineno,
                    "start_span(...) result never reaches .end() or a "
                    "with-block on any visible path — the span never "
                    "emits its record and the trace it belongs to "
                    "shows a hole where the latency went (the gauge-"
                    "leak lesson applied to spans, ISSUE 20); context-"
                    "manage it, end the bound name in this function, "
                    "or end the attribute it is stored on somewhere "
                    "in this file"))
        return out
