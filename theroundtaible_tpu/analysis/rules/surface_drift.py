"""RT-SURFACE-DRIFT — observability surface keys must be bound to
registry series in telemetry.SURFACE_BINDINGS (the ISSUE-5 single-
source-of-truth contract, now with file/line findings).

This is the static migration of the tests/test_telemetry.py
TestSurfaceDrift pair (which stays in place — the dynamic test proves
the RUNTIME dict matches; this rule points at the exact offending key
expression without constructing an engine): the dict literals returned
by `fleet_health()` (engine/fleet.py) and `SessionScheduler.describe()`
(engine/scheduler.py) may only carry keys declared in
`utils/telemetry.py`'s SURFACE_BINDINGS — a new surface key with no
declared registry backing is how the four PR-1..4 provenance stores
forked in the first place.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..astlint import Finding, ProjectIndex, Rule

# surface name in SURFACE_BINDINGS -> (file suffix, locator)
_SURFACES = (
    ("fleet_health", "engine/fleet.py", ("function", "fleet_health")),
    ("scheduler_describe", "engine/scheduler.py",
     ("method", "SessionScheduler", "describe")),
    # ISSUE 19: the capacity view's machine shape — frontier record
    # joined with live gateway series.
    ("capacity_status", "commands/status.py",
     ("function", "capacity_surface")),
    # ISSUE 20: the SLO burn-rate view — capacity-record baseline
    # joined with the live burn gauges and trace retention.
    ("slo_status", "commands/status.py",
     ("function", "slo_surface")),
)


def _literal_keys(fn: ast.FunctionDef) -> list[tuple[str, int]]:
    """String keys (with lines) of every dict literal returned by
    `fn`, ignoring nested function bodies."""
    out: list[tuple[str, int]] = []
    for node in ast.walk(fn):
        if not (isinstance(node, ast.Return)
                and isinstance(node.value, ast.Dict)):
            continue
        for k in node.value.keys:
            if isinstance(k, ast.Constant) and isinstance(k.value, str):
                out.append((k.value, k.lineno))
    return out


def _find_fn(tree: ast.Module,
             locator: tuple) -> Optional[ast.FunctionDef]:
    if locator[0] == "function":
        for node in tree.body:
            if (isinstance(node, ast.FunctionDef)
                    and node.name == locator[1]):
                return node
        return None
    for node in tree.body:
        if isinstance(node, ast.ClassDef) and node.name == locator[1]:
            for item in node.body:
                if (isinstance(item, ast.FunctionDef)
                        and item.name == locator[2]):
                    return item
    return None


def bound_keys(index: ProjectIndex) -> dict[str, set[str]]:
    """Surface -> declared keys, parsed from the SURFACE_BINDINGS dict
    literal in utils/telemetry.py."""
    rel = index.find_file("utils/telemetry.py")
    out: dict[str, set[str]] = {}
    if rel is None:
        return out
    for node in ast.walk(index.tree(rel)):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        if not any(isinstance(t, ast.Name)
                   and t.id == "SURFACE_BINDINGS" for t in targets):
            continue
        if not isinstance(node.value, ast.Dict):
            continue
        for k, v in zip(node.value.keys, node.value.values):
            if (isinstance(k, ast.Constant)
                    and isinstance(v, ast.Dict)):
                out[k.value] = {
                    kk.value for kk in v.keys
                    if isinstance(kk, ast.Constant)
                    and isinstance(kk.value, str)}
    return out


class SurfaceDriftRule(Rule):
    id = "RT-SURFACE-DRIFT"
    severity = "error"
    description = ("observability surface key with no "
                   "SURFACE_BINDINGS registry declaration")

    def run(self, index: ProjectIndex) -> list[Finding]:
        bindings = bound_keys(index)
        out: list[Finding] = []
        for surface, suffix, locator in _SURFACES:
            rel = index.find_file(suffix)
            if rel is None or surface not in bindings:
                continue
            fn = _find_fn(index.tree(rel), locator)
            if fn is None:
                continue
            declared = bindings[surface]
            for key, line in _literal_keys(fn):
                if key not in declared:
                    out.append(self.finding(
                        rel, line,
                        f"surface key {key!r} of {surface} has no "
                        "registry binding — declare how the unified "
                        "registry sees it in telemetry."
                        f"SURFACE_BINDINGS[{surface!r}] (the single-"
                        "source-of-truth contract, ISSUE 5)"))
        return out
