"""RT-LOCK-BUMP — SessionScheduler counter bumps happen under the cv
or in a documented loop-thread-only method.

The scheduler's provenance counters (`self._bump(...)` — which moves
the attribute AND its registry series in lockstep) have exactly two
sanctioned writers: code holding `self._cv`/`self._lock` (submitter /
drain / monitoring threads racing each other), and the single-writer
scheduler loop thread. The second case is a THREADING CONTRACT the
code cannot show lexically, so this rule requires it written down: a
bump outside a `with self._cv:` block is only clean when the enclosing
method's docstring declares the loop-thread contract ("loop thread" /
"loop-thread" / "scheduler thread"). A bump that is neither locked nor
documented is exactly the racy increment PR 4's review passes kept
finding by hand.
"""

from __future__ import annotations

import ast

from ..astlint import Finding, ProjectIndex, Rule, call_name, dotted_name

_LOCK_ATTRS = ("self._cv", "self._lock")
_LOOP_MARKERS = ("loop thread", "loop-thread", "scheduler thread")
_COUNTER_CALLS = frozenset({"_bump"})


def _with_holds_lock(node: ast.With) -> bool:
    for item in node.items:
        if dotted_name(item.context_expr) in _LOCK_ATTRS:
            return True
    return False


class LockBumpRule(Rule):
    id = "RT-LOCK-BUMP"
    severity = "error"
    description = ("scheduler counter mutation outside a with "
                   "self._cv/_lock block in a method not documented "
                   "loop-thread-only")

    def run(self, index: ProjectIndex) -> list[Finding]:
        out: list[Finding] = []
        for rel in index.files():
            tree = index.tree(rel)
            for cls in ast.walk(tree):
                if (isinstance(cls, ast.ClassDef)
                        and cls.name == "SessionScheduler"):
                    out.extend(self._check_class(index, rel, cls))
        return out

    def _check_class(self, index: ProjectIndex, rel: str,
                     cls: ast.ClassDef) -> list[Finding]:
        out = []
        for node in ast.walk(cls):
            if not (isinstance(node, ast.Call)
                    and call_name(node) in _COUNTER_CALLS
                    and dotted_name(node.func).startswith("self.")):
                continue
            encl = index.enclosing(
                rel, node, (ast.With, ast.FunctionDef,
                            ast.AsyncFunctionDef))
            method = next((e for e in encl
                           if isinstance(e, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))),
                          None)
            if method is not None and method.name in _COUNTER_CALLS:
                continue    # the definition itself
            if any(isinstance(e, ast.With) and _with_holds_lock(e)
                   for e in encl):
                continue
            doc = (ast.get_docstring(method) or "") if method else ""
            if any(m in doc.lower() for m in _LOOP_MARKERS):
                continue
            where = method.name if method else "<module>"
            out.append(self.finding(
                rel, node.lineno,
                f"self._bump(...) in {where}() runs outside a `with "
                "self._cv:`/`with self._lock:` block and the method's "
                "docstring does not declare the loop-thread-only "
                "contract — either take the cv (it is reentrant) or "
                "document which single thread owns this path"))
        return out
