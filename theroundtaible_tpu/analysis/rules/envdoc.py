"""RT-ENV-DOC — every ROUNDTABLE_* environment variable the package
reads is documented in README.md or ARCHITECTURE.md.

Env vars are this repo's operational surface (kill switches, STRICT
mode, budgets); an undocumented one is a control an operator cannot
find during an incident. Detection is read-context-based so doc prose
and rule source never self-flag: a ROUNDTABLE_* string literal counts
only when it is (a) an argument of an os.environ/getenv read, (b) a
subscript key of environ, or (c) assigned to a `*_ENV` constant (the
serving_loop pattern, read later through the constant).
"""

from __future__ import annotations

import ast
import re

from ..astlint import Finding, ProjectIndex, Rule, dotted_name, str_const

_VAR = re.compile(r"^ROUNDTABLE_[A-Z0-9_]+$")


def _env_call(node: ast.Call) -> bool:
    name = dotted_name(node.func)
    return (name.endswith("environ.get")
            or name.endswith("environ.setdefault")
            or name.endswith("environ.pop")
            or name.endswith("os.getenv")
            or name == "getenv")


class EnvDocRule(Rule):
    id = "RT-ENV-DOC"
    severity = "error"
    description = ("ROUNDTABLE_* env var read in the package with no "
                   "README.md / ARCHITECTURE.md mention")

    def run(self, index: ProjectIndex) -> list[Finding]:
        docs = index.text("README.md", "ARCHITECTURE.md")
        documented = set(re.findall(r"ROUNDTABLE_[A-Z0-9_]+", docs))
        reads: dict[str, tuple[str, int]] = {}
        for rel in index.files():
            if rel.split("/")[0] == "tests":
                continue
            for node in ast.walk(index.tree(rel)):
                var = None
                if isinstance(node, ast.Call) and _env_call(node):
                    for arg in node.args[:1]:
                        var = str_const(arg)
                elif (isinstance(node, ast.Subscript)
                      and dotted_name(node.value).endswith("environ")):
                    var = str_const(node.slice)
                elif isinstance(node, ast.Assign):
                    if any(isinstance(t, ast.Name)
                           and t.id.endswith("_ENV")
                           for t in node.targets):
                        var = str_const(node.value)
                if var is not None and _VAR.match(var):
                    reads.setdefault(var, (rel, node.lineno))
        out = []
        for var in sorted(set(reads) - documented):
            rel, line = reads[var]
            out.append(self.finding(
                rel, line,
                f"env var {var} is read here but appears nowhere in "
                "README.md or ARCHITECTURE.md — an operational control "
                "nobody can find during an incident; document it (or "
                "delete the dead read)"))
        return out
