"""RT-ERROR-KIND — every in-tree exception class raised under engine/
must be classifiable (core/errors.py), not just raisable.

PR 12's `device_lost` bug class: an engine error the classifier had
never heard of took the wrong recovery ladder (a blind retry on a dead
chip) because classification is message-sniffing and nobody registered
the new class. The static check: for every `raise X(...)` in engine/
where X is a class DEFINED in this tree, X must either

- subclass (transitively, by the in-tree class graph) the
  RoundtableError family — those carry exit codes and, for
  AdapterError, an explicit `kind`; or
- appear as a key of core/errors.py's `ERROR_KIND_TABLE` — the
  declarative class→kind classification table `classify_error`
  consults when message sniffing comes up empty.

Stdlib raises (ValueError, RuntimeError, ...) are out of scope: their
classification IS the message-marker sniffing, by design.
"""

from __future__ import annotations

import ast
from typing import Optional

from ..astlint import Finding, ProjectIndex, Rule

_ROOT_FAMILY = {"RoundtableError", "ConfigError", "AdapterError",
                "SessionError", "FileWriteError", "ConsensusError"}


def _base_names(cls: ast.ClassDef) -> list[str]:
    out = []
    for b in cls.bases:
        if isinstance(b, ast.Name):
            out.append(b.id)
        elif isinstance(b, ast.Attribute):
            out.append(b.attr)
    return out


def _table_keys(index: ProjectIndex, errors_rel: str) -> set[str]:
    """Keys of the ERROR_KIND_TABLE dict literal in core/errors.py."""
    keys: set[str] = set()
    tree = index.tree(errors_rel)
    if tree is None:
        return keys
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = (node.targets if isinstance(node, ast.Assign)
                   else [node.target])
        names = {t.id for t in targets if isinstance(t, ast.Name)}
        if "ERROR_KIND_TABLE" not in names:
            continue
        value = node.value
        if isinstance(value, ast.Dict):
            for k in value.keys:
                if (isinstance(k, ast.Constant)
                        and isinstance(k.value, str)):
                    keys.add(k.value)
    return keys


class ErrorKindRule(Rule):
    id = "RT-ERROR-KIND"
    severity = "error"
    description = ("in-tree exception class raised in engine/ that is "
                   "neither a RoundtableError descendant nor "
                   "registered in core/errors.py ERROR_KIND_TABLE")

    def run(self, index: ProjectIndex) -> list[Finding]:
        # In-tree class graph (name -> base names), tree-wide.
        bases: dict[str, list[str]] = {}
        for rel in index.files():
            for node in ast.walk(index.tree(rel)):
                if isinstance(node, ast.ClassDef):
                    bases.setdefault(node.name, _base_names(node))

        def is_roundtable(name: str,
                          seen: Optional[set] = None) -> bool:
            if name in _ROOT_FAMILY:
                return True
            seen = seen or set()
            if name in seen or name not in bases:
                return False
            seen.add(name)
            return any(is_roundtable(b, seen) for b in bases[name])

        errors_rel = index.find_file("core/errors.py")
        table = (_table_keys(index, errors_rel)
                 if errors_rel is not None else set())

        out: list[Finding] = []
        reported: set[tuple[str, str]] = set()
        for rel in index.files():
            if "engine/" not in rel:
                continue
            for node in ast.walk(index.tree(rel)):
                if not isinstance(node, ast.Raise) or node.exc is None:
                    continue
                exc = node.exc
                name = None
                if isinstance(exc, ast.Call) and isinstance(exc.func,
                                                            ast.Name):
                    name = exc.func.id
                elif isinstance(exc, ast.Name):
                    name = exc.id
                if name is None or name not in bases:
                    continue    # stdlib / out-of-tree: sniffing's job
                if is_roundtable(name) or name in table:
                    continue
                if (rel, name) in reported:
                    continue
                reported.add((rel, name))
                out.append(self.finding(
                    rel, node.lineno,
                    f"engine code raises in-tree exception {name!r} "
                    "which neither descends from RoundtableError nor "
                    "appears in core/errors.py ERROR_KIND_TABLE — an "
                    "unregistered class takes the wrong recovery "
                    "ladder (the PR-12 device_lost ordering bug "
                    "class); register it with its actionable kind"))
        return out
