"""RT-MARKER-REG — every pytest.mark.<x> used under tests/ is
registered in pyproject.toml.

The conftest guards (scheduler / spec_decode / lora / ... markers) are
how this repo fails LOUD when a subsystem silently serves its
fallback; an unregistered marker is exactly the silent failure mode —
pytest treats it as an unknown no-op mark, the guard never arms, and
the test "passes" while covering nothing.
"""

from __future__ import annotations

import ast
import re

from ..astlint import Finding, ProjectIndex, Rule

# pytest's own marks plus the plugin marks this tree may legitimately
# carry without a [tool.pytest.ini_options] registration.
_BUILTIN = frozenset({
    "parametrize", "skip", "skipif", "xfail", "usefixtures",
    "filterwarnings", "tryfirst", "trylast", "timeout",
})

_MARKERS_BLOCK = re.compile(
    r"markers\s*=\s*\[(?P<body>.*?)\]", re.DOTALL)
_MARKER_NAME = re.compile(r"[\"']\s*([A-Za-z_][A-Za-z0-9_]*)\s*[:(\"']")


def registered_markers(pyproject_text: str) -> set[str]:
    m = _MARKERS_BLOCK.search(pyproject_text)
    if not m:
        return set()
    return set(_MARKER_NAME.findall(m.group("body")))


class MarkerRegRule(Rule):
    id = "RT-MARKER-REG"
    severity = "error"
    description = ("pytest.mark used in tests/ without a pyproject "
                   "markers registration — the mark (and its conftest "
                   "guard) is a silent no-op")

    def run(self, index: ProjectIndex) -> list[Finding]:
        registered = registered_markers(index.text("pyproject.toml"))
        test_files = [p for p in index.files()
                      if p.split("/")[0] == "tests"
                      or p.startswith("test_")]
        out: list[Finding] = []
        reported: set[tuple[str, str]] = set()
        for rel in test_files:
            for node in ast.walk(index.tree(rel)):
                if not (isinstance(node, ast.Attribute)
                        and isinstance(node.value, ast.Attribute)
                        and node.value.attr == "mark"
                        and isinstance(node.value.value, ast.Name)
                        and node.value.value.id == "pytest"):
                    continue
                name = node.attr
                if name in _BUILTIN or name in registered:
                    continue
                if (rel, name) in reported:
                    continue
                reported.add((rel, name))
                out.append(self.finding(
                    rel, node.lineno,
                    f"pytest.mark.{name} is not registered under "
                    "[tool.pytest.ini_options] markers in "
                    "pyproject.toml — pytest treats it as an unknown "
                    "no-op mark and any conftest guard keyed on it "
                    "never arms"))
        return out
