"""AST rule engine — the static half of `roundtable lint` (ISSUE 15).

PRs 4-13 accumulated serving invariants (shapes-are-config-only,
per-entity gauges removed at retire, lock-held counter bumps, error
kinds classified, donation never read-after-dispatch) that were only
ever enforced DYNAMICALLY: runtime sentinels and conftest guards that
fire late and only on exercised paths. This module makes them checkable
at import time, on CPU, with zero devices: a file-walking visitor
framework with file/line findings, machine-readable rule ids, and an
explicit allowlist whose every entry carries a written reason.

Architecture:

- `ProjectIndex` walks a root, parses every .py into an AST once, and
  hands rules cheap access to trees, sources and sibling text files
  (README/pyproject) — rules never re-read the disk.
- `Rule` subclasses (analysis/rules/*.py) each encode ONE lesson the
  repo already paid for, returning `Finding`s with a stable id.
- `Allowlist` (analysis/allowlist.toml) suppresses findings one
  written-reason entry at a time; an entry with no reason is a lint
  CONFIG error, and an entry matching nothing is reported stale
  (`RT-ALLOWLIST-STALE`) so dead suppressions can't accumulate.

The engine is root-relative on purpose: the fixture corpus under
tests/fixtures/analysis/ runs each rule over a mini-root proving it
catches its seeded violation and passes its clean twin.
"""

from __future__ import annotations

import ast
import fnmatch
import os
from dataclasses import dataclass, field
from typing import Any, Iterable, Optional

SEVERITIES = ("error", "warning")


@dataclass
class Finding:
    """One rule violation at a file/line — the machine-readable unit
    the CLI renders, --json emits, and the allowlist matches on."""

    rule: str
    path: str            # root-relative, "/"-separated
    line: int
    message: str
    severity: str = "error"
    allowed: bool = False
    allow_reason: Optional[str] = None

    def to_dict(self) -> dict[str, Any]:
        d = {"rule": self.rule, "path": self.path, "line": self.line,
             "severity": self.severity, "message": self.message}
        if self.allowed:
            d["allowed"] = True
            d["allow_reason"] = self.allow_reason
        return d

    def render(self) -> str:
        mark = " (allowlisted)" if self.allowed else ""
        return (f"{self.path}:{self.line}: {self.rule} "
                f"[{self.severity}]{mark} {self.message}")


class LintConfigError(RuntimeError):
    """The lint CONFIGURATION is broken (malformed allowlist, entry
    without a reason) — distinct from findings: a broken config must
    fail the run loudly, never silently suppress everything."""


# ---------------------------------------------------------------------------
# project index
# ---------------------------------------------------------------------------

_SKIP_DIRS = {"__pycache__", ".git", ".pytest_xla_cache", "node_modules",
              ".venv", "venv"}


class ProjectIndex:
    """Parsed view of a source root.

    On the real repo the scan is the package + tests (bench scripts and
    build artifacts are out of scope); a fixture mini-root without the
    package directory scans every .py under it."""

    def __init__(self, root: str):
        self.root = os.path.abspath(root)
        self.trees: dict[str, ast.Module] = {}
        self.sources: dict[str, str] = {}
        self.parse_errors: dict[str, str] = {}
        self._parents: dict[str, dict[ast.AST, ast.AST]] = {}
        for rel in self._discover():
            full = os.path.join(self.root, rel)
            try:
                with open(full, "r", encoding="utf-8") as f:
                    src = f.read()
                self.sources[rel] = src
                self.trees[rel] = ast.parse(src, filename=rel)
            except (SyntaxError, UnicodeDecodeError, OSError) as e:
                self.parse_errors[rel] = str(e)

    def _discover(self) -> list[str]:
        pkg = os.path.join(self.root, "theroundtaible_tpu")
        repo_layout = os.path.isdir(pkg)
        roots = ([os.path.join(self.root, d)
                  for d in ("theroundtaible_tpu", "tests")
                  if os.path.isdir(os.path.join(self.root, d))]
                 if repo_layout else [self.root])
        out: list[str] = []
        for base in roots:
            for dirpath, dirnames, filenames in os.walk(base):
                dirnames[:] = [d for d in dirnames if d not in _SKIP_DIRS]
                if repo_layout:
                    # The seeded-violation corpus (tests/fixtures/...)
                    # is lint INPUT for the per-rule tests, not part of
                    # the tree: scanning it would make the live-tree
                    # clean run impossible by construction.
                    dirnames[:] = [d for d in dirnames
                                   if d != "fixtures"]
                for fn in sorted(filenames):
                    if fn.endswith(".py"):
                        rel = os.path.relpath(
                            os.path.join(dirpath, fn), self.root)
                        out.append(rel.replace(os.sep, "/"))
        return sorted(out)

    # --- access helpers rules share ---

    def files(self, prefix: str = "") -> list[str]:
        return [p for p in sorted(self.trees) if p.startswith(prefix)]

    def tree(self, rel: str) -> Optional[ast.Module]:
        return self.trees.get(rel)

    def text(self, *names: str) -> str:
        """Concatenated contents of sibling non-.py files at the root
        (README.md, pyproject.toml, ...) — empty when absent."""
        parts = []
        for name in names:
            full = os.path.join(self.root, name)
            if os.path.isfile(full):
                try:
                    with open(full, "r", encoding="utf-8") as f:
                        parts.append(f.read())
                except OSError:
                    pass
        return "\n".join(parts)

    def find_file(self, suffix: str) -> Optional[str]:
        """First indexed file whose path ends with `suffix` (resource
        lookups like core/errors.py that must also resolve inside
        fixture mini-roots)."""
        for rel in sorted(self.trees):
            if rel.endswith(suffix):
                return rel
        return None

    def parents(self, rel: str) -> dict[ast.AST, ast.AST]:
        """Child -> parent map for one file's tree (lazily built): the
        lexical-enclosure walks (with-blocks, enclosing defs) rules
        need and ast doesn't provide."""
        cached = self._parents.get(rel)
        if cached is not None:
            return cached
        parent: dict[ast.AST, ast.AST] = {}
        tree = self.trees[rel]
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                parent[child] = node
        self._parents[rel] = parent
        return parent

    def enclosing(self, rel: str, node: ast.AST,
                  kinds: tuple[type, ...]) -> list[ast.AST]:
        """All ancestors of `node` (innermost first) matching `kinds`."""
        parent = self.parents(rel)
        out = []
        cur = parent.get(node)
        while cur is not None:
            if isinstance(cur, kinds):
                out.append(cur)
            cur = parent.get(cur)
        return out


# ---------------------------------------------------------------------------
# rule base
# ---------------------------------------------------------------------------


class Rule:
    """One invariant. Subclasses set the class attrs and implement
    run(); findings carry the rule id so the allowlist and --json
    stay machine-readable."""

    id: str = "RT-UNSET"
    severity: str = "error"
    description: str = ""

    def run(self, index: ProjectIndex) -> list[Finding]:
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str) -> Finding:
        return Finding(rule=self.id, path=path, line=line,
                       message=message, severity=self.severity)


# --- shared AST helpers ---


def call_name(node: ast.Call) -> str:
    """Rightmost name of the callee: `telemetry.REGISTRY.set_gauge(...)`
    -> "set_gauge", `set_gauge(...)` -> "set_gauge"."""
    fn = node.func
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def dotted_name(node: ast.AST) -> str:
    """Full dotted rendering of a Name/Attribute chain ("" when the
    chain contains calls/subscripts)."""
    parts: list[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
        return ".".join(reversed(parts))
    return ""


def str_const(node: Optional[ast.AST]) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


# ---------------------------------------------------------------------------
# allowlist
# ---------------------------------------------------------------------------


@dataclass
class AllowEntry:
    rule: str
    reason: str
    path: str = "*"
    match: str = ""
    line: int = 0            # line in allowlist.toml (stale reporting)
    hits: int = 0

    def matches(self, f: Finding) -> bool:
        if self.rule != f.rule:
            return False
        if not fnmatch.fnmatch(f.path, self.path):
            return False
        return self.match in f.message


def _parse_allowlist_toml(text: str, source: str) -> list[AllowEntry]:
    """Minimal TOML-subset parser for the allowlist: `[[allow]]` array
    tables with single-line `key = "string"` pairs. Python 3.10 has no
    tomllib and the container must not grow a dependency; the subset is
    pinned by tests so drift fails loudly."""
    entries: list[AllowEntry] = []
    cur: Optional[dict[str, Any]] = None

    def close(d: Optional[dict]) -> None:
        if d is None:
            return
        if not d.get("rule"):
            raise LintConfigError(
                f"{source}:{d['_line']}: allowlist entry missing "
                "required key 'rule'")
        if not str(d.get("reason", "")).strip():
            raise LintConfigError(
                f"{source}:{d['_line']}: allowlist entry for "
                f"{d['rule']!r} carries no reason — every suppression "
                "must say WHY (the allowlist policy, ISSUE 15)")
        entries.append(AllowEntry(
            rule=d["rule"], reason=d["reason"].strip(),
            path=d.get("path", "*"), match=d.get("match", ""),
            line=d["_line"]))

    for i, raw in enumerate(text.splitlines(), start=1):
        line = raw.strip()
        if not line or line.startswith("#"):
            continue
        if line == "[[allow]]":
            close(cur)
            cur = {"_line": i}
            continue
        if line.startswith("["):
            raise LintConfigError(
                f"{source}:{i}: unsupported table {line!r} — the "
                "allowlist holds only [[allow]] entries")
        if cur is None:
            raise LintConfigError(
                f"{source}:{i}: key/value outside an [[allow]] entry")
        if "=" not in line:
            raise LintConfigError(f"{source}:{i}: expected key = "
                                  f"\"value\", got {line!r}")
        key, _, val = line.partition("=")
        key = key.strip()
        val = val.strip()
        if not (len(val) >= 2 and val[0] == '"' and val[-1] == '"'):
            raise LintConfigError(
                f"{source}:{i}: value for {key!r} must be a one-line "
                "double-quoted string")
        cur[key] = val[1:-1].replace('\\"', '"')
    close(cur)
    return entries


class Allowlist:
    """Written-reason suppressions. apply() marks matching findings
    allowed (first matching entry wins) and appends one STALE finding
    per entry that matched nothing this run."""

    def __init__(self, entries: list[AllowEntry], source: str = ""):
        self.entries = entries
        self.source = source

    @classmethod
    def load(cls, path: Optional[str]) -> "Allowlist":
        if path is None or not os.path.isfile(path):
            return cls([], source=path or "")
        with open(path, "r", encoding="utf-8") as f:
            text = f.read()
        return cls(_parse_allowlist_toml(text, os.path.basename(path)),
                   source=path)

    def apply(self, findings: list[Finding],
              active_rules: Optional[set[str]] = None) -> list[Finding]:
        """`active_rules` is the set of rule ids that actually RAN this
        invocation (None = all): an entry whose rule was filtered out
        by --rules (or whose jaxpr half didn't run) legitimately
        matches nothing and must not be reported stale."""
        for e in self.entries:
            e.hits = 0
        for f in findings:
            for e in self.entries:
                if e.matches(f):
                    f.allowed = True
                    f.allow_reason = e.reason
                    e.hits += 1
                    break
        out = list(findings)
        for e in self.entries:
            if e.hits == 0 and (active_rules is None
                                or e.rule in active_rules):
                out.append(Finding(
                    rule="RT-ALLOWLIST-STALE",
                    path=os.path.basename(self.source or
                                          "allowlist.toml"),
                    line=e.line, severity="error",
                    message=(f"allowlist entry for {e.rule} "
                             f"(path={e.path!r}, match={e.match!r}) "
                             "matched no finding — the violation it "
                             "suppressed is gone; delete the entry")))
        return out


# ---------------------------------------------------------------------------
# driver
# ---------------------------------------------------------------------------


def default_allowlist_path() -> str:
    return os.path.join(os.path.dirname(__file__), "allowlist.toml")


def run_rules(root: str, rules: Iterable[Rule],
              allowlist: Optional[Allowlist] = None,
              index: Optional[ProjectIndex] = None,
              extra_findings: Optional[list[Finding]] = None,
              extra_active: Optional[set[str]] = None) -> list[Finding]:
    """Run `rules` over `root`; returns ALL findings (allowlisted ones
    marked, stale-allowlist findings appended), sorted by path/line.
    Unparseable files are findings too — a syntax error must not make
    its invariants unenforceable silently.

    `extra_findings` (the jaxpr audit's output) joins the set BEFORE
    the allowlist applies, so both halves suppress through the one
    mechanism; `extra_active` names their rule ids for staleness
    accounting even when the extra pass found nothing."""
    rules = list(rules)
    index = index or ProjectIndex(root)
    findings: list[Finding] = []
    for rel, err in sorted(index.parse_errors.items()):
        findings.append(Finding(
            rule="RT-PARSE", path=rel, line=0, severity="error",
            message=f"file failed to parse — unlintable: {err}"))
    for rule in rules:
        findings.extend(rule.run(index))
    findings.extend(extra_findings or [])
    if allowlist is not None:
        active = {r.id for r in rules} | {"RT-PARSE"}
        active |= extra_active or set()
        active |= {f.rule for f in findings}
        findings = allowlist.apply(findings, active_rules=active)
    findings.sort(key=lambda f: (f.path, f.line, f.rule))
    return findings


def unallowlisted(findings: list[Finding]) -> list[Finding]:
    return [f for f in findings if not f.allowed]
