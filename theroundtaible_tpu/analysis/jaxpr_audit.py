"""Device-free jaxpr audit of the serving programs (ISSUE 15, half 2).

The AST rules see source; this half sees the PROGRAMS. Every serving
program family (prefill / decode / ragged / spec-verify / propose /
LoRA-setter) registers a provider via `analysis_register()` at its
defining seam (engine.py / paged_forward.py / spec_decode.py /
lora.py); `audit_engine(engine)` abstractly traces each registered
program on CPU with `jax.make_jaxpr` — tracing never dispatches, so
the whole audit runs with zero devices — across the SAME shape grid
warmup compiles, and statically asserts three invariants:

- **RT-JAXPR-DONATION** — inside every traced composition, a pjit
  eqn's donated invars are dead afterwards: not consumed by any later
  eqn and not returned as outputs. A donated buffer read after the
  dispatch is the deleted-array crash the PR-1 ladder can only clean
  up after; here it is a parse-time finding.
- **RT-JAXPR-CALLBACK** — no `pure_callback` / `io_callback` /
  `debug_callback` primitive (recursively, through pjit/while/cond
  sub-jaxprs) in a decode / ragged / verify-phase program: a host
  callback in the hot loop is a per-token host sync.
- **RT-JAXPR-VARIANTS** — the variant grid replays runtime drift
  (occupancies, compositions) through the REAL static-argument
  computation the serving path uses; every declared variant label must
  map to EXACTLY ONE distinct jaxpr. A static-arg leak (a value
  derived from runtime state reaching a static parameter) shows up as
  extra distinct jaxprs under one label — RECOMPILE_STRICT proven
  before a device exists.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Any, Callable, Optional

from .astlint import Finding

# Program phases whose jaxprs must be host-callback-free: these run
# per decode tick, so one callback is one host sync per token.
HOT_PHASES = frozenset({"decode", "ragged", "verify"})

_CALLBACK_PRIMS = ("pure_callback", "io_callback", "debug_callback")

# The audit's finding ids — the CLI passes these as the allowlist's
# active set when --jaxpr runs, so a jaxpr suppression can go stale.
JAXPR_RULE_IDS = frozenset({"RT-JAXPR-DONATION", "RT-JAXPR-CALLBACK",
                            "RT-JAXPR-VARIANTS", "RT-JAXPR-TRACE"})


@dataclass
class Variant:
    """One grid point: a runtime-ish situation (occupancy, composition)
    mapped onto the label of the compiled program that SHOULD serve it.
    `thunk()` returns the traced ClosedJaxpr for that situation."""

    label: str
    thunk: Callable[[], Any]
    situation: str = ""      # human description ("occupancy 3", ...)


@dataclass
class ProgramSpec:
    """One serving program family across its warmed-variant grid."""

    name: str                # "decode[paged]", "ragged", ...
    phase: str               # prefill|decode|ragged|verify|propose|setter
    variants: list[Variant] = field(default_factory=list)


# ---------------------------------------------------------------------------
# provider registry — the analysis_register() hook
# ---------------------------------------------------------------------------

_PROVIDERS: dict[str, Callable[[Any], list[ProgramSpec]]] = {}


def analysis_register(name: str):
    """Register a serving-program provider at its defining seam.

    `fn(engine) -> list[ProgramSpec]` builds trace thunks from a LIVE
    engine's own state (params, pools, shape grids) exactly the way
    the serving path builds dispatch arguments — returning [] when the
    engine does not serve that family. Decorating at module scope
    keeps registration import-time cheap; nothing traces until
    audit_engine() runs."""

    def deco(fn: Callable[[Any], list[ProgramSpec]]):
        _PROVIDERS[name] = fn
        return fn

    return deco


def registered_providers() -> dict[str, Callable]:
    return dict(_PROVIDERS)


# The modules that register providers at import time. Several are
# imported LAZILY by the serving path (paged_forward inside the jitted
# closures, lora on first store construction), so an audit run must
# pull them in itself — a provider that silently never registered
# would silently audit nothing.
_PROVIDER_MODULES = ("engine.engine", "engine.paged_forward",
                     "engine.spec_decode", "engine.lora")


def _ensure_provider_modules() -> None:
    import importlib

    pkg = __name__.rsplit(".", 2)[0]    # theroundtaible_tpu
    for mod in _PROVIDER_MODULES:
        importlib.import_module(f"{pkg}.{mod}")


def collect_programs(engine) -> list[ProgramSpec]:
    _ensure_provider_modules()
    specs: list[ProgramSpec] = []
    for name in sorted(_PROVIDERS):
        specs.extend(_PROVIDERS[name](engine) or [])
    return specs


# ---------------------------------------------------------------------------
# jaxpr checks
# ---------------------------------------------------------------------------


def _iter_sub_jaxprs(jaxpr):
    """jaxpr plus every nested jaxpr reachable through eqn params
    (pjit bodies, while/cond branches, custom calls)."""
    import jax.core as jcore  # noqa: F401 — jax import kept local

    stack = [jaxpr]
    while stack:
        j = stack.pop()
        yield j
        for eqn in j.eqns:
            for v in eqn.params.values():
                for sub in _as_jaxprs(v):
                    stack.append(sub)


def _as_jaxprs(value):
    out = []
    vals = value if isinstance(value, (list, tuple)) else [value]
    for v in vals:
        inner = getattr(v, "jaxpr", None)
        if inner is not None and hasattr(inner, "eqns"):
            out.append(inner)          # ClosedJaxpr
        elif hasattr(v, "eqns"):
            out.append(v)              # raw Jaxpr
    return out


def find_callbacks(closed_jaxpr) -> list[str]:
    """Callback primitive names present anywhere in the program."""
    found = []
    for j in _iter_sub_jaxprs(closed_jaxpr.jaxpr):
        for eqn in j.eqns:
            name = eqn.primitive.name
            if any(name.startswith(p) for p in _CALLBACK_PRIMS):
                found.append(name)
    return sorted(set(found))


def donation_violations(closed_jaxpr) -> list[str]:
    """For each pjit eqn with donated invars in the TOP-LEVEL
    composition, the donated vars must be dead afterwards: consumed by
    no later eqn and absent from the jaxpr's outputs. Returns
    human-readable violation strings."""
    jaxpr = closed_jaxpr.jaxpr
    out: list[str] = []
    outvars = {id(v) for v in jaxpr.outvars if hasattr(v, "aval")}
    for i, eqn in enumerate(jaxpr.eqns):
        donated = eqn.params.get("donated_invars")
        if not donated or not any(donated):
            continue
        dead = [v for v, d in zip(eqn.invars, donated)
                if d and hasattr(v, "aval")]
        for v in dead:
            later = [j for j in range(i + 1, len(jaxpr.eqns))
                     if any(u is v for u in jaxpr.eqns[j].invars
                            if hasattr(u, "aval"))]
            if later:
                out.append(
                    f"donated input {v} of eqn #{i} "
                    f"({eqn.params.get('name', eqn.primitive.name)}) is "
                    f"read again by eqn #{later[0]} "
                    f"({jaxpr.eqns[later[0]].primitive.name}) — "
                    "use-after-donation")
            if id(v) in outvars:
                out.append(
                    f"donated input {v} of eqn #{i} "
                    f"({eqn.params.get('name', eqn.primitive.name)}) is "
                    "returned by the composition — the caller receives "
                    "a deleted buffer")
    return out


def jaxpr_fingerprint(closed_jaxpr) -> str:
    """Stable identity of a traced program: avals + the full pretty-
    printed jaxpr (shapes, primitives, static literals). Two traces
    that would compile the same executable fingerprint identically;
    a static-arg change shows up as a new fingerprint."""
    h = hashlib.sha1()
    for a in closed_jaxpr.in_avals:
        h.update(str(a).encode())
    h.update(b"|")
    h.update(str(closed_jaxpr.jaxpr).encode())
    return h.hexdigest()


# ---------------------------------------------------------------------------
# audit driver
# ---------------------------------------------------------------------------


def audit_programs(specs: list[ProgramSpec]) -> list[Finding]:
    """Trace every variant of every spec and run the three checks.
    Findings reuse the astlint Finding type with a pseudo-path
    `<jaxpr:program>` so the CLI/allowlist treat both halves
    uniformly. A variant whose trace itself fails is a finding
    (RT-JAXPR-TRACE) — an untraceable serving program is unauditable,
    which must be loud, not skipped."""
    findings: list[Finding] = []
    for spec in specs:
        path = f"<jaxpr:{spec.name}>"
        by_label: dict[str, dict[str, str]] = {}
        for var in spec.variants:
            try:
                traced = var.thunk()
            except Exception as e:  # noqa: BLE001 — finding, not crash
                findings.append(Finding(
                    rule="RT-JAXPR-TRACE", path=path, line=0,
                    message=(f"variant {var.label!r} "
                             f"({var.situation}) failed to trace: "
                             f"{type(e).__name__}: {str(e)[:300]}")))
                continue
            fp = jaxpr_fingerprint(traced)
            by_label.setdefault(var.label, {})[fp] = var.situation
            if spec.phase in HOT_PHASES:
                cbs = find_callbacks(traced)
                if cbs:
                    findings.append(Finding(
                        rule="RT-JAXPR-CALLBACK", path=path, line=0,
                        message=(f"{spec.phase} program variant "
                                 f"{var.label!r} contains host "
                                 f"callback primitive(s) "
                                 f"{', '.join(cbs)} — a host sync "
                                 "per hot-loop dispatch")))
            for viol in donation_violations(traced):
                findings.append(Finding(
                    rule="RT-JAXPR-DONATION", path=path, line=0,
                    message=f"variant {var.label!r}: {viol}"))
        for label, fps in sorted(by_label.items()):
            if len(fps) > 1:
                sits = "; ".join(sorted(fps.values()))
                findings.append(Finding(
                    rule="RT-JAXPR-VARIANTS", path=path, line=0,
                    message=(f"declared variant {label!r} traced to "
                             f"{len(fps)} DISTINCT jaxprs across the "
                             f"grid ({sits}) — a static argument is "
                             "leaking runtime state: one compile per "
                             "runtime value in steady state "
                             "(RECOMPILE_STRICT violation, proven "
                             "device-free)")))
    return findings


def audit_engine(engine) -> list[Finding]:
    """Run every registered provider against a live (CPU) engine and
    audit the produced program grid."""
    return audit_programs(collect_programs(engine))


def audited_program_names(engine) -> list[str]:
    return sorted(s.name for s in collect_programs(engine))
