"""Static-analysis subsystem — `roundtable lint` (ISSUE 15).

Two halves behind one driver:

- AST rule engine (`astlint` + `rules/`): file/line findings with
  machine-readable ids, encoding the serving invariants PRs 4-13
  learned dynamically; suppressions live in `allowlist.toml`, every
  entry carrying a written reason.
- jaxpr auditor (`jaxpr_audit`): abstract CPU traces of every
  registered serving program, asserting donation safety, callback-free
  hot loops, and the warmed-variant count across the shape grid.

Lazy exports (PEP 562): the engine modules import
`jaxpr_audit.analysis_register` at import time, and that must not drag
the AST machinery (or anything heavier) into the serving path.
"""

from __future__ import annotations

_EXPORTS = {
    "Allowlist": ("astlint", "Allowlist"),
    "Finding": ("astlint", "Finding"),
    "LintConfigError": ("astlint", "LintConfigError"),
    "ProjectIndex": ("astlint", "ProjectIndex"),
    "Rule": ("astlint", "Rule"),
    "default_allowlist_path": ("astlint", "default_allowlist_path"),
    "run_rules": ("astlint", "run_rules"),
    "unallowlisted": ("astlint", "unallowlisted"),
    "ALL_RULES": ("rules", "ALL_RULES"),
    "get_rules": ("rules", "get_rules"),
    "analysis_register": ("jaxpr_audit", "analysis_register"),
    "audit_engine": ("jaxpr_audit", "audit_engine"),
    "audit_programs": ("jaxpr_audit", "audit_programs"),
}


def __getattr__(name: str):
    try:
        mod_name, attr = _EXPORTS[name]
    except KeyError:
        raise AttributeError(name) from None
    import importlib

    mod = importlib.import_module(f".{mod_name}", __name__)
    return getattr(mod, attr)


def run_lint(root: str, rule_ids=None, allowlist_path=None,
             extra_findings=None, extra_active=None):
    """One-call lint driver: rules + allowlist over `root`. Returns
    the full finding list (allowlisted findings marked). The jaxpr
    audit's findings ride in via `extra_findings`/`extra_active` so
    both halves suppress (and go stale) through the one allowlist."""
    from .astlint import Allowlist, default_allowlist_path, run_rules
    from .rules import get_rules

    if allowlist_path is None:
        allowlist_path = default_allowlist_path()
    return run_rules(root, get_rules(rule_ids),
                     allowlist=Allowlist.load(allowlist_path),
                     extra_findings=extra_findings,
                     extra_active=extra_active)


__all__ = sorted(_EXPORTS) + ["run_lint"]
