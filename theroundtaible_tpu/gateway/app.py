"""The Gateway: asyncio HTTP/SSE front door over one SessionScheduler.

Endpoints:

| route                     | method | behavior                        |
|---------------------------|--------|---------------------------------|
| /v1/chat/completions      | POST   | OpenAI-compatible; stream=true → SSE chunks + [DONE] |
| /v1/discussions           | POST   | native multi-knight round → SSE token events |
| /v1/streams/<id>          | GET    | reconnect a stream (Last-Event-ID watermark) |
| /v1/admin/roll            | POST   | rolling restart (router fleets) |
| /healthz                  | GET    | liveness + drain state          |
| /metrics                  | GET    | Prometheus exposition snapshot  |

Every admitted stream: one fsynced intent record (gateway/resume.py),
one scheduler submit with `on_commit` bridged onto the asyncio loop,
one `roundtable_gateway_inflight_streams{request=...}` gauge removed
at completion (the PR-6 gauge-leak rule). Generation is GREEDY by
default — that is what makes post-crash re-generation byte-identical
and the resume protocol exact.

Deadline propagation: the client deadline (X-Roundtable-Deadline-S
header or body `deadline_s`, default ROUNDTABLE_GATEWAY_DEFAULT_
DEADLINE_S) becomes a `deadlines.Budget` root handed to submit_async —
an already-spent budget fails fast there with DeadlineExpired (its own
classified kind) before any prefill dispatch.
"""

from __future__ import annotations

import asyncio
import json
import os
import threading
import time
import uuid
from typing import Any, Optional

from ..engine import deadlines
from ..engine.sampling import SamplingParams
from ..engine.scheduler import DeadlineExpired, SchedulerClosed, \
    SchedulerRefused
from ..utils import telemetry, tracing
from .admission import AdmissionController, Decision, _env_float, \
    _env_int, make_budget
from .http import HttpError, Request, SseWriter, read_request, \
    send_json, send_text
from .resume import StreamIntentJournal, committed_rows
from .streams import StreamState, format_event_id, parse_event_id

_DONE_STREAM_CAP = 256   # completed streams kept for reconnects

# Failure kinds where a reconnect should FAIL OVER instead of replaying
# the failure: the stream died with its replica, not with its request —
# under a router, restore it (journal leg 2 / greedy-regen leg 3) on a
# surviving replica rather than handing the corpse back to the client.
_FAILOVER_KINDS = {"device_lost", "engine_dead", "restarting",
                   "data_loss"}


class _Shed(Exception):
    def __init__(self, decision: Decision, trace_id: str = ""):
        super().__init__(decision.reason)
        self.decision = decision
        # Echoed on the shed payload (ISSUE 20): a shed request still
        # has a trace — tail retention keeps it, and the client can
        # quote the id.
        self.trace_id = trace_id


class Gateway:
    """One gateway over one scheduler — or, with `router=`, over a
    SessionRouter's replica fleet (the scheduler argument stays the
    primary: its tokenizer and shared journal serve every replica)."""

    def __init__(self, scheduler, *, host: Optional[str] = None,
                 port: Optional[int] = None,
                 intent_dir: Optional[str] = None,
                 admission: Optional[AdmissionController] = None,
                 router=None):
        self.sched = scheduler
        self.router = router
        self.host = host or os.environ.get(
            "ROUNDTABLE_GATEWAY_HOST", "127.0.0.1")
        self.port = port if port is not None \
            else _env_int("ROUNDTABLE_GATEWAY_PORT", 8080)
        self.admission = admission or AdmissionController(
            scheduler,
            source=router.signals() if router is not None else None)
        self.default_deadline_s = _env_float(
            "ROUNDTABLE_GATEWAY_DEFAULT_DEADLINE_S", 120.0)
        self.sse_buffer = _env_int("ROUNDTABLE_GATEWAY_SSE_BUFFER", 512)
        self.keepalive_s = _env_float(
            "ROUNDTABLE_GATEWAY_KEEPALIVE_S", 15.0)
        # Abandonment linger (ISSUE 19): a stream whose LAST consumer
        # disconnected gets this long for a reconnect before its
        # scheduler round is abandoned (adapters/KV/gauges released).
        # Long enough for the Last-Event-ID resume ladder, short
        # enough that walked-away clients stop burning capacity.
        self.abandon_s = _env_float(
            "ROUNDTABLE_GATEWAY_ABANDON_S", 30.0)
        self.streams: dict[str, StreamState] = {}
        self.resumed_streams = 0
        # Stream-intent journal: rides in the session journal's
        # directory when one is attached (one durable root per pod).
        root = intent_dir
        if root is None and scheduler.journal is not None:
            root = str(scheduler.journal.root)
        self.intents = StreamIntentJournal(root) if root else None
        self._intent_cache: dict[str, dict] = (
            self.intents.load() if self.intents else {})
        # Compaction threshold for the intent journal + cache: above
        # this many records, intents whose turn already committed in
        # the session journal are compacted away (the newest half of
        # the cap stays for leg-2 reconnects). Bounds a long-lived
        # gateway's disk and memory (review fix).
        self.intent_cap = 2 * _DONE_STREAM_CAP
        self._server: Optional[asyncio.AbstractServer] = None
        self._loop: Optional[asyncio.AbstractEventLoop] = None
        self._stop_event: Optional[asyncio.Event] = None
        self._thread: Optional[threading.Thread] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    @classmethod
    def boot(cls, scheduler, *, resume_dir: Optional[str] = None,
             **kw) -> "Gateway":
        """Build a gateway, optionally restoring committed sessions
        first: `resume_dir` replays the session journal through the
        library seam (engine/recovery.py) so every session's KV sits
        at its last committed turn before the first reconnect."""
        if resume_dir is not None:
            from ..engine.recovery import resume_from_journal
            resume_from_journal(resume_dir, scheduler=scheduler)
            kw.setdefault("intent_dir", resume_dir)
        return cls(scheduler, **kw)

    async def start(self) -> None:
        self._loop = asyncio.get_running_loop()
        self._stop_event = asyncio.Event()
        self._server = await asyncio.start_server(
            self._handle_conn, self.host, self.port)
        self.port = self._server.sockets[0].getsockname()[1]
        telemetry.recorder().record("gateway_start", host=self.host,
                                    port=self.port)

    async def serve_until_stopped(self) -> None:
        await self.start()
        async with self._server:
            await self._stop_event.wait()

    def run(self) -> None:
        """Blocking entry (the CLI): serve until SIGINT."""
        try:
            asyncio.run(self.serve_until_stopped())
        except KeyboardInterrupt:
            pass

    def start_in_thread(self, timeout_s: float = 10.0) -> int:
        """Background entry (tests / embedding): returns the bound
        port once the socket is listening."""
        ready = threading.Event()

        async def _main():
            await self.start()
            ready.set()
            async with self._server:
                await self._stop_event.wait()

        self._thread = threading.Thread(
            target=lambda: asyncio.run(_main()),
            name="gateway", daemon=True)
        self._thread.start()
        if not ready.wait(timeout_s):
            raise RuntimeError("gateway did not start listening")
        return self.port

    def stop(self, timeout_s: float = 10.0) -> None:
        if self._loop is not None and self._stop_event is not None:
            try:
                self._loop.call_soon_threadsafe(self._stop_event.set)
            except RuntimeError:
                pass  # loop already closed
        if self._thread is not None:
            self._thread.join(timeout_s)
        # Close must not leak per-stream gauges (RT-GAUGE-LEAK): any
        # stream still marked inflight drops its series here.
        for sid, st in list(self.streams.items()):
            if not st.done:
                telemetry.REGISTRY.remove_gauge(
                    "roundtable_gateway_inflight_streams",
                    **self._stream_labels(st))
                if st.trace is not None:
                    # A leg cut off by shutdown is an anomaly worth
                    # keeping: flag → tail retention.
                    st.trace.flag("interrupted")
                    st.trace.finish("interrupted")

    # ------------------------------------------------------------------
    # observability
    # ------------------------------------------------------------------

    def describe(self) -> dict[str, Any]:
        """Keys ⊆ SURFACE_BINDINGS["gateway"] (drift-tested like the
        scheduler's describe)."""
        adm = self.admission
        out = {
            "admitted": adm.admitted,
            "shed": adm.shed,
            "queued": adm.queued,
            "expired": adm.expired,
            "inflight": self._inflight(),
            "draining": self._draining(),
            "resumed_streams": self.resumed_streams,
            "dropped_events": int(telemetry.REGISTRY.counter_total(
                "roundtable_gateway_dropped_events_total")),
            "sessions": len(self.streams),
            "host": self.host,
            "port": self.port,
            "slo": adm.slo.describe(),
            "tracing": {
                "retained": tracing.store().retained,
                "sample_rate": tracing.sample_rate(),
            },
        }
        if self.router is not None:
            out["replicas"] = self.router.describe()
        return out

    def _inflight(self) -> int:
        return sum(1 for s in self.streams.values() if not s.done)

    def _draining(self) -> bool:
        """Fleet-aware drain state: under a router, the front door only
        reports draining when NO replica is open (one rolling replica
        keeps /healthz green and admission flowing to its peers)."""
        if self.router is not None:
            return bool(self.admission.source.drain_state())
        return bool(deadlines.DRAINING
                    or self.sched.paused is not None)

    def _sched_for(self, session: str, adapters: Optional[list] = None
                   ) -> tuple[Any, Optional[str]]:
        """(scheduler, replica-name) that serves this session: the
        router's affinity/load placement, or the one scheduler with no
        replica label in the N=1 case."""
        if self.router is not None:
            rep = self.router.replica_for(session, adapters)
            return rep.scheduler, rep.name
        return self.sched, None

    def _stream_labels(self, state: StreamState) -> dict[str, str]:
        labels = {"request": state.stream_id}
        replica = getattr(state, "replica", None)
        if replica is not None:
            labels["replica"] = replica
        return labels

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------

    async def _handle_conn(self, reader: asyncio.StreamReader,
                           writer: asyncio.StreamWriter) -> None:
        try:
            req = await read_request(reader)
            if req is not None:
                await self._route(req, writer)
        except _Shed as s:
            d = s.decision
            payload = {
                "error": f"request shed: {d.reason}",
                "reason": d.reason,
            }
            headers = {"Retry-After": f"{max(int(d.retry_after_s), 1)}"}
            if s.trace_id:
                payload["trace"] = s.trace_id
                headers["Traceparent"] = tracing.format_traceparent(
                    s.trace_id)
            await send_json(writer, d.status, payload, headers)
        except HttpError as e:
            try:
                await self._send_error(writer, e.status, str(e),
                                       e.reason,
                                       getattr(e, "trace_id", ""))
            except (ConnectionError, RuntimeError):
                pass
        except (ConnectionError, asyncio.IncompleteReadError):
            pass  # client went away mid-write — its stream state stays
        except Exception as e:  # noqa: BLE001 — one conn must not kill the server
            try:
                await self._send_error(writer, 500, str(e)[:200],
                                       "internal")
            except Exception:  # noqa: BLE001
                pass
        finally:
            try:
                writer.close()
                await writer.wait_closed()
            except Exception:  # noqa: BLE001
                pass

    async def _send_error(self, writer: asyncio.StreamWriter,
                          status: int, error: str, kind: str,
                          trace_id: str = "") -> None:
        """Error the connection WITHOUT corrupting the protocol: once
        an SSE head has been written (the pump path failed late), a
        fresh HTTP status line would land mid-stream as malformed
        bytes — emit a terminal `failed` SSE event instead. The trace
        id (when the failure happened after one existed) rides every
        error payload so a failure always names its trace."""
        if getattr(writer, "_sse_opened", False):
            payload = {"type": "failed", "error": error, "kind": kind}
            if trace_id:
                payload["trace"] = trace_id
            await SseWriter(writer).event(payload)
        else:
            payload = {"error": error, "reason": kind}
            headers = None
            if trace_id:
                payload["trace"] = trace_id
                headers = {"Traceparent": tracing.format_traceparent(
                    trace_id)}
            await send_json(writer, status, payload, headers)

    async def _route(self, req: Request,
                     writer: asyncio.StreamWriter) -> None:
        path = req.path.rstrip("/") or "/"
        if path == "/healthz" and req.method == "GET":
            health = {
                "ok": True,
                "draining": self._draining(),
                "paused": self.sched.paused,
                "inflight": self._inflight(),
            }
            if self.router is not None:
                health["replicas"] = {
                    name: {"dead": d["dead"], "paused": d["paused"]}
                    for name, d in
                    self.router.describe()["replicas"].items()}
            await send_json(writer, 200, health)
            return
        if path == "/metrics" and req.method == "GET":
            await send_text(writer, 200,
                            telemetry.REGISTRY.prometheus_text(),
                            "text/plain; version=0.0.4")
            return
        if path == "/v1/chat/completions" and req.method == "POST":
            await self._chat_completions(req, writer)
            return
        if path == "/v1/discussions" and req.method == "POST":
            await self._discussions(req, writer)
            return
        if path.startswith("/v1/streams/") and req.method == "GET":
            await self._reconnect(req, writer,
                                  path[len("/v1/streams/"):])
            return
        if path == "/v1/admin/roll" and req.method == "POST":
            await self._admin_roll(req, writer)
            return
        raise HttpError(404, f"no route for {req.method} {req.path}",
                        "not_found")

    async def _admin_roll(self, req: Request,
                          writer: asyncio.StreamWriter) -> None:
        """Rolling restart over the fleet (or one named replica) —
        runs off the event loop; in-flight streams keep pumping and
        any stream crossing the roll reconnects through the resume
        ladder."""
        if self.router is None:
            raise HttpError(400, "no router attached: single-engine "
                            "gateway cannot roll", "no_router")
        target = None
        if req.body:
            try:
                target = req.json().get("replica")
            except (ValueError, json.JSONDecodeError) as e:
                raise HttpError(400, f"bad JSON body: {e}", "bad_json")
        loop = asyncio.get_running_loop()
        reports = await loop.run_in_executor(
            None, lambda: self.router.roll(target))
        await send_json(writer, 200, {"rolled": reports})

    # ------------------------------------------------------------------
    # admission + submit (the shared front half of both POST routes)
    # ------------------------------------------------------------------

    def _client_deadline(self, req: Request, body: dict
                         ) -> Optional[float]:
        raw = req.header("x-roundtable-deadline-s")
        if raw is None:
            raw = body.get("deadline_s")
        if raw is None:
            return self.default_deadline_s or None
        try:
            return float(raw)
        except (TypeError, ValueError):
            raise HttpError(400, f"bad deadline: {raw!r}",
                            "bad_deadline")

    def _submit_stream(self, *, session: str,
                       turns: list[tuple[str, Any]], max_new: int,
                       deadline_s: Optional[float], priority: str,
                       adapters: Optional[list], kind: str,
                       temperature: float = 0.0,
                       record_intent: bool = True,
                       traceparent: Optional[str] = None) -> StreamState:
        # One trace per client request (ISSUE 20): join the client's
        # traceparent when one parses, mint a root otherwise. The
        # RequestTrace is the critical-path clock; its span (armed
        # telemetry) is the parent everything downstream hangs off.
        tp = tracing.parse_traceparent(traceparent)
        trace = tracing.RequestTrace(
            tp[0] if tp else None,
            parent_span_id=tp[1] if tp else "",
            kind="request", session=session, endpoint=kind,
            priority=priority, rows=len(turns))
        try:
            with telemetry.attached(trace.context()):
                dec = self.admission.decide(
                    rows=len(turns), inflight=self._inflight(),
                    deadline_s=deadline_s, priority=priority,
                    adapters=adapters)
            if not dec.admit:
                raise _Shed(dec)
            trace.stage("admission")
            stream_id = uuid.uuid4().hex[:16]
            trace.stream_id = stream_id
            if trace.span is not None:
                trace.span.set_attr("stream", stream_id)
            journal = self.sched.journal
            last = journal.last_turn(session) \
                if journal is not None else None
            turn = 0 if last is None else last + 1
            state = StreamState(stream_id, session,
                                [k for k, _p in turns], turn,
                                buffer_cap=self.sse_buffer)
            state.trace = trace
            if record_intent and self.intents is not None:
                rec = self.intents.record(
                    stream_id, session=session,
                    knights=[k for k, _p in turns],
                    prompts=[p for _k, p in turns], turn=turn,
                    max_new=max_new, deadline_s=deadline_s, kind=kind,
                    adapters=adapters, temperature=temperature,
                    trace=trace.trace_id)
                if rec is not None:
                    self._intent_cache[stream_id] = rec
            self._submit_state(state, turns, max_new=max_new,
                               deadline_s=deadline_s, adapters=adapters,
                               temperature=temperature)
            trace.stage("placement")
            trace.replica = getattr(state, "replica", None)
            self.admission.note_admitted(
                queued=dec.queued,
                replica=getattr(state, "replica", None))
            return state
        except _Shed as s:
            trace.flag("shed")
            trace.finish(f"shed:{s.decision.reason}",
                         tail_stage="admission")
            s.trace_id = trace.trace_id
            raise
        except HttpError as e:
            trace.flag("failed")
            trace.finish(f"error:{e.reason}", tail_stage="admission")
            e.trace_id = trace.trace_id
            raise

    def _submit_state(self, state: StreamState,
                      turns: list[tuple[str, Any]], *, max_new: int,
                      deadline_s: Optional[float],
                      adapters: Optional[list],
                      temperature: float = 0.0) -> None:
        """The scheduler half: pick the serving replica (router) or the
        one scheduler (N=1), submit with the streaming seam bridged
        onto the asyncio loop, classify every refusal into the shed
        taxonomy, and publish the inflight gauge."""
        loop = self._loop
        assert loop is not None, "gateway not started"

        def on_commit(event: dict, _st=state) -> None:
            # Scheduler loop thread → asyncio loop. A closed loop means
            # the gateway is going down; the journal keeps the story.
            try:
                loop.call_soon_threadsafe(self._on_stream_event, _st,
                                          dict(event))
            except RuntimeError:
                pass

        # Placement + submit run under the request trace's context
        # (ISSUE 20): the router's placement span and the scheduler's
        # tele_ctx capture (engine/scheduler.py submit) both read the
        # thread-local stack, so the whole engine-side span tree joins
        # this trace with zero signature changes.
        ctx = state.trace.context() if state.trace is not None else None
        with telemetry.attached(ctx):
            try:
                sched, replica = self._sched_for(state.session, adapters)
            except Exception as e:  # noqa: BLE001 — NoLiveReplica et al.
                self.admission.note_shed("engine_dead")
                raise _Shed(Decision(False, "engine_dead", 503,
                                     4 * self.admission.retry_after_s)) \
                    from e
            state.replica = replica
            sampling = [SamplingParams(temperature=temperature,
                                       max_new_tokens=max_new)
                        for _ in turns]
            timeout_s = deadline_s if deadline_s else 600.0
            try:
                req = sched.submit_async(
                    state.session, turns, max_new_tokens=max_new,
                    timeout_s=timeout_s, sampling_per_turn=sampling,
                    budget=make_budget(deadline_s),
                    adapters_per_turn=adapters, on_commit=on_commit,
                    queue_when_paused=False)
            except DeadlineExpired as e:
                self.admission._count("expired", "deadline_expired")
                raise HttpError(408, str(e), "deadline_expired")
            except deadlines.DrainingError as e:
                self.admission.note_shed("draining", replica=replica)
                raise _Shed(Decision(False, "draining", 503,
                                     self.admission.retry_after_s)) \
                    from e
            except SchedulerRefused as e:
                reason = e.reason or "refused"
                self.admission.note_shed(reason, replica=replica)
                status = 503 if reason in ("fleet.drain", "quiesce") \
                    else 429
                raise _Shed(Decision(False, reason, status,
                                     self.admission.retry_after_s)) \
                    from e
            except SchedulerClosed as e:
                self.admission.note_shed("closed", replica=replica)
                raise _Shed(Decision(False, "closed", 503,
                                     self.admission.retry_after_s)) \
                    from e
            except Exception as e:  # noqa: BLE001 — classify dead engines etc.
                from ..core.errors import classify_error
                kind = classify_error(e)
                self.admission.note_shed(kind, replica=replica)
                raise _Shed(Decision(False, kind, 503,
                                     4 * self.admission.retry_after_s)) \
                    from e
        # Keep the request handle: abandonment (client disconnected,
        # nobody reconnected within abandon_s) flips req.abandoned and
        # the scheduler's health check releases the round's LoRA refs,
        # KV rows and gauges — without it a walked-away client's round
        # would burn capacity to completion.
        state.request = req
        self.streams[state.stream_id] = state
        telemetry.set_gauge("roundtable_gateway_inflight_streams", 1,
                            **self._stream_labels(state))

    def _on_stream_event(self, state: StreamState, event: dict) -> None:
        """Asyncio-loop side of the scheduler's on_commit bridge."""
        first = not any(state.history) and event.get("type") == "tokens"
        trace = state.trace
        if first and trace is not None:
            # Everything since placement was the submit→first-token
            # lump; the scheduler reports its share of that lump spent
            # queued (queue_wait_s on the event), which is carved out
            # so the waterfall separates waiting from prefill.
            trace.stage("prefill")
            trace.carve("prefill", "queue_wait",
                        event.get("queue_wait_s"))
        state.on_commit_event(event)
        if first:
            if trace is not None:
                # TTFT = the stage sum through first_flush — the SAME
                # number the trace waterfall shows, so the admission
                # SLO signal and the trace can never disagree (the old
                # code lumped time.monotonic() - state.created).
                trace.stage("first_flush")
                ttft = trace.ttft()
                slo = self.admission.p95_slo_s
                if slo and ttft > slo:
                    trace.flag("slo_violation")
                self.admission.note_ttft(ttft,
                                         trace_id=trace.trace_id)
            else:
                self.admission.note_ttft(
                    time.monotonic() - state.created)
        if state.done:
            if trace is not None:
                if state.failed is not None:
                    trace.flag("failed")
                    trace.finish(
                        f"failed:{state.failed.get('kind', 'unknown')}")
                else:
                    trace.finish("ok")
            # Stream finished (retired or failed): its per-request
            # gauge series dies NOW — a long-lived gateway must not
            # keep one series per stream ever served (RT-GAUGE-LEAK).
            telemetry.REGISTRY.remove_gauge(
                "roundtable_gateway_inflight_streams",
                **self._stream_labels(state))
            self._evict_done_streams()

    def _release_consumer(self, state: StreamState, consumer) -> None:
        """Detach a pump's consumer; when that was the LAST one on a
        live stream, start the abandonment clock — a reconnect within
        `abandon_s` cancels it, otherwise the round is abandoned and
        the scheduler releases everything it held (ISSUE 19)."""
        state.detach(consumer)
        if state.done or state.attached() or self._loop is None:
            return
        self._loop.call_later(self.abandon_s, self._reap_orphan, state)

    def _reap_orphan(self, state: StreamState) -> None:
        if state.done or state.attached():
            return  # finished or reconnected — not abandoned
        req = getattr(state, "request", None)
        if req is None:
            return
        req.abandoned = True
        telemetry.inc("roundtable_gateway_abandoned_streams_total")

    def _evict_done_streams(self) -> None:
        done = [sid for sid, st in self.streams.items() if st.done]
        while len(done) > _DONE_STREAM_CAP:
            self.streams.pop(done.pop(0), None)
        self._compact_intents()

    def _compact_intents(self) -> None:
        """Bound the intent journal + cache. A record whose turn is
        committed in the session journal is only ever needed again for
        a leg-2 reconnect, so only the newest `intent_cap // 2` of
        those are kept; uncommitted intents (a crash would need them
        for leg-3 regeneration) always survive."""
        if (self.intents is None or self.sched.journal is None
                or len(self._intent_cache) <= self.intent_cap):
            return
        committed = [
            sid for sid, rec in self._intent_cache.items()
            if committed_rows(self.sched.journal, rec["session"],
                              rec["turn"]) is not None]
        keep_committed = max(self.intent_cap // 2, 1)
        drop = set(committed[:-keep_committed])
        if not drop:
            return
        keep = {sid: rec for sid, rec in self._intent_cache.items()
                if sid not in drop}
        # Cache evicts only if the on-disk journal rewrote: the two
        # must never disagree about which streams can reconnect.
        if self.intents.compact(keep):
            self._intent_cache = keep

    # ------------------------------------------------------------------
    # POST /v1/chat/completions (OpenAI-compatible)
    # ------------------------------------------------------------------

    async def _chat_completions(self, req: Request,
                                writer: asyncio.StreamWriter) -> None:
        try:
            body = req.json()
        except (ValueError, json.JSONDecodeError) as e:
            raise HttpError(400, f"bad JSON body: {e}", "bad_json")
        messages = body.get("messages")
        if not isinstance(messages, list) or not messages:
            raise HttpError(400, "messages[] is required",
                            "bad_request")
        prompt = "\n".join(
            f"{m.get('role', 'user')}: {m.get('content', '')}"
            for m in messages) + "\nassistant:"
        knight = str(body.get("model") or "assistant")
        session = str(body.get("session")
                      or f"chat-{uuid.uuid4().hex[:8]}")
        max_new = int(body.get("max_tokens") or 128)
        temperature = float(body.get("temperature") or 0.0)
        deadline_s = self._client_deadline(req, body)
        priority = str(req.header("x-roundtable-priority")
                       or body.get("priority") or "normal")
        state = self._submit_stream(
            session=session, turns=[(knight, prompt)], max_new=max_new,
            deadline_s=deadline_s, priority=priority, adapters=None,
            kind="chat", temperature=temperature,
            traceparent=req.header("traceparent"))
        consumer = state.attach()
        if body.get("stream"):
            await self._pump_chat(writer, state, consumer)
        else:
            trace_id = state.trace.trace_id \
                if state.trace is not None else ""
            try:
                failed = await self._await_done(consumer, deadline_s)
            finally:
                self._release_consumer(state, consumer)
            if failed is not None:
                err = HttpError(500, failed.get("error", "failed"),
                                failed.get("kind", "unknown"))
                err.trace_id = trace_id
                raise err
            text = self._decode(state.history[0])
            headers = {"Traceparent": tracing.format_traceparent(
                trace_id)} if trace_id else None
            await send_json(writer, 200, {
                "id": f"chatcmpl-{state.stream_id}",
                "object": "chat.completion",
                "created": int(time.time()),
                "model": knight,
                "choices": [{"index": 0,
                             "message": {"role": "assistant",
                                         "content": text},
                             "finish_reason": "stop"}],
                "usage": {"completion_tokens": len(state.history[0])},
            }, headers)

    async def _await_done(self, consumer,
                          deadline_s: Optional[float]) -> Optional[dict]:
        """Drain a consumer without a socket (non-streaming response).
        Returns the failure payload, or None on clean retirement."""
        bound = time.monotonic() + (deadline_s or 600.0) + 60.0
        while not consumer.finished():
            if time.monotonic() > bound:
                tr = consumer.state.trace
                err = HttpError(500, "stream never finished",
                                "gateway_wedged")
                if tr is not None:
                    tr.flag("hung")
                    tr.finish("hung")
                    err.trace_id = tr.trace_id
                raise err
            for ev in await consumer.next_events(self.keepalive_s):
                if ev["type"] == "failed":
                    return {"error": ev.get("error", ""),
                            "kind": ev.get("kind", "unknown")}
        return consumer.state.failed

    # ------------------------------------------------------------------
    # POST /v1/discussions (native multi-knight)
    # ------------------------------------------------------------------

    async def _discussions(self, req: Request,
                           writer: asyncio.StreamWriter) -> None:
        try:
            body = req.json()
        except (ValueError, json.JSONDecodeError) as e:
            raise HttpError(400, f"bad JSON body: {e}", "bad_json")
        raw_turns = body.get("turns")
        if not isinstance(raw_turns, list) or not raw_turns:
            raise HttpError(400, "turns[] is required", "bad_request")
        turns: list[tuple[str, Any]] = []
        for t in raw_turns:
            if not isinstance(t, dict) or "knight" not in t \
                    or "prompt" not in t:
                raise HttpError(400, "each turn needs knight + prompt",
                                "bad_request")
            turns.append((str(t["knight"]), t["prompt"]))
        session = str(body.get("session")
                      or f"disc-{uuid.uuid4().hex[:8]}")
        max_new = int(body.get("max_new_tokens") or 64)
        adapters = body.get("adapters")
        deadline_s = self._client_deadline(req, body)
        priority = str(req.header("x-roundtable-priority")
                       or body.get("priority") or "normal")
        state = self._submit_stream(
            session=session, turns=turns, max_new=max_new,
            deadline_s=deadline_s, priority=priority,
            adapters=adapters, kind="native",
            temperature=float(body.get("temperature") or 0.0),
            traceparent=req.header("traceparent"))
        consumer = state.attach()
        await self._pump_native(writer, state, consumer)

    # ------------------------------------------------------------------
    # GET /v1/streams/<id> (reconnect)
    # ------------------------------------------------------------------

    async def _reconnect(self, req: Request,
                         writer: asyncio.StreamWriter,
                         stream_id: str) -> None:
        state = self.streams.get(stream_id)
        crossed = False
        if (state is not None and state.failed is not None
                and self.router is not None
                and state.failed.get("kind") in _FAILOVER_KINDS):
            # The stream died WITH its replica, not with its request:
            # drop the corpse and restore on a survivor — the router's
            # failover already re-established the session's KV there,
            # so leg 2/3 of the ladder resumes byte-identically and the
            # client's Last-Event-ID skips what it already saw.
            self.streams.pop(stream_id, None)
            state = None
            crossed = True
        if state is None:
            state = self._restore_stream(stream_id, crossed=crossed)
        elif state.trace is not None:
            # Live-stream rejoin (ladder leg 1): same trace, counted,
            # marked with a follow-on `resume` span so the waterfall
            # shows the reconnect without starting a new leg clock.
            state.trace.reconnects += 1
            with telemetry.span("resume", parent=state.trace.context(),
                                stream=stream_id,
                                session=state.session, live=True):
                pass
        watermark = [0] * len(state.knights)
        leid = req.header("last-event-id")
        if leid:
            parsed = parse_event_id(leid, len(state.knights))
            if parsed is not None and parsed[0] == state.turn:
                watermark = parsed[1]
        consumer = state.attach(watermark)
        self.resumed_streams += 1
        telemetry.inc("roundtable_gateway_resumed_streams_total")
        await self._pump_native(writer, state, consumer)

    def _restore_stream(self, stream_id: str,
                        crossed: bool = False) -> StreamState:
        """Post-restart reconnect: rebuild the stream from the intent
        journal — from the committed turn when the round finished
        before the crash, by greedy re-generation otherwise. The
        restore leg REJOINS the original trace (the intent record
        carries its id), so one client request stays one stitched
        trace across kill -9 and failover; `crossed` marks a leg that
        moved replicas (always tail-retained)."""
        intent = self._intent_cache.get(stream_id)
        if intent is None:
            raise HttpError(404, f"unknown stream {stream_id!r}",
                            "unknown_stream")
        session = intent["session"]
        knights = intent["knights"]
        trace = tracing.RequestTrace(
            intent.get("trace") or None, kind="resume",
            stream=stream_id, session=session,
            endpoint=str(intent.get("kind", "native")))
        if crossed:
            trace.flag("replica_crossed")
        state = StreamState(stream_id, session, knights,
                            intent["turn"], buffer_cap=self.sse_buffer)
        state.trace = trace
        rows = committed_rows(self.sched.journal, session,
                              intent["turn"])
        if rows is not None:
            # Leg 2: the round committed before the crash — serve
            # straight from the durable record, no recompute. The leg
            # is pure replay: its whole (tiny) wall is resume_replay.
            for i, row in enumerate(rows[:len(knights)]):
                state.history[i] = [int(t) for t in
                                    row.get("produced", [])]
            state.done = True
            self.streams[stream_id] = state
            trace.finish("ok", tail_stage="resume_replay")
        else:
            # Leg 3: crash mid-round — greedy re-generation over the
            # replayed KV produces the identical token stream; the
            # client's watermark skips what it already saw. A sampled
            # stream (temperature > 0) cannot regenerate identically,
            # so refuse rather than splice a different stream onto the
            # client's watermark (silent corruption).
            temperature = float(intent.get("temperature") or 0.0)
            if temperature > 0.0:
                err = HttpError(
                    409, f"stream {stream_id!r} was sampled "
                    "(temperature > 0) and its turn never committed — "
                    "post-crash regeneration cannot be byte-identical; "
                    "start a new request", "nondeterministic_stream")
                trace.flag("failed")
                trace.finish("nondeterministic_stream",
                             tail_stage="resume_replay")
                err.trace_id = trace.trace_id
                raise err
            turns = list(zip(knights, intent["prompts"]))
            # Restore bookkeeping up to here is the resume_replay
            # stage; the re-submit itself is placement, and the regen
            # prefill/decode land in the usual stages via the event
            # bridge — the resume leg gets a full waterfall.
            trace.stage("resume_replay")
            try:
                self._submit_state(state, turns,
                                   max_new=int(intent["max_new"]),
                                   deadline_s=intent.get("deadline_s"),
                                   adapters=intent.get("adapters"))
            except _Shed as s:
                trace.flag("shed")
                trace.finish(f"shed:{s.decision.reason}",
                             tail_stage="resume_replay")
                s.trace_id = trace.trace_id
                raise
            except HttpError as e:
                trace.flag("failed")
                trace.finish(f"error:{e.reason}",
                             tail_stage="resume_replay")
                e.trace_id = trace.trace_id
                raise
            trace.stage("placement")
            trace.replica = getattr(state, "replica", None)
        return state

    # ------------------------------------------------------------------
    # SSE pumps
    # ------------------------------------------------------------------

    def _decode(self, ids: list[int]) -> str:
        try:
            return self.sched.engine.tokenizer.decode(ids)
        except Exception:  # noqa: BLE001 — stream ids even if decode trips
            return ""

    async def _pump_native(self, writer: asyncio.StreamWriter,
                           state: StreamState, consumer) -> None:
        tid = state.trace.trace_id if state.trace is not None else ""
        sse = SseWriter(writer)
        await sse.open({"Traceparent": tracing.format_traceparent(tid)}
                       if tid else None)
        # Metadata first: the stream id IS the reconnect handle
        # (GET /v1/streams/<id>) — a client that only ever saw this
        # event can still resume from zero after a crash. The trace id
        # rides it (and every payload below) so any single event a
        # client holds names the trace to quote in a report.
        meta = {"type": "stream", "stream": state.stream_id,
                "session": state.session, "turn": state.turn,
                "knights": state.knights}
        if tid:
            meta["trace"] = tid
        await sse.event(
            meta,
            event_id=format_event_id(state.turn, list(consumer.sent)))
        try:
            while True:
                events = await consumer.next_events(self.keepalive_s)
                if not events:
                    if consumer.finished():
                        break
                    await sse.comment()
                    continue
                terminal = False
                for ev in events:
                    payload, ntok = self._native_payload(state, ev)
                    if tid:
                        payload["trace"] = tid
                    await sse.event(payload, event_id=ev["id"],
                                    tokens=ntok)
                    terminal = terminal or ev["type"] in ("retired",
                                                          "failed")
                if terminal:
                    break
        finally:
            self._release_consumer(state, consumer)

    def _native_payload(self, state: StreamState,
                        ev: dict) -> tuple[dict, int]:
        if ev["type"] == "tokens":
            toks = ev["tokens"]
            return ({"type": "tokens", "row": ev["row"],
                     "knight": ev["knight"], "tokens": toks,
                     "text": self._decode(toks)}, len(toks))
        if ev["type"] == "summary":
            rows = {str(i): {"tokens": d, "text": self._decode(d),
                             "knight": state.knights[i]}
                    for i, d in ev["rows"].items()}
            n = sum(len(d) for d in ev["rows"].values())
            return ({"type": "summary", "rows": rows,
                     "coalesced": True}, n)
        if ev["type"] == "failed":
            return ({"type": "failed", "error": ev.get("error", ""),
                     "kind": ev.get("kind", "unknown")}, 0)
        return ({"type": "retired", "session": state.session,
                 "turn": state.turn}, 0)

    async def _pump_chat(self, writer: asyncio.StreamWriter,
                         state: StreamState, consumer) -> None:
        tid = state.trace.trace_id if state.trace is not None else ""
        sse = SseWriter(writer)
        await sse.open({"Traceparent": tracing.format_traceparent(tid)}
                       if tid else None)
        cid = f"chatcmpl-{state.stream_id}"
        model = state.knights[0]

        def chunk(delta: dict, finish: Optional[str] = None) -> dict:
            out = {"id": cid, "object": "chat.completion.chunk",
                   "created": int(time.time()), "model": model,
                   "choices": [{"index": 0, "delta": delta,
                                "finish_reason": finish}]}
            if tid:
                out["trace"] = tid
            return out

        try:
            while True:
                events = await consumer.next_events(self.keepalive_s)
                if not events:
                    if consumer.finished():
                        break
                    await sse.comment()
                    continue
                terminal = False
                for ev in events:
                    if ev["type"] in ("tokens", "summary"):
                        toks = ev.get("tokens") or [
                            t for d in ev.get("rows", {}).values()
                            for t in d]
                        await sse.event(
                            chunk({"content": self._decode(toks)}),
                            event_id=ev["id"], tokens=len(toks))
                    elif ev["type"] == "failed":
                        await sse.event(chunk({}, finish="error"),
                                        event_id=ev["id"])
                        terminal = True
                    else:  # retired
                        await sse.event(chunk({}, finish="stop"),
                                        event_id=ev["id"])
                        await sse.event("[DONE]")
                        terminal = True
                if terminal:
                    break
        finally:
            self._release_consumer(state, consumer)
