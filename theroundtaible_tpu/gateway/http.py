"""Minimal asyncio HTTP/1.1 + SSE plumbing (stdlib-only, no deps).

One request per connection (`Connection: close`) keeps the parser
honest and small: read the request line + headers, read the body by
Content-Length, dispatch, write either a full JSON response or an SSE
stream. That is everything the gateway needs — this is a serving seam,
not a web framework.
"""

from __future__ import annotations

import asyncio
import json
from typing import Any, Optional

from . import streams as _streams

MAX_HEADER_BYTES = 32 * 1024
MAX_BODY_BYTES = 4 * 1024 * 1024


class Request:
    __slots__ = ("method", "path", "query", "headers", "body")

    def __init__(self, method: str, path: str, query: str,
                 headers: dict[str, str], body: bytes):
        self.method = method
        self.path = path
        self.query = query
        self.headers = headers
        self.body = body

    def json(self) -> dict:
        if not self.body:
            return {}
        obj = json.loads(self.body.decode("utf-8"))
        if not isinstance(obj, dict):
            raise ValueError("request body must be a JSON object")
        return obj

    def header(self, name: str, default: Optional[str] = None):
        return self.headers.get(name.lower(), default)


class HttpError(Exception):
    def __init__(self, status: int, message: str,
                 reason: str = "bad_request"):
        super().__init__(message)
        self.status = status
        self.reason = reason


_STATUS_TEXT = {
    200: "OK", 400: "Bad Request", 404: "Not Found",
    405: "Method Not Allowed", 408: "Request Timeout",
    409: "Conflict", 429: "Too Many Requests",
    500: "Internal Server Error", 503: "Service Unavailable",
}


async def read_request(reader: asyncio.StreamReader) -> Optional[Request]:
    """Parse one HTTP/1.1 request; None on clean EOF before a request
    line (client connected and left)."""
    try:
        head = await reader.readuntil(b"\r\n\r\n")
    except asyncio.IncompleteReadError as e:
        if not e.partial:
            return None
        raise HttpError(400, "truncated request head")
    except asyncio.LimitOverrunError:
        raise HttpError(400, "request head too large")
    if len(head) > MAX_HEADER_BYTES:
        raise HttpError(400, "request head too large")
    lines = head.decode("latin-1").split("\r\n")
    parts = lines[0].split(" ")
    if len(parts) != 3:
        raise HttpError(400, f"malformed request line: {lines[0]!r}")
    method, target, _version = parts
    path, _, query = target.partition("?")
    headers: dict[str, str] = {}
    for line in lines[1:]:
        if not line:
            continue
        name, _, value = line.partition(":")
        headers[name.strip().lower()] = value.strip()
    length = int(headers.get("content-length", "0") or "0")
    if length > MAX_BODY_BYTES:
        raise HttpError(400, "request body too large")
    body = await reader.readexactly(length) if length else b""
    return Request(method.upper(), path, query, headers, body)


def _head(status: int, content_type: str,
          extra: Optional[dict[str, str]] = None,
          length: Optional[int] = None) -> bytes:
    lines = [f"HTTP/1.1 {status} {_STATUS_TEXT.get(status, 'Unknown')}",
             f"Content-Type: {content_type}",
             "Connection: close"]
    if length is not None:
        lines.append(f"Content-Length: {length}")
    for k, v in (extra or {}).items():
        lines.append(f"{k}: {v}")
    return ("\r\n".join(lines) + "\r\n\r\n").encode("latin-1")


async def send_json(writer: asyncio.StreamWriter, status: int,
                    payload: dict,
                    extra_headers: Optional[dict[str, str]] = None
                    ) -> None:
    body = json.dumps(payload, indent=None).encode("utf-8")
    writer.write(_head(status, "application/json", extra_headers,
                       len(body)))
    writer.write(body)
    await writer.drain()


async def send_text(writer: asyncio.StreamWriter, status: int,
                    text: str, content_type: str = "text/plain"
                    ) -> None:
    body = text.encode("utf-8")
    writer.write(_head(status, content_type, None, len(body)))
    writer.write(body)
    await writer.drain()


class SseWriter:
    """Server-Sent Events over one connection. Every event carries the
    stream's cumulative event id (`id:` field), so whatever a client
    last received doubles as its reconnect watermark."""

    def __init__(self, writer: asyncio.StreamWriter):
        self._w = writer
        self.opened = False

    async def open(self, extra_headers: Optional[dict[str, str]] = None
                   ) -> None:
        # extra_headers: the gateway echoes the request's traceparent
        # here (ISSUE 20) so clients can join server traces without
        # parsing the SSE body.
        headers = {
            "Cache-Control": "no-cache",
            "X-Accel-Buffering": "no",
        }
        headers.update(extra_headers or {})
        self._w.write(_head(200, "text/event-stream", headers))
        await self._w.drain()
        self.opened = True
        # Marked on the connection itself so the gateway's error
        # handlers (which never see this SseWriter) know a response
        # head is already on the wire — a late failure must become a
        # terminal SSE event, never a second HTTP head mid-stream.
        self._w._sse_opened = True

    async def event(self, data: Any,
                    event_id: Optional[str] = None,
                    tokens: int = 0) -> None:
        chunk = ""
        if event_id is not None:
            chunk += f"id: {event_id}\n"
        payload = data if isinstance(data, str) else json.dumps(
            data, indent=None)
        chunk += f"data: {payload}\n\n"
        self._w.write(chunk.encode("utf-8"))
        await self._w.drain()
        if tokens:
            # The conftest `gateway` guard's proof-of-streaming: token
            # events written to a REAL socket, counted after drain.
            _streams.note_tokens_streamed(tokens)

    async def comment(self, text: str = "keepalive") -> None:
        self._w.write(f": {text}\n\n".encode("utf-8"))
        await self._w.drain()
