"""Per-stream state: committed-token history, event ids, bounded
consumer buffers with drop-to-summary.

The scheduler's streaming seam delivers COMMITTED tokens (eos-trimmed,
segment-grain) on its loop thread; the gateway bridges each event onto
the asyncio loop into one `StreamState`. The state keeps the full
per-row committed history for the round — bounded by rows x max_new by
construction — so any number of consumers (including a reconnecting
one with a `Last-Event-ID` watermark) read exactly the tokens after
their last-seen event: no loss, no duplication.

Event-id scheme (crash-consistent): `"<turn>:<c0>,<c1>,..."` — the
journal turn this stream commits as, plus the cumulative per-row
committed-token counts AFTER the event. One id therefore encodes the
whole multi-row watermark, so a single `Last-Event-ID` header resumes
every knight's row of a discussion stream at once. Greedy decoding +
journal replay regenerate identical token streams after a crash, so
the counts stay aligned across process generations.

Slow consumers: each connection drains through a BOUNDED event queue
(ROUNDTABLE_GATEWAY_SSE_BUFFER). On overflow the oldest fine-grained
events are dropped (counted: roundtable_gateway_dropped_events_total)
and the consumer is handed one catch-up SUMMARY event computed from
history-vs-watermark — content is never lost, only event granularity.
"""

from __future__ import annotations

import asyncio
import threading
import time
from typing import Optional

from ..utils import telemetry

# --- test counters (conftest `gateway` marker guard) -----------------
# A gateway-marked test that never streamed a token over a real socket
# proves nothing about the serving path — the guard fails LOUD unless
# this counter moved (the scheduler test-counter pattern).

_test_tokens_streamed = 0
_test_lock = threading.Lock()


def reset_test_counters() -> None:
    global _test_tokens_streamed
    with _test_lock:
        _test_tokens_streamed = 0


def tokens_streamed() -> int:
    return _test_tokens_streamed


def note_tokens_streamed(n: int) -> None:
    """Called by the SSE write path when token events hit a socket."""
    global _test_tokens_streamed
    with _test_lock:
        _test_tokens_streamed += n


# --- event ids -------------------------------------------------------

def format_event_id(turn: int, counts: list[int]) -> str:
    return f"{turn}:{','.join(str(c) for c in counts)}"


def parse_event_id(eid: str, rows: int) -> Optional[tuple[int, list[int]]]:
    """(turn, per-row counts) of a client's Last-Event-ID, or None when
    it doesn't parse / doesn't match the stream's row count (a client
    replaying a stale id against the wrong stream restarts from 0
    rather than silently skipping tokens)."""
    try:
        turn_s, _, counts_s = eid.strip().partition(":")
        turn = int(turn_s)
        counts = [int(c) for c in counts_s.split(",")] if counts_s else []
    except ValueError:
        return None
    if len(counts) != rows:
        return None
    if turn < 0 or any(c < 0 for c in counts):
        return None
    return turn, counts


# --- stream state ----------------------------------------------------

class StreamState:
    """One admitted stream: the round's committed history plus the
    fan-out to live SSE consumers. All mutation happens on the asyncio
    loop (the scheduler thread bridges via call_soon_threadsafe)."""

    def __init__(self, stream_id: str, session: str, knights: list[str],
                 turn: int, *, buffer_cap: int = 512):
        self.stream_id = stream_id
        self.session = session
        self.knights = knights
        self.turn = turn
        self.buffer_cap = max(buffer_cap, 8)
        # Committed token history per row — the resume source of truth
        # for in-process reconnects (post-crash reconnects read the
        # session journal / regenerate instead).
        self.history: list[list[int]] = [[] for _ in knights]
        self.done = False
        self.failed: Optional[dict] = None  # {"error", "kind"}
        self.created = time.monotonic()
        self._consumers: list["_Consumer"] = []
        # The scheduler's _Request handle (set by the gateway after
        # submit): the abandonment seam — flipping request.abandoned
        # makes the scheduler release the round's holds (ISSUE 19).
        self.request = None
        # The request's RequestTrace (utils/tracing, ISSUE 20): one per
        # serving leg; its trace id is echoed on every SSE payload and
        # survives reconnects/restarts via the intent journal.
        self.trace = None

    # -- producer side (bridged scheduler events) --

    def on_commit_event(self, event: dict) -> None:
        """Fold one scheduler stream event ({"type": "tokens"|"retired"
        |"failed", ...}) into history and wake every consumer."""
        kind = event.get("type")
        if kind == "tokens":
            row = event["row"]
            if 0 <= row < len(self.history):
                self.history[row].extend(event["tokens"])
        elif kind == "retired":
            self.done = True
        elif kind == "failed":
            self.done = True
            self.failed = {"error": event.get("error", ""),
                           "kind": event.get("kind", "unknown")}
        for c in list(self._consumers):
            c.wake(event)

    def counts(self) -> list[int]:
        return [len(h) for h in self.history]

    def event_id(self) -> str:
        return format_event_id(self.turn, self.counts())

    # -- consumer side --

    def attach(self, watermark: Optional[list[int]] = None) -> "_Consumer":
        c = _Consumer(self, watermark or [0] * len(self.knights))
        self._consumers.append(c)
        return c

    def detach(self, c: "_Consumer") -> None:
        if c in self._consumers:
            self._consumers.remove(c)

    def attached(self) -> int:
        return len(self._consumers)


class _Consumer:
    """One SSE connection's view of a stream: a watermark into the
    shared history plus a bounded wake queue. The queue bounds EVENT
    backlog, not content — overflow drops granularity (summary
    catch-up from history), never tokens."""

    def __init__(self, state: StreamState, watermark: list[int]):
        self.state = state
        self.sent = list(watermark)
        self._wakes: asyncio.Queue = asyncio.Queue(
            maxsize=state.buffer_cap)
        self.overflowed = False

    def wake(self, event: dict) -> None:
        try:
            self._wakes.put_nowait(event)
        except asyncio.QueueFull:
            # Slow consumer: drop the fine-grained event (counted) —
            # the next drain emits one summary catch-up from history.
            self.overflowed = True
            telemetry.inc("roundtable_gateway_dropped_events_total")

    async def next_events(self, timeout_s: float = 15.0) -> list[dict]:
        """Unsent committed content since this consumer's watermark,
        as a list of emit-ready events (each tagged with the POST-event
        cumulative id). Blocks until something new commits, the stream
        finishes, or `timeout_s` passes (empty list = keepalive tick).

        Coalescing rule: on overflow, everything pending collapses to
        one summary event; otherwise each call emits per-row deltas at
        whatever grain has accumulated — a fast consumer sees
        segment-grain events, a slow one sees bigger batches."""
        st = self.state
        if not self._pending() and not st.done:
            try:
                await asyncio.wait_for(self._wakes.get(), timeout_s)
                # Drain coalesced wakes — deltas come from history.
                while not self._wakes.empty():
                    self._wakes.get_nowait()
            except asyncio.TimeoutError:
                return []
        out: list[dict] = []
        was_summary = self.overflowed
        self.overflowed = False
        deltas: dict[int, list[int]] = {}
        for i, h in enumerate(st.history):
            if len(h) > self.sent[i]:
                deltas[i] = h[self.sent[i]:]
        if was_summary and deltas:
            # One catch-up event carries every row, so its id advances
            # all watermarks at once.
            for i, d in deltas.items():
                self.sent[i] += len(d)
            out.append({"type": "summary",
                        "id": format_event_id(st.turn, list(self.sent)),
                        "rows": dict(deltas)})
        else:
            # The watermark advances PER EVENT: a client cut off after
            # the first event of a multi-row batch holds an id counting
            # only the tokens it actually received — stamping the whole
            # batch with the post-batch id would make its reconnect
            # silently skip the later rows' tokens.
            for i, d in deltas.items():
                self.sent[i] += len(d)
                out.append({"type": "tokens",
                            "id": format_event_id(st.turn,
                                                  list(self.sent)),
                            "row": i, "knight": st.knights[i],
                            "tokens": d})
        if st.done and not self._pending():
            eid = format_event_id(st.turn, list(self.sent))
            if st.failed is not None:
                out.append({"type": "failed", "id": eid, **st.failed})
            else:
                out.append({"type": "retired", "id": eid})
        return out

    def _pending(self) -> bool:
        return any(len(h) > self.sent[i]
                   for i, h in enumerate(self.state.history))

    def finished(self) -> bool:
        return self.state.done and not self._pending()
