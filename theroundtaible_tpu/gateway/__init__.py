"""Streaming serving gateway (ISSUE 16): the asyncio HTTP/SSE front
door over SessionScheduler.

Stdlib-only by design (asyncio + json — no new deps): an
OpenAI-compatible `/v1/chat/completions` streaming endpoint plus a
native `/v1/discussions` endpoint, fed by the scheduler's
committed-token streaming seam (`submit_async(on_commit=...)`), with

- SLO-driven admission + load shedding (gateway/admission.py),
- bounded per-consumer SSE buffers with drop-to-summary
  (gateway/streams.py),
- crash-consistent mid-stream resume via journal-backed SSE event ids
  and `Last-Event-ID` reconnects (gateway/resume.py),
- graceful drain: `fleet.drain()` flips admission to 503/draining
  while in-flight streams finish.
"""

from .admission import AdmissionController, Decision
from .app import Gateway
from .streams import StreamState, reset_test_counters, tokens_streamed

__all__ = ["Gateway", "AdmissionController", "Decision", "StreamState",
           "reset_test_counters", "tokens_streamed"]
