"""SLO-driven admission control: shed/queue decisions from live
serving signals.

Every decision derives from state the serving stack already publishes
— nothing here samples the device or adds a poll loop:

| signal            | source                                  | shed reason    |
|-------------------|-----------------------------------------|----------------|
| fleet drain       | deadlines.DRAINING / scheduler.paused   | draining (503) |
| dead engine       | supervisor.engine_dead_reason           | engine_dead (503) |
| spent deadline    | client deadline header <= 0             | deadline_expired (408) |
| inflight cap      | live gateway stream table               | inflight_cap (429) |
| queue depth       | scheduler describe()["admission"]       | queue_full (429) |
| KV page pressure  | paged free pages + spill headroom       | kv_pressure (429) |
| adapter residency | LoraStore.can_admit (lora.py)           | adapters_busy (429) |
| p95 turn latency  | gateway's own recent-TTFT window        | slo_p95 (429)  |

The serving-stack signals arrive through a provider (`source=`):
`SchedulerSignals` reads one scheduler/engine (the default — and the
exact pre-ISSUE-17 behavior), the router's `FleetSignals` reads the
whole replica fleet and only sheds when NO replica can serve.

Priority classes: "high" requests bypass the soft signals (p95) and
shed only at hard caps; "low" requests shed at half the inflight/queue
caps — under pressure the cheap traffic goes first. Every shed carries
`Retry-After` plus a machine-readable reason so clients back off
deterministically instead of hammering a collapsing server.

Counters move in lockstep with decisions (`_count` is the one writer):
roundtable_gateway_{admitted,shed,queued,expired}_total{reason=...};
`queued` is the subset of admissions that entered a NONEMPTY scheduler
queue — admitted, but waiting behind in-flight rounds to start.
"""

from __future__ import annotations

import os
import sys
import time
from dataclasses import dataclass, field
from typing import Any, Optional

from ..engine import deadlines
from ..utils import telemetry, tracing

_PRIORITY_SCALE = {"high": 1.0, "normal": 1.0, "low": 0.5}


def _env_int(name: str, default: int) -> int:
    try:
        return int(os.environ.get(name, ""))
    except ValueError:
        return default


def _env_float(name: str, default: float) -> float:
    try:
        return float(os.environ.get(name, ""))
    except ValueError:
        return default


# --- derived thresholds (ISSUE 19) -----------------------------------

CAPACITY_FILE_ENV = "ROUNDTABLE_GATEWAY_CAPACITY_FILE"

# field -> (env var, parse, built-in default). Precedence per FIELD:
# explicit ctor arg > env var > capacity record > built-in default.
_FIELD_ENVS: dict[str, tuple] = {
    "max_inflight": ("ROUNDTABLE_GATEWAY_MAX_INFLIGHT", int, 32),
    "max_queue_depth": ("ROUNDTABLE_GATEWAY_MAX_QUEUE_DEPTH", int, 16),
    "page_headroom": ("ROUNDTABLE_GATEWAY_PAGE_HEADROOM", float, 0.05),
    "p95_slo_s": ("ROUNDTABLE_GATEWAY_P95_SLO_S", float, 0.0),
    "retry_after_s": ("ROUNDTABLE_GATEWAY_RETRY_AFTER_S", float, 2.0),
}


@dataclass(frozen=True)
class Thresholds:
    """The admission caps with their provenance. `resolve()` layers
    env var > measured capacity record (CAPACITY_FILE_ENV) > built-in
    default — a malformed record degrades LOUDLY to defaults (stderr
    + roundtable_gateway_capacity_record_errors_total) and never
    crashes admission."""

    max_inflight: int = 32
    max_queue_depth: int = 16
    page_headroom: float = 0.05
    p95_slo_s: float = 0.0
    retry_after_s: float = 2.0
    source: str = "default"      # default | capacity_record
    record_path: Optional[str] = None
    env_overrides: tuple = field(default_factory=tuple)

    @classmethod
    def from_capacity_record(cls, record: Any, *,
                             path: Optional[str] = None
                             ) -> "Thresholds":
        """Thresholds DERIVED from a measured capacity frontier
        (loadgen sweep record, bare or bench-wrapped). Raises
        ValueError on a malformed record — resolve() turns that into
        the loud-degrade path."""
        from ..loadgen.capacity import extract_thresholds
        th = extract_thresholds(record)
        return cls(max_inflight=int(th["max_inflight"]),
                   max_queue_depth=int(th["max_queue_depth"]),
                   p95_slo_s=float(th["p95_slo_s"]),
                   source="capacity_record", record_path=path)

    @classmethod
    def resolve(cls) -> "Thresholds":
        base = cls()
        path = os.environ.get(CAPACITY_FILE_ENV)
        if path:
            try:
                from ..loadgen.capacity import load_record
                base = cls.from_capacity_record(load_record(path),
                                                path=path)
            except ValueError as e:
                telemetry.inc("roundtable_gateway_capacity_record_"
                              "errors_total")
                print(f"[gateway] ignoring {CAPACITY_FILE_ENV}="
                      f"{path!r}: {e} — falling back to built-in "
                      "admission defaults", file=sys.stderr)
        overrides: dict[str, Any] = {}
        for fname, (env, parse, _default) in _FIELD_ENVS.items():
            if env not in os.environ:
                continue
            try:
                overrides[fname] = parse(os.environ[env])
            except ValueError:
                # Matches the historical _env_* behavior: an unparsable
                # env value falls through to the layer below.
                continue
        if not overrides:
            return base
        return cls(**{**{f: getattr(base, f) for f in _FIELD_ENVS},
                      **overrides},
                   source=base.source, record_path=base.record_path,
                   env_overrides=tuple(sorted(overrides)))

    def describe(self) -> dict[str, Any]:
        return {
            "max_inflight": self.max_inflight,
            "max_queue_depth": self.max_queue_depth,
            "page_headroom": self.page_headroom,
            "p95_slo_s": self.p95_slo_s,
            "source": self.source,
            "record_path": self.record_path,
            "env_overrides": list(self.env_overrides),
        }


@dataclass(frozen=True)
class Decision:
    admit: bool
    reason: str                  # "ok" or the shed reason tag
    status: int = 200            # HTTP status for sheds
    retry_after_s: float = 0.0
    # Admitted INTO a nonempty scheduler queue: the request parks
    # behind in-flight rounds instead of starting now. Drives the
    # queued counter (roundtable_gateway_queued_total).
    queued: bool = False


class SchedulerSignals:
    """The single-engine admission signal provider: every signal reads
    ONE scheduler/engine, exactly as the gateway did before ISSUE 17.
    The router's FleetSignals implements the same protocol over N
    replicas — single-engine serving is just the N=1 case."""

    def __init__(self, scheduler):
        self.sched = scheduler

    def drain_state(self) -> Optional[str]:
        paused = self.sched.paused
        if deadlines.DRAINING or paused is not None:
            return "draining" if (deadlines.DRAINING
                                  or paused == "fleet.drain") \
                else f"paused:{paused}"
        return None

    def dead_reason(self) -> Optional[str]:
        from ..engine.supervisor import engine_dead_reason
        return engine_dead_reason(self.sched.engine)

    def queue_depth(self) -> int:
        return self.sched.describe()["admission"]["queued"]

    def kv_pressure(self, headroom: float) -> bool:
        engine = self.sched.engine
        if getattr(engine, "kv_layout", None) != "paged":
            return False
        kv = engine.kv
        floor = int(kv.usable_pages() * headroom)
        return (kv.free_pages() <= floor
                and getattr(engine, "kv_offload", None) is None)

    def adapters_busy(self, adapters) -> bool:
        store = getattr(self.sched.engine, "lora", None)
        return (store is not None
                and not store.can_admit(adapters))


class AdmissionController:
    """Derives one Decision per request from the live signals above.

    Stateless against the signal source (reads its provider methods —
    `SchedulerSignals` for one engine, the router's `FleetSignals` for
    a fleet); its own state is the shed/admit accounting and a bounded
    window of recent TTFT samples for the p95 SLO signal."""

    def __init__(self, scheduler, *,
                 source=None,
                 max_inflight: Optional[int] = None,
                 max_queue_depth: Optional[int] = None,
                 page_headroom: Optional[float] = None,
                 p95_slo_s: Optional[float] = None,
                 retry_after_s: Optional[float] = None,
                 thresholds: Optional[Thresholds] = None):
        self.sched = scheduler
        self.source = source if source is not None \
            else SchedulerSignals(scheduler)
        # Defaults layer through Thresholds.resolve(): env var >
        # measured capacity record (ROUNDTABLE_GATEWAY_CAPACITY_FILE)
        # > built-in. Explicit ctor args still win over everything.
        th = thresholds if thresholds is not None \
            else Thresholds.resolve()
        self.thresholds = th
        self.max_inflight = max_inflight if max_inflight is not None \
            else th.max_inflight
        self.max_queue_depth = max_queue_depth \
            if max_queue_depth is not None else th.max_queue_depth
        self.page_headroom = page_headroom if page_headroom is not None \
            else th.page_headroom
        self.p95_slo_s = p95_slo_s if p95_slo_s is not None \
            else th.p95_slo_s
        self.retry_after_s = retry_after_s if retry_after_s is not None \
            else th.retry_after_s
        self._ttfts: list[float] = []   # bounded window, newest last
        self.admitted = 0
        self.shed = 0
        self.expired = 0
        self.queued = 0
        # SLO burn-rate monitor (ISSUE 20): every TTFT sample and shed
        # this controller sees also feeds the multiwindow burn rate
        # against the capacity-record SLO — the PR-19 frontier becomes
        # a live alerting baseline instead of a one-shot bench artifact.
        self.slo = tracing.SloBurnMonitor(p95_slo_s=self.p95_slo_s,
                                          source=th.source)

    # -- accounting (single writer for counters + registry) --

    def _count(self, outcome: str, reason: str,
               replica: Optional[str] = None) -> None:
        setattr(self, outcome, getattr(self, outcome) + 1)
        if replica is not None:
            telemetry.inc(f"roundtable_gateway_{outcome}_total",
                          reason=reason, replica=replica)
        else:
            telemetry.inc(f"roundtable_gateway_{outcome}_total",
                          reason=reason)
        if outcome == "shed":
            # Sheds are budget-burning events regardless of the SLO
            # being armed — both burn windows see them.
            self.slo.note_shed()

    def note_ttft(self, seconds: float, trace_id: str = "") -> None:
        """One writer for every TTFT surface: the p95 shed window, the
        roundtable_gateway_ttft_seconds histogram (with a trace-id
        exemplar so a bad bucket links to a concrete trace), and the
        SLO burn monitor."""
        self._ttfts.append(seconds)
        if len(self._ttfts) > 256:
            del self._ttfts[:-256]
        telemetry.observe("roundtable_gateway_ttft_seconds", seconds,
                          exemplar=trace_id or None)
        self.slo.note_ttft(seconds, trace_id)

    def p95_ttft(self) -> Optional[float]:
        if len(self._ttfts) < 8:
            return None
        ordered = sorted(self._ttfts)
        return ordered[min(int(len(ordered) * 0.95),
                           len(ordered) - 1)]

    # -- the decision ladder --

    def decide(self, *, rows: int, inflight: int,
               deadline_s: Optional[float] = None,
               priority: str = "normal",
               adapters: Optional[list] = None) -> Decision:
        """The decision ladder, wrapped in an `admission` span (armed
        telemetry only) recording the signal that decided — the trace
        waterfall's first stage. Callers put the request trace on the
        thread stack (telemetry.attached) so the span parents to it."""
        if not telemetry.ACTIVE:
            return self._decide(rows=rows, inflight=inflight,
                                deadline_s=deadline_s,
                                priority=priority, adapters=adapters)
        with telemetry.span("admission", rows=rows, inflight=inflight,
                            priority=priority) as sp:
            dec = self._decide(rows=rows, inflight=inflight,
                               deadline_s=deadline_s,
                               priority=priority, adapters=adapters)
            sp.set_attr("admit", dec.admit)
            sp.set_attr("signal", dec.reason)
            if not dec.admit:
                sp.set_attr("status", dec.status)
            return dec

    def _decide(self, *, rows: int, inflight: int,
                deadline_s: Optional[float] = None,
                priority: str = "normal",
                adapters: Optional[list] = None) -> Decision:
        src = self.source
        scale = _PRIORITY_SCALE.get(priority, 1.0)

        # 1. Drain / pause: finish in-flight, refuse new (503 — the
        # gate reopens; clients retry the same pod after Retry-After).
        # Fleet sources only report this when EVERY live replica is
        # closed — one rolling replica never 503s the front door.
        drain = src.drain_state()
        if drain is not None:
            return self._shed(drain, 503)

        # 2. Dead engine: the supervisor exhausted its restart budget —
        # (fleet: on EVERY replica) nothing this pod serves can
        # succeed (503, longer backoff).
        if src.dead_reason() is not None:
            return self._shed("engine_dead", 503,
                              retry_after=4 * self.retry_after_s)

        # 3. Spent deadline: the client's SLO budget is already gone —
        # admitting would burn a slot to produce a guaranteed timeout.
        if deadline_s is not None and deadline_s <= 0:
            self._count("expired", "deadline_expired")
            return Decision(False, "deadline_expired", 408,
                            self.retry_after_s)

        # 4. Hard caps, priority-scaled: low-priority traffic sheds at
        # half the cap so paid/interactive traffic keeps headroom.
        if inflight >= max(int(self.max_inflight * scale), 1):
            return self._shed("inflight_cap", 429)
        depth = src.queue_depth()
        if depth >= max(int(self.max_queue_depth * scale), 1):
            return self._shed("queue_full", 429)
        # Below the cap but behind queued work: the request admits but
        # parks in the scheduler's FIFO — surfaced on the Decision so
        # note_admitted() counts it under `queued`.
        will_queue = depth > 0

        # 5. KV page pressure: a paged pool within the headroom band
        # AND no host-RAM spill tier to evacuate into means the next
        # admission trades page faults for collapse — shed instead.
        if src.kv_pressure(self.page_headroom):
            return self._shed("kv_pressure", 429)

        # 6. Adapter residency: every LoRA store slot referenced by
        # live rows — retirement frees refs; back off rather than park
        # in the scheduler queue behind an unknown-duration round.
        if (adapters and any(a is not None for a in adapters)
                and src.adapters_busy(adapters)):
            return self._shed("adapters_busy", 429)

        # 7. Soft SLO: the gateway's own p95 TTFT window over target —
        # shed everything except high priority until latency recovers.
        slo = self.p95_slo_s
        if slo and priority != "high":
            p95 = self.p95_ttft()
            if p95 is not None and p95 > slo:
                return self._shed("slo_p95", 429)

        return Decision(True, "ok", queued=will_queue)

    def note_admitted(self, queued: bool = False,
                      replica: Optional[str] = None) -> None:
        """Counted by the gateway AFTER submit_async succeeds — the
        scheduler can still refuse between decide() and submit (a
        drain racing the request), and that lands under `shed`, so the
        two counters never both claim one request. `queued` marks an
        admission that parked behind a nonempty scheduler queue
        (Decision.queued) — the queue path's own lockstep counter.
        `replica` labels the series when a router placed the stream
        (single-engine output stays byte-identical)."""
        self._count("admitted", "ok", replica=replica)
        if queued:
            self._count("queued", "behind_queue", replica=replica)

    def note_shed(self, reason: str,
                  replica: Optional[str] = None) -> None:
        """Submit-time refusals (scheduler raced the decision)."""
        self._count("shed", reason, replica=replica)

    def _shed(self, reason: str, status: int,
              retry_after: Optional[float] = None) -> Decision:
        self._count("shed", reason)
        return Decision(False, reason, status,
                        retry_after if retry_after is not None
                        else self.retry_after_s)

    def describe(self) -> dict:
        return {
            "admitted": self.admitted,
            "shed": self.shed,
            "expired": self.expired,
            "queued": self.queued,
            "p95_ttft_s": self.p95_ttft(),
            "caps": {
                "max_inflight": self.max_inflight,
                "max_queue_depth": self.max_queue_depth,
                "page_headroom": self.page_headroom,
                "p95_slo_s": self.p95_slo_s,
                "source": self.thresholds.source,
                "record_path": self.thresholds.record_path,
            },
            "slo": self.slo.describe(),
        }


def make_budget(deadline_s: Optional[float]):
    """The scheduler-facing deadline: a Budget root bounded by the
    client's remaining SLO (None = unbounded). 0 is born expired —
    submit_async fails it fast with DeadlineExpired."""
    if deadline_s is None:
        return None
    return deadlines.Budget.root(max(deadline_s, 0.0), rung="turn")


def clock() -> float:
    return time.monotonic()
