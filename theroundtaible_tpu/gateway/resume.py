"""Crash-consistent stream resume: the gateway's intent journal plus
the reconnect ladder.

The SessionJournal (engine/session_journal.py) makes COMMITTED turns
durable; what it cannot answer after a kill -9 is "which HTTP streams
were open, serving what". This module journals that intent: one
fsynced JSONL record per admitted stream (stream id, session, knights,
prompts, budget, the turn number it will commit as) — written BEFORE
the first token, so the record on disk always covers every stream a
client could hold an event id for.

Reconnect ladder for `GET /v1/streams/<id>` with `Last-Event-ID`:

1. **Live stream** (same process): attach to its in-memory history at
   the client's watermark — tokens after the id flow, nothing repeats.
2. **Committed turn** (post-restart, turn present in the session
   journal): the round finished before the crash — serve the remaining
   tokens straight from the journal record's `produced` ids and close.
3. **Uncommitted turn** (post-restart, crash mid-round): re-submit the
   recorded prompts greedily. `--resume` already replayed every
   committed turn into KV, so the prefix cache makes the re-prefill
   cheap and greedy decoding regenerates the IDENTICAL token stream;
   the client's watermark skips everything it already saw.

All three legs deliver exactly the tokens after the last-seen event:
zero loss, zero duplication — the chaos acceptance (GATEWAY_r16.json)
measures this end to end.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Any, Optional

INTENT_FILE = "gateway-streams.jsonl"


class StreamIntentJournal:
    """Append-only fsynced record of admitted streams (torn-tail
    tolerant, the SessionJournal WAL rule)."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.path = self.root / INTENT_FILE
        self._lock = threading.Lock()

    def record(self, stream_id: str, *, session: str,
               knights: list[str], prompts: list[Any], turn: int,
               max_new: int, deadline_s: Optional[float] = None,
               kind: str = "native") -> Optional[dict]:
        rec = {
            "v": 1,
            "stream": stream_id,
            "session": session,
            "knights": list(knights),
            "prompts": list(prompts),
            "turn": turn,
            "max_new": max_new,
            "deadline_s": deadline_s,
            "kind": kind,
        }
        try:
            with self._lock, open(self.path, "a",
                                  encoding="utf-8") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # Durability < availability (the journal rule): the stream
            # serves; it just won't survive a crash.
            return None
        return rec

    def load(self) -> dict[str, dict]:
        """stream_id -> intent record, last-writer-wins, stopping at
        the first torn line."""
        out: dict[str, dict] = {}
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail from the crash
                    if not isinstance(rec, dict) or "stream" not in rec:
                        break
                    out[rec["stream"]] = rec
        except OSError:
            return out
        return out


def committed_rows(journal, session: str,
                   turn: int) -> Optional[list[dict]]:
    """The journal record of `session`'s turn `turn`, if that round
    committed before the crash (reconnect ladder leg 2). Returns the
    record's rows ({"knight", "produced", ...}) or None."""
    if journal is None:
        return None
    for rec in journal.turns(session):
        if rec.get("turn") == turn:
            return rec.get("rows", [])
    return None
