"""Crash-consistent stream resume: the gateway's intent journal plus
the reconnect ladder.

The SessionJournal (engine/session_journal.py) makes COMMITTED turns
durable; what it cannot answer after a kill -9 is "which HTTP streams
were open, serving what". This module journals that intent: one
fsynced JSONL record per admitted stream (stream id, session, knights,
prompts, budget, the turn number it will commit as) — written BEFORE
the first token, so the record on disk always covers every stream a
client could hold an event id for.

Reconnect ladder for `GET /v1/streams/<id>` with `Last-Event-ID`:

1. **Live stream** (same process): attach to its in-memory history at
   the client's watermark — tokens after the id flow, nothing repeats.
2. **Committed turn** (post-restart, turn present in the session
   journal): the round finished before the crash — serve the remaining
   tokens straight from the journal record's `produced` ids and close.
3. **Uncommitted turn** (post-restart, crash mid-round): re-submit the
   recorded prompts — with the recorded adapters — greedily. `--resume`
   already replayed every committed turn into KV, so the prefix cache
   makes the re-prefill cheap and greedy decoding regenerates the
   IDENTICAL token stream; the client's watermark skips everything it
   already saw. A stream whose intent recorded temperature > 0 CANNOT
   regenerate identically (sampling), so leg 3 refuses it with 409
   `nondeterministic_stream` rather than splice a different stream
   onto the client's watermark.

All three legs deliver exactly the tokens after the last-seen event:
zero loss, zero duplication — the chaos acceptance (GATEWAY_r16.json)
measures this end to end.
"""

from __future__ import annotations

import json
import os
import threading
from pathlib import Path
from typing import Any, Optional

INTENT_FILE = "gateway-streams.jsonl"


class StreamIntentJournal:
    """Append-only fsynced record of admitted streams (torn-tail
    tolerant, the SessionJournal WAL rule)."""

    def __init__(self, root: str | os.PathLike):
        self.root = Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.path = self.root / INTENT_FILE
        self._lock = threading.Lock()

    def record(self, stream_id: str, *, session: str,
               knights: list[str], prompts: list[Any], turn: int,
               max_new: int, deadline_s: Optional[float] = None,
               kind: str = "native",
               adapters: Optional[list] = None,
               temperature: float = 0.0,
               trace: Optional[str] = None) -> Optional[dict]:
        # adapters + temperature are part of the intent (review fix):
        # leg-3 resume re-submits from this record, and replaying with
        # different adapters — or regenerating a sampled stream at all
        # — would splice a DIFFERENT token stream onto the client's
        # watermark instead of the byte-identical continuation.
        # `trace` (ISSUE 20) is the request's trace id: a post-crash
        # reconnect's restore leg rejoins the ORIGINAL trace, so one
        # client request stays one stitched trace across kill -9.
        rec = {
            "v": 1,
            "stream": stream_id,
            "session": session,
            "knights": list(knights),
            "prompts": list(prompts),
            "turn": turn,
            "max_new": max_new,
            "deadline_s": deadline_s,
            "kind": kind,
            "adapters": list(adapters) if adapters is not None else None,
            "temperature": temperature,
            "trace": trace,
        }
        try:
            with self._lock, open(self.path, "a",
                                  encoding="utf-8") as f:
                f.write(json.dumps(rec, separators=(",", ":")) + "\n")
                f.flush()
                os.fsync(f.fileno())
        except OSError:
            # Durability < availability (the journal rule): the stream
            # serves; it just won't survive a crash.
            return None
        return rec

    def compact(self, records: dict[str, dict]) -> bool:
        """Atomically rewrite the journal to exactly `records` (write
        tmp, fsync, rename) — the unbounded-growth fix: the gateway
        periodically drops records whose reconnect story the session
        journal already covers. Returns False (journal unchanged) on
        I/O failure, same durability-<-availability rule as record()."""
        tmp = self.path.with_suffix(".compact.tmp")
        try:
            with self._lock:
                with open(tmp, "w", encoding="utf-8") as f:
                    for rec in records.values():
                        f.write(json.dumps(rec, separators=(",", ":"))
                                + "\n")
                    f.flush()
                    os.fsync(f.fileno())
                os.replace(tmp, self.path)
        except OSError:
            try:
                tmp.unlink(missing_ok=True)
            except OSError:
                pass
            return False
        return True

    def load(self) -> dict[str, dict]:
        """stream_id -> intent record, last-writer-wins, stopping at
        the first torn line."""
        out: dict[str, dict] = {}
        try:
            with open(self.path, "r", encoding="utf-8") as f:
                for line in f:
                    line = line.strip()
                    if not line:
                        continue
                    try:
                        rec = json.loads(line)
                    except json.JSONDecodeError:
                        break  # torn tail from the crash
                    if not isinstance(rec, dict) or "stream" not in rec:
                        break
                    out[rec["stream"]] = rec
        except OSError:
            return out
        return out


def committed_rows(journal, session: str,
                   turn: int) -> Optional[list[dict]]:
    """The journal record of `session`'s turn `turn`, if that round
    committed before the crash (reconnect ladder leg 2). Returns the
    record's rows ({"knight", "produced", ...}) or None."""
    if journal is None:
        return None
    for rec in journal.turns(session):
        if rec.get("turn") == turn:
            return rec.get("rows", [])
    return None
