"""Benchmark — BASELINE.md measured config 2: 3-knight × 5-round discuss.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

This measures the NORTH-STAR metric (BASELINE.md: "3-knight × 5-round
`discuss` wall-clock ... at wall-clock parity with Ollama on a single
A100") end to end through the REAL orchestrator: context build, prompt
assembly, one batched device program per round over 3 persistent KV slots,
consensus parsing, session/chronicle writes. Only the consensus SCORES are
scripted (random-weight models can't emit the JSON block; the reference's
compute path is identical either way) — scores run 6,6,6,6 then 9.5 so the
discussion terminates exactly at round 5.

vs_baseline anchors to Ollama gemma-2b on A100 ≈ 120 tok/s decode: a
3-knight × 5-round discussion with ~160-token turns ≈ 15 × 160 / 120 ≈ 20 s
of pure decode, plus prefill ≈ a few seconds — call it 25 s of model time.
The reference itself publishes no numbers (BASELINE.md "published: {}").

Usage: python bench_discuss.py            (real chip; gemma-2b × 3 knights)
       ROUNDTABLE_BENCH_CPU=1 ...         (tiny model smoke test)
Same watchdog+retry child-process pattern as bench.py (the single-claim
TPU tunnel hangs rather than erroring while another process holds it).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

A100_OLLAMA_DISCUSS_WALL_S = 25.0  # derivation in module docstring

ATTEMPT_TIMEOUT_S = 420.0
MAX_ATTEMPTS = 2
RETRY_DELAY_S = 20.0

TOPIC = ("Should the session store move to an append-only event log "
         "before the apply pipeline lands?")


def child() -> int:
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.adapters.tpu_llm import TpuLlmAdapter
    from theroundtaible_tpu.core.orchestrator import run_discussion
    from theroundtaible_tpu.core.types import (ConsensusBlock, KnightConfig,
                                               RoundtableConfig, RulesConfig)
    from theroundtaible_tpu.utils.metrics import aggregate_engine_stats

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    max_new = 48 if on_cpu else 160
    rounds = 5

    # Sampler provenance (ISSUE 3 satellite): config 2 records WHICH
    # sampler path its decode ran — greedy (the temp=0 default), the
    # sort-free candidate-pool fast path, or the exact full-vocab sort —
    # so the unmeasured sort-free sampler gets an attributable number in
    # the same window. Flip the env knobs to measure the sampled paths:
    # ROUNDTABLE_BENCH_TEMPERATURE=0.7 [ROUNDTABLE_BENCH_TOP_P=0.95,
    # ROUNDTABLE_BENCH_TOP_K=40] turns the run sort-free;
    # ROUNDTABLE_BENCH_TOP_K>128 forces the sort fallback.
    temp = float(os.environ.get("ROUNDTABLE_BENCH_TEMPERATURE", "0.0"))
    top_p = float(os.environ.get("ROUNDTABLE_BENCH_TOP_P", "1.0"))
    top_k = int(os.environ.get("ROUNDTABLE_BENCH_TOP_K", "0"))
    from theroundtaible_tpu.engine.sampling import (SamplingParams,
                                                    sampler_mode)
    mode = sampler_mode([SamplingParams(temperature=temp, top_k=top_k,
                                        top_p=top_p)])

    real_parse = {"count": 0, "ok": 0, "seconds": 0.0}

    class ScriptedConsensusAdapter(TpuLlmAdapter):
        """Real engine serving; consensus SCORES scripted per round so the
        discussion terminates at exactly `rounds` rounds — but the real
        parse path is wall-clocked on every turn (VERDICT r2 weak #6):
        the model's raw output gets a canonical consensus JSON appended
        (the forced continuation a real checkpoint would emit) and runs
        through parse_consensus_from_response → ConsensusBlock
        validation, so extraction + repair + validation cost is INSIDE
        the measured wall. Only the resulting score is then overridden."""

        def parse_consensus(self, response, round_num):
            score = 9.5 if round_num >= rounds else 6.0
            forced = response + (
                '\n```json\n{"consensus_score": %s, "agrees_with": '
                '["Knight-A"], "pending_issues": [], "proposal": '
                '"benchmark proposal", "files_to_modify": %s}\n```\n'
                % (score, '["bench.md"]' if score >= 9 else "[]"))
            t0 = time.monotonic()
            parsed = super().parse_consensus(forced, round_num)
            real_parse["seconds"] += time.monotonic() - t0
            real_parse["count"] += 1
            if parsed is not None:
                real_parse["ok"] += 1
                # The scripted score ALWAYS wins (termination guarantee):
                # should the model's raw output ever contain its own
                # parseable consensus block, that block parses first and
                # its arbitrary score must not end the discussion early.
                parsed.consensus_score = score
                parsed.files_to_modify = (["bench.md"] if score >= 9
                                          else [])
                return parsed
            return ConsensusBlock(
                knight=self.name, round=round_num, consensus_score=score,
                agrees_with=[], pending_issues=[],
                proposal="benchmark proposal",
                files_to_modify=["bench.md"] if score >= 9 else [])

    adapter = ScriptedConsensusAdapter(
        "tpu-llm", {"model": model, "max_seq_len": max_seq, "num_slots": 4,
                    "sampling": {"temperature": temp, "top_k": top_k,
                                 "top_p": top_p,
                                 "max_new_tokens": max_new}})

    config = RoundtableConfig(
        version="1.0", project="bench", language="en",
        knights=[
            KnightConfig(name=f"Knight-{c}", adapter="tpu-llm",
                         capabilities=[], priority=i + 1)
            for i, c in enumerate("ABC")],
        rules=RulesConfig(max_rounds=rounds, consensus_threshold=9,
                          timeout_per_turn_seconds=300,
                          escalate_to_user_after=4, auto_execute=False,
                          parallel_rounds=True),
        chronicle="chronicle.md",
        adapter_config={"tpu-llm": {}},
    )

    with tempfile.TemporaryDirectory() as root:
        os.makedirs(os.path.join(root, ".roundtable", "sessions"))
        engine = adapter._get_engine()
        t_warm = time.monotonic()
        engine.warmup(max_prompt_tokens=max_seq - 256, batch_sizes=(1, 3))
        warmup_s = time.monotonic() - t_warm

        reporter = None
        if os.environ.get("ROUNDTABLE_BENCH_DEBUG"):
            from theroundtaible_tpu.commands.reporter import ConsoleReporter
            reporter = ConsoleReporter()
        t0 = time.monotonic()
        result = run_discussion(TOPIC, config, {"tpu-llm": adapter}, root,
                                read_source_code=False, reporter=reporter)
        wall = time.monotonic() - t0

        metrics_path = os.path.join(result.session_path, "metrics.json")
        metrics = json.loads(open(metrics_path).read())

    assert result.consensus, "scripted discussion must reach consensus"
    assert result.rounds == rounds

    totals = metrics["totals"]
    turns = [t for r in metrics["rounds"] for t in r["turns"]]
    agg = aggregate_engine_stats(
        type("T", (), {"engine": t["engine"]})() for t in turns)
    prefill = agg["prefill_tokens"]
    reused = agg["reused_tokens"]
    reuse_pct = 100.0 * reused / max(prefill + reused, 1)

    # The stable greedy metric key is unchanged; a sampled run (the env
    # knobs above) lands under a mode-suffixed key so the two never
    # collide in per-key dedup and each stays attributable.
    metric_key = f"discuss_wall_clock_3knight_{rounds}round[{model}]"
    if mode != "greedy":
        metric_key += f"[{mode}]"
    result_line = {
        "metric": metric_key,
        "value": round(wall, 2),
        "unit": "seconds",
        "vs_baseline": round(A100_OLLAMA_DISCUSS_WALL_S / max(wall, 1e-9),
                             3),
        "detail": {
            "rounds": result.rounds,
            "decode_tokens": agg["decode_tokens"],
            "decode_tps": agg["decode_tps"],
            "prefill_tokens": prefill,
            "reused_tokens": reused,
            "cache_reuse_pct": round(reuse_pct, 1),
            "warmup_s": round(warmup_s, 1),
            "engine_wall_s": totals.get("wall_s"),
            "platform": jax.devices()[0].platform,
            # Per-run sampler attribution: greedy / sort-free / sort
            # (engine/sampling.sampler_mode) + the knobs that chose it.
            "sampler": {"mode": mode, "temperature": temp,
                        "top_k": top_k, "top_p": top_p},
            # Scores are scripted (random weights can't emit the JSON
            # block) but the full parse→validate path ran inside the
            # wall on every turn via a forced continuation:
            "consensus": {
                "scripted_scores": True,
                "real_parse_turns": real_parse["count"],
                "real_parse_ok": real_parse["ok"],
                "real_parse_s": round(real_parse["seconds"], 4),
                # Emergent (unscripted) termination is proven hermetically
                # by tests/test_emergent_consensus.py: a constructed
                # checkpoint's DECODED output carries the consensus JSON
                # and the unmodified adapter+orchestrator terminate on the
                # parsed scores. Scripting here is purely a wall-clock
                # termination guarantee for random bench weights.
                "emergent_consensus_test": "tests/test_emergent_consensus.py",
            },
        },
    }
    # flush=True: the watchdog salvages a timeout-killed child's stdout,
    # which only works if the line left this process's buffer.
    print(json.dumps(result_line), flush=True)
    return 0


def main() -> int:
    from bench_common import run_watchdogged
    return run_watchdogged(os.path.abspath(__file__), [],
                           ATTEMPT_TIMEOUT_S, MAX_ATTEMPTS, RETRY_DELAY_S)


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
