"""Benchmark — BASELINE.md measured config 2: 3-knight × 5-round discuss.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline", "detail"}.

This measures the NORTH-STAR metric (BASELINE.md: "3-knight × 5-round
`discuss` wall-clock ... at wall-clock parity with Ollama on a single
A100") end to end through the REAL orchestrator: context build, prompt
assembly, one batched device program per round over 3 persistent KV slots,
consensus parsing, session/chronicle writes. Only the consensus SCORES are
scripted (random-weight models can't emit the JSON block; the reference's
compute path is identical either way) — scores run 6,6,6,6 then 9.5 so the
discussion terminates exactly at round 5.

vs_baseline anchors to Ollama gemma-2b on A100 ≈ 120 tok/s decode: a
3-knight × 5-round discussion with ~160-token turns ≈ 15 × 160 / 120 ≈ 20 s
of pure decode, plus prefill ≈ a few seconds — call it 25 s of model time.
The reference itself publishes no numbers (BASELINE.md "published: {}").

Usage: python bench_discuss.py            (real chip; gemma-2b × 3 knights)
       ROUNDTABLE_BENCH_CPU=1 ...         (tiny model smoke test)
       ROUNDTABLE_BENCH_OFFERED_LOAD=1 .. (offered-load sweep, ISSUE 4:
           K ∈ {1,2,4,8} concurrent scripted discussions through the
           continuous-batching session scheduler on ONE shared engine;
           emits one JSON line per K with aggregate decode tok/s,
           batch-occupancy %, p50/p95 turn latency, p50/p95 TTFT per
           round under concurrent admission (ISSUE 8 — served off a
           PAGED engine so ragged chunk-interleaved admission and the
           prefix cache are in play; ragged-path provenance embedded;
           ROUNDTABLE_RAGGED_ATTN=0 A/Bs the PR-4 prologue), and the
           scheduler's decision provenance embedded like int4_paths.
           ROUNDTABLE_BENCH_LOAD_KS=1,2,4 overrides the sweep.)
       ROUNDTABLE_BENCH_PREFIX_REUSE=1 .. (prefix-reuse sweep, ISSUE 7:
           the offered-load run twice on a PAGED engine — cross-session
           prefix cache ON then OFF — emitting one JSON line per mode
           with the reused-token fraction, prefill tok/s EFFECTIVE
           (total prompt tokens / prefill wall — what the user feels)
           vs COMPUTED (actually-prefilled tokens / wall — what the
           chip did), the memory ledger's shared-page split, and the
           estimated max resident sessions before refusal.)
       ROUNDTABLE_BENCH_SPEC_DECODE=1 ..  (speculation A/B, ISSUE 9: a
           scripted multi-round discussion served spec-ON then
           spec-OFF on one paged+ragged engine, in ONE record —
           accepted tok/s, acceptance rate BY ROUND (the transcript is
           the drafter's corpus, so later rounds should accept more),
           mean accepted tokens per verify dispatch, p50/p95 turn
           latency, and the greedy token-parity bit across modes.
           ROUNDTABLE_BENCH_SPEC_ROUNDS overrides the round count.)
       ROUNDTABLE_BENCH_LORA=1 ..        (multi-LoRA persona A/B,
           ISSUE 10: the same K-knight scripted load served (a) as K
           LoRA personas co-batched on ONE shared base engine vs (b)
           as a K-checkpoint fleet (one engine per distinct seed — the
           pre-LoRA diversity recipe), in ONE record — aggregate
           decode tok/s, resident HBM bytes per mode (the acceptance
           bar: shared-base K personas < 1.5x a single base vs ~Kx for
           the fleet), per-knight next-token distribution divergence
           (personas must be DIFFERENT models, measurably), the
           mixed-vs-alone token-parity bit, and the lora store/path
           provenance embedded. ROUNDTABLE_BENCH_LORA_K overrides K.)
       ROUNDTABLE_BENCH_KV_QUANT=1 ..    (quantized-KV-page A/B,
           ISSUE 11: the same pool BYTE budget served int8-KV-ON then
           bf16-OFF, in ONE record — max resident sessions before the
           allocator evicts (the acceptance bar: >= 1.8x at int8),
           scheduled decode tok/s, the ledger's resident-vs-logical
           byte split, the greedy token-parity bit across modes, the
           per-page-path dequant provenance (kernel vs XLA, with
           machine-readable fallback_reason), the quant-aware roofline
           block, and ROUNDTABLE_RECOMPILE_STRICT=1 green across the
           serve. On CPU the model is a head_dim=64 tiny-gemma variant
           (D=16's per-cell f32 scale overhead caps the page ratio at
           1.6x; serving head_dims amortize it — gemma-2b's D=256
           gives 1.97x). ROUNDTABLE_BENCH_KVQ_DTYPE=int4 A/Bs int4.)
       ROUNDTABLE_BENCH_RESTART=1 ..     (restart-under-load, ISSUE 12:
           K concurrent multi-round scripted sessions on one paged +
           host-offload engine, served fault-free then with ROLLING
           supervisor.restart() cycles fired mid-run (after rounds 1
           and 2) — ONE record with sessions recovered vs lost, the
           recovery wall per restart (quiesce → evacuate → rebuild →
           restore) and its p95, and the greedy token-parity bit vs
           the uninterrupted run: the across-restart KV restore is
           byte-identical exactly when later rounds' own-slot reuse
           produces the same tokens. ROUNDTABLE_BENCH_RESTART_N
           overrides the restart count.)
Same watchdog+retry child-process pattern as bench.py (the single-claim
TPU tunnel hangs rather than erroring while another process holds it).
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

A100_OLLAMA_DISCUSS_WALL_S = 25.0  # derivation in module docstring

ATTEMPT_TIMEOUT_S = 420.0
MAX_ATTEMPTS = 2
RETRY_DELAY_S = 20.0

TOPIC = ("Should the session store move to an append-only event log "
         "before the apply pipeline lands?")


def _registry_snapshot() -> dict:
    """Compact unified-registry snapshot for run-record embedding."""
    from theroundtaible_tpu.utils import telemetry
    return telemetry.REGISTRY.snapshot_compact()


def _perf_block() -> dict:
    """Perf-attribution block (ISSUE 6): roofline gauges, compile
    observatory summary, memory ledger, span overheads — every run
    record explains its own number."""
    from theroundtaible_tpu.utils import perfmodel
    return perfmodel.attribution_snapshot()


def offered_load_child() -> int:
    """Offered-load sweep (ISSUE 4 satellite): K concurrent 3-knight
    scripted discussions through ONE shared engine + session scheduler,
    for K in {1, 2, 4, 8}. Scores are scripted (random weights can't
    emit the consensus JSON — same stance as the main benchmark); the
    serving path is the REAL orchestrator → scheduler-routed adapter →
    continuously-batched engine."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import statistics
    import tempfile
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.adapters.tpu_llm import TpuLlmAdapter
    from theroundtaible_tpu.core.orchestrator import run_discussion
    from theroundtaible_tpu.core.types import (ConsensusBlock, KnightConfig,
                                               RoundtableConfig, RulesConfig)
    from theroundtaible_tpu.engine.scheduler import SessionScheduler

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    # Decode-representative turns (ISSUE 8): real discussion turns run
    # ~160 tokens (BASELINE.md); 32-token CPU turns made the sweep
    # prefill-dominated, which hid exactly the admission stall the
    # TTFT percentiles exist to measure.
    max_new = 96
    rounds = 2
    num_slots = 12  # up to 4 concurrent 3-knight sessions resident
    ks = [int(x) for x in os.environ.get(
        "ROUNDTABLE_BENCH_LOAD_KS", "1,2,4,8").split(",")]
    # Arrival stagger (ISSUE 8): offered load means sessions ARRIVE
    # over time — session i starts i*stagger seconds in, so later
    # sessions are LATE JOINERS admitted against a live decode batch
    # (the admission-stall shape the TTFT percentiles measure). 0
    # restores the PR-4 all-at-once burst.
    stagger_s = float(os.environ.get(
        "ROUNDTABLE_BENCH_LOAD_STAGGER_S", "1.0"))

    class Scripted(TpuLlmAdapter):
        """Real serving; scripted consensus scores terminate each
        discussion at exactly `rounds` rounds (random weights cannot
        emit the JSON block — bench_discuss's standing stance)."""

        def parse_consensus(self, response, round_num):
            score = 9.5 if round_num >= rounds else 6.0
            return ConsensusBlock(
                knight=self.name, round=round_num, consensus_score=score,
                agrees_with=[], pending_issues=[], proposal="bench",
                files_to_modify=["bench.md"] if score >= 9 else [])

    # Paged pool (ISSUE 8): the offered-load sweep measures the MODERN
    # serving shape — prefix cache + ragged chunk-interleaved admission
    # both ride the paged engines; ROUNDTABLE_RAGGED_ATTN=0 serves the
    # same sweep through the PR-4 prologue for A/B TTFT comparisons.
    engine_cfg = {"model": model, "max_seq_len": max_seq,
                  "num_slots": num_slots, "kv_layout": "paged",
                  # Contiguous-equal pool: the sweep HOLDS K sessions
                  # resident concurrently — the default half-budget
                  # pool would serve admission backpressure, not the
                  # scheduling behavior this sweep measures.
                  "num_pages": num_slots * max_seq // 128,
                  "sampling": {"temperature": 0.0,
                               "max_new_tokens": max_new}}

    def make_config():
        return RoundtableConfig(
            version="1.0", project="bench", language="en",
            knights=[KnightConfig(name=f"Knight-{c}", adapter="tpu-llm",
                                  capabilities=[], priority=i + 1)
                     for i, c in enumerate("ABC")],
            rules=RulesConfig(max_rounds=rounds, consensus_threshold=9,
                              timeout_per_turn_seconds=300,
                              escalate_to_user_after=4, auto_execute=False,
                              parallel_rounds=True),
            chronicle="chronicle.md", adapter_config={"tpu-llm": {}})

    base = Scripted("tpu-llm", engine_cfg)
    engine = base._get_engine()
    t_warm = time.monotonic()
    engine.warmup(max_prompt_tokens=max_seq - 256, batch_sizes=(1, 3))
    warmup_s = time.monotonic() - t_warm

    for k in ks:
        sched = SessionScheduler(engine, admit_hold_s=0.25)
        config = make_config()
        entries = []
        with tempfile.TemporaryDirectory() as root:
            os.makedirs(os.path.join(root, ".roundtable", "sessions"))

            session_errors = []

            def run_one(i, k=k, root=root, config=config, sched=sched):
                try:
                    time.sleep(i * stagger_s)
                    adapter = Scripted("tpu-llm", engine_cfg)
                    adapter.attach_scheduler(sched, session=f"k{k}s{i}")
                    # Disambiguator goes FIRST: slugify truncates topics
                    # at 50 chars, and same-slug concurrent sessions
                    # would share (and corrupt) one session directory.
                    topic = f"(load {k}.{i}) {TOPIC}"
                    t0 = time.monotonic()
                    result = run_discussion(topic, config,
                                            {"tpu-llm": adapter}, root,
                                            read_source_code=False)
                    entries.append((result, time.monotonic() - t0))
                except Exception as e:  # noqa: BLE001 — reported below
                    # A silently-dropped session would make the emitted
                    # throughput/occupancy line claim a K-session sweep
                    # that never happened — fail the run loud instead.
                    session_errors.append((i, e))

            t0 = time.monotonic()
            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(k)]
            for th in threads:
                th.start()

            # Late-join probe stream (ISSUE 8): fresh single-knight
            # sessions keep ARRIVING while the K discussions hold the
            # decode batch — the "new user hits a busy server" shape.
            # Their TTFT is the admission-stall number ragged
            # chunk-interleaved admission exists to move; the prologue
            # path serializes each probe's prefill against the live
            # batch and any concurrent admissions.
            probe_ttfts = []
            probe_errors = []
            probe_stop = threading.Event()

            def probe_loop(k=k, sched=sched):
                base = ("A new petitioner arrives at the castle and "
                        "lays out the matter before the court. ")
                i = 0
                while not probe_stop.is_set():
                    # ~400 fresh tokens per probe: a cold prefill (past
                    # any prefix-cache hit) is the admission stall under
                    # measurement.
                    prompt = (base * 16
                              + f" Petition {i} of load {k}: advise.")
                    try:
                        _texts, stats = sched.submit(
                            f"probe-k{k}-{i}",
                            [("petitioner", prompt)],
                            max_new_tokens=16, timeout_s=120.0)
                        tt = (stats.sched or {}).get("ttft_s")
                        if tt is not None:
                            probe_ttfts.append(tt)
                    except Exception as e:  # noqa: BLE001 — recorded
                        # A refused/timed-out probe IS a late-join
                        # datapoint (the record must not read "instant
                        # TTFT" when admission was saturated) — count
                        # it and keep probing.
                        probe_errors.append(type(e).__name__)
                        if len(probe_errors) >= 8:
                            break
                    i += 1
                    probe_stop.wait(0.25)

            prober = threading.Thread(target=probe_loop)
            prober.start()
            for th in threads:
                th.join()
            probe_stop.set()
            prober.join(timeout=130)
            wall = time.monotonic() - t0

            turn_walls, queue_waits, ttfts = [], [], []
            decode_tokens = 0
            occupancies = []
            for result, _sess_wall in entries:
                metrics = json.loads(open(os.path.join(
                    result.session_path, "metrics.json")).read())
                for r in metrics["rounds"]:
                    for t in r["turns"]:
                        turn_walls.append(t["wall_s"])
                        if t.get("queue_wait_s") is not None:
                            queue_waits.append(t["queue_wait_s"])
                        if t.get("batch_occupancy") is not None:
                            occupancies.append(t["batch_occupancy"])
                        if t.get("engine"):
                            decode_tokens += t["engine"].get(
                                "decode_tokens", 0)
                            # TTFT (ISSUE 8): submit → every row of the
                            # round sampled its first token, straight
                            # from the scheduler's sched stats — the
                            # admission-stall number ragged admission
                            # moves.
                            tt = (t["engine"].get("sched") or {}).get(
                                "ttft_s")
                            if tt is not None:
                                ttfts.append(tt)
        provenance = sched.describe()
        sched.close()
        if session_errors:
            raise RuntimeError(
                f"offered-load K={k}: {len(session_errors)}/{k} "
                f"session(s) failed: "
                + "; ".join(f"s{i}: {e}" for i, e in session_errors))
        assert len(entries) == k, f"K={k} ran only {len(entries)} sessions"
        assert all(r.consensus for r, _ in entries), \
            "every scripted discussion must reach consensus"
        turn_walls.sort()
        ttfts.sort()
        probe_ttfts.sort()

        def _pct_of(vals, p):
            if not vals:
                return 0.0
            idx = min(int(p / 100 * len(vals)), len(vals) - 1)
            return round(vals[idx], 3)

        def pct(p):
            return _pct_of(turn_walls, p)

        result_line = {
            "metric": f"offered_load_discuss[{model}][K={k}]",
            "value": round(decode_tokens / max(wall, 1e-9), 2),
            "unit": "aggregate_decode_tok_s",
            "detail": {
                "sessions": k,
                "rounds_per_session": rounds,
                "arrival_stagger_s": stagger_s,
                "wall_s": round(wall, 2),
                "decode_tokens": decode_tokens,
                "p50_turn_s": pct(50),
                "p95_turn_s": pct(95),
                "turn_count": len(turn_walls),
                # Time-to-first-token per round under concurrent
                # admission — the headline number ragged
                # chunk-interleaved admission moves (ISSUE 8).
                "p50_ttft_s": _pct_of(ttfts, 50),
                "p95_ttft_s": _pct_of(ttfts, 95),
                "ttft_count": len(ttfts),
                # The late-join probe stream's TTFT — sessions arriving
                # at the already-busy batch (the headline this PR
                # moves; see probe_loop above). None (never 0.0) when
                # no probe completed — an empty stream must not read
                # as instant admission.
                "p50_ttft_late_join_s": (_pct_of(probe_ttfts, 50)
                                         if probe_ttfts else None),
                "p95_ttft_late_join_s": (_pct_of(probe_ttfts, 95)
                                         if probe_ttfts else None),
                "late_join_count": len(probe_ttfts),
                "late_join_errors": probe_errors,
                "queue_wait_mean_s": (
                    round(statistics.mean(queue_waits), 3)
                    if queue_waits else 0.0),
                "batch_occupancy_mean": (
                    round(statistics.mean(occupancies), 2)
                    if occupancies else 0.0),
                "batch_occupancy_pct": round(
                    100.0 * provenance["occupancy_mean"]
                    / max(num_slots, 1), 1),
                "warmup_s": round(warmup_s, 1),
                "platform": jax.devices()[0].platform,
                # Scheduler decision provenance embedded in the run
                # record, the int4_paths pattern (ISSUE 4).
                "scheduler": {kk: vv for kk, vv in provenance.items()
                              if kk != "events"},
                # Ragged-path provenance (ISSUE 8): dispatch counts and
                # fallback reasons, so the TTFT numbers are attributable
                # to the mixed-dispatch path (or its absence).
                "ragged": engine.ragged_describe(),
                "kv_layout": "paged",
                # Unified-registry snapshot (ISSUE 5): the same
                # occupancy/fallback/hang counters fleet_health reads,
                # frozen into the run record.
                "telemetry": _registry_snapshot(),
                "perf": _perf_block(),
            },
        }
        print(json.dumps(result_line), flush=True)
    return 0


def late_join_child() -> int:
    """Late-join TTFT A/B (ISSUE 8 acceptance): K fresh sessions submit
    while a resident session is DEEP IN DECODE — the admission-stall
    scenario ragged chunk-interleaved admission exists to kill — served
    twice on one paged config, ragged ON then OFF (the
    prefix_reuse_child on/off pattern), so the record carries the
    measured p50/p95 TTFT delta, not a projection. Direct scheduler
    submissions (no orchestrator): the measurement is the scheduler's
    admission path itself. Emits ONE JSON line with both modes, the
    deltas, greedy token parity across modes, and the ragged-path
    provenance (dispatch counts, fallback reasons) embedded."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.scheduler import SessionScheduler

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    k = int(os.environ.get("ROUNDTABLE_BENCH_LATE_JOIN_K", "3"))
    bg_tokens = 256
    join_new = 48
    cfg = get_model_config(model, max_seq_len=max_seq)
    kw = {}
    if on_cpu:
        # Tests/CI expose 8 virtual devices; tiny-gemma's heads don't
        # partition an 8-way model axis, which would (correctly)
        # decline the kernel — measure the kernel path.
        kw["mesh_shape"] = {"data": 1, "model": 1}

    joiner_prompt = ("A new petitioner arrives at the castle and lays "
                     "out the matter before the court in great detail. "
                     * 16)

    def run_mode(ragged: bool) -> dict:
        eng = InferenceEngine(
            cfg, num_slots=k + 2, kv_layout="paged",
            num_pages=(k + 2) * max_seq // 128, ragged_attn=ragged,
            **kw)
        warm_s = eng.warmup(max_prompt_tokens=512, batch_sizes=(1, 2))
        sched = SessionScheduler(eng)
        results: dict = {}
        errors: list = []

        def background():
            try:
                results["bg"] = sched.submit(
                    "bg", [("scribe", "The scribe recounts the history "
                                      "of the order at great length.")],
                    max_new_tokens=bg_tokens)
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append(("bg", e))

        def joiner(i):
            try:
                while not sched._active:
                    time.sleep(0.005)
                time.sleep(0.15 * i)
                results[f"j{i}"] = sched.submit(
                    f"j{i}", [("petitioner",
                               joiner_prompt + f" Petition {i}.")],
                    max_new_tokens=join_new)
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append((f"j{i}", e))

        threads = [threading.Thread(target=background)] + [
            threading.Thread(target=joiner, args=(i,)) for i in range(k)]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=300)
        wall = time.monotonic() - t0
        if errors:
            raise RuntimeError(f"late-join mode ragged={ragged}: "
                               + "; ".join(f"{s}: {e}"
                                           for s, e in errors))
        ttfts = sorted(results[f"j{i}"][1].sched["ttft_s"]
                       for i in range(k))
        provenance = sched.describe()
        sched.close()

        def pct(p):
            idx = min(int(p / 100 * len(ttfts)), len(ttfts) - 1)
            return round(ttfts[idx], 3)

        return {
            "ttfts_s": ttfts, "p50_ttft_s": pct(50),
            "p95_ttft_s": pct(95), "wall_s": round(wall, 2),
            "warmup_s": round(warm_s, 1),
            "texts": {s: results[s][0] for s in results},
            "ragged": eng.ragged_describe(),
            "scheduler": {kk: vv for kk, vv in provenance.items()
                          if kk != "events"},
        }

    on = run_mode(True)
    off = run_mode(False)
    parity = on.pop("texts") == off.pop("texts")
    result_line = {
        "metric": f"late_join_ttft[{model}][K={k}]",
        "value": on["p95_ttft_s"],
        "unit": "p95_ttft_s_ragged_on",
        "detail": {
            "late_joiners": k,
            "bg_decode_tokens": bg_tokens,
            "ragged_on": on,
            "prologue": off,
            "p95_ttft_improvement_s": round(
                off["p95_ttft_s"] - on["p95_ttft_s"], 3),
            "p50_ttft_improvement_s": round(
                off["p50_ttft_s"] - on["p50_ttft_s"], 3),
            # Greedy outputs must not depend on the admission path —
            # the kill-switch byte-identity acceptance, measured here.
            "token_parity_on_vs_off": parity,
            "platform": jax.devices()[0].platform,
            "telemetry": _registry_snapshot(),
        },
    }
    print(json.dumps(result_line), flush=True)
    return 0


def spec_decode_child() -> int:
    """Speculation A/B (ISSUE 9 acceptance): a scripted multi-round
    discussion — each round's turn prompt carries the WHOLE transcript
    so far, the roundtable shape that makes self-drafting work — served
    twice on one paged+ragged config, speculation ON then OFF (the
    late_join_child on/off pattern). Emits ONE JSON line with both
    modes, acceptance rate by round (the transcript is the drafter's
    corpus: later rounds should accept more), mean accepted tokens per
    verify dispatch, accepted tok/s, p50/p95 turn latency, the greedy
    token-parity bit across modes, and the spec/ragged provenance
    embedded. One session serves at a time, so accepted-per-dispatch is
    exact: each verify dispatch carries exactly one row."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.scheduler import SessionScheduler

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    rounds = int(os.environ.get("ROUNDTABLE_BENCH_SPEC_ROUNDS", "4"))
    knights = 2
    max_new = 48 if on_cpu else 64
    cfg = get_model_config(model, max_seq_len=max_seq)
    kw = {}
    if on_cpu:
        # Tests/CI expose 8 virtual devices; tiny-gemma's heads don't
        # partition an 8-way model axis — measure the kernel path.
        kw["mesh_shape"] = {"data": 1, "model": 1}

    def pct(xs, p):
        xs = sorted(xs)
        return round(xs[min(int(p / 100 * len(xs)), len(xs) - 1)], 3)

    def run_mode(spec: bool) -> dict:
        eng = InferenceEngine(
            cfg, num_slots=4, kv_layout="paged",
            num_pages=4 * max_seq // 128, spec_decode=spec, **kw)
        warm_s = eng.warmup(max_prompt_tokens=512, batch_sizes=(1, 2))
        sched = SessionScheduler(eng)
        transcript = ("The roundtable convenes to score the proposal. "
                      "Each knight quotes the proposal verbatim before "
                      "scoring it. ")
        by_round = []
        turn_walls: list[float] = []
        texts: list[str] = []
        dec_tok = 0
        dec_sec = 0.0
        try:
            for rnd in range(rounds):
                d0, a0 = eng._spec_drafted, eng._spec_accepted
                v0 = eng._spec_dispatches
                r_tok, r_sec = 0, 0.0
                for k in range(knights):
                    prompt = (transcript
                              + f"\nKnight {k} now speaks in turn: ")
                    t0 = time.monotonic()
                    txts, stats = sched.submit(
                        "bench", [(f"knight{k}", prompt)],
                        max_new_tokens=max_new)
                    turn_walls.append(time.monotonic() - t0)
                    texts.append(txts[0])
                    transcript += f"\nKnight {k}: {txts[0]}"
                    r_tok += stats.decode_tokens
                    r_sec += stats.decode_seconds
                dec_tok += r_tok
                dec_sec += r_sec
                dd = eng._spec_drafted - d0
                da = eng._spec_accepted - a0
                dv = eng._spec_dispatches - v0
                by_round.append({
                    "round": rnd,
                    "drafted": dd, "accepted": da,
                    "verify_dispatches": dv,
                    "acceptance_rate": (round(da / dd, 3) if dd
                                        else None),
                    "accepted_tok_s": (round(r_tok / r_sec, 1)
                                       if r_sec else None),
                })
            info = eng.spec_describe()
            sched_d = sched.describe()
        finally:
            sched.close()
        disp = info["verify_dispatches"]
        return {
            "spec": info,
            "by_round": by_round,
            # Tokens COMMITTED per verify dispatch: the guaranteed 1
            # (correction/bonus) plus every accepted draft — exact
            # here because each dispatch carries one row.
            "mean_accepted_tokens_per_verify_dispatch": (
                round(1.0 + info["accepted_tokens"] / disp, 3)
                if disp else None),
            "accepted_tok_s": (round(dec_tok / dec_sec, 1)
                               if dec_sec else None),
            "decode_tokens": dec_tok,
            "p50_turn_s": pct(turn_walls, 50),
            "p95_turn_s": pct(turn_walls, 95),
            "warmup_s": round(warm_s, 1),
            "texts": texts,
            "ragged": eng.ragged_describe(),
            "scheduler": {k: v for k, v in sched_d.items()
                          if k != "events"},
        }

    on = run_mode(True)
    off = run_mode(False)
    parity = on.pop("texts") == off.pop("texts")
    result_line = {
        "metric": f"spec_decode[{model}][rounds={rounds}]",
        "value": on["mean_accepted_tokens_per_verify_dispatch"],
        "unit": "accepted_tokens_per_verify_dispatch",
        "detail": {
            "rounds": rounds, "knights": knights,
            "max_new_tokens": max_new,
            "spec_on": on,
            "spec_off": off,
            "accepted_tok_s_speedup": (
                round(on["accepted_tok_s"] / off["accepted_tok_s"], 3)
                if on["accepted_tok_s"] and off["accepted_tok_s"]
                else None),
            # Greedy outputs must not depend on speculation — the
            # kill-switch byte-identity acceptance, measured here.
            "token_parity_on_vs_off": parity,
            "platform": jax.devices()[0].platform,
            "telemetry": _registry_snapshot(),
            "perf": _perf_block(),
        },
    }
    print(json.dumps(result_line), flush=True)
    return 0


def prefix_reuse_child() -> int:
    """Prefix-reuse sweep (ISSUE 7 satellite): the K-session scripted
    discussion load served twice on ONE paged-engine config — with the
    cross-session prefix cache on, then off — so the run record carries
    the reuse the radix tree actually delivered, not a projection.
    Recorded per mode: reused-token fraction, effective vs computed
    prefill tok/s, shared/exclusive page split, and the estimated max
    resident sessions before admission refusal (pool pages / per-session
    exclusive footprint — the capacity multiplier the tentpole claims)."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import statistics
    import tempfile
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.adapters.tpu_llm import TpuLlmAdapter
    from theroundtaible_tpu.core.orchestrator import run_discussion
    from theroundtaible_tpu.core.types import (ConsensusBlock, KnightConfig,
                                               RoundtableConfig, RulesConfig)
    from theroundtaible_tpu.engine.scheduler import SessionScheduler

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    max_new = 32 if on_cpu else 96
    rounds = 2
    num_slots = 12
    k = int(os.environ.get("ROUNDTABLE_BENCH_REUSE_K", "3"))
    # Arrival stagger between sessions: simultaneous (lockstep) arrivals
    # would admit every session before any peer COMMITS, so the index
    # would have nothing to serve — production arrivals are a process in
    # time, and the stagger is what lets session i+1 match the pages
    # session i just committed.
    stagger_s = float(os.environ.get(
        "ROUNDTABLE_BENCH_REUSE_STAGGER_S", "2.0" if on_cpu else "5.0"))

    class Scripted(TpuLlmAdapter):
        def parse_consensus(self, response, round_num):
            score = 9.5 if round_num >= rounds else 6.0
            return ConsensusBlock(
                knight=self.name, round=round_num, consensus_score=score,
                agrees_with=[], pending_issues=[], proposal="bench",
                files_to_modify=["bench.md"] if score >= 9 else [])

    def make_config():
        return RoundtableConfig(
            version="1.0", project="bench", language="en",
            knights=[KnightConfig(name=f"Knight-{c}", adapter="tpu-llm",
                                  capabilities=[], priority=i + 1)
                     for i, c in enumerate("ABC")],
            rules=RulesConfig(max_rounds=rounds, consensus_threshold=9,
                              timeout_per_turn_seconds=300,
                              escalate_to_user_after=4, auto_execute=False,
                              parallel_rounds=True),
            chronicle="chronicle.md", adapter_config={"tpu-llm": {}})

    for cache_on in (True, False):
        # Drop the previous mode's memoized engine BEFORE building this
        # one: the get_engine cache would otherwise pin BOTH full
        # engines (weights + paged pool) resident through the cache-off
        # half — ~2x HBM on a real chip, OOM risk during exactly the
        # run meant to be the fair comparison.
        from theroundtaible_tpu.engine import reset_engines
        reset_engines()
        engine_cfg = {"model": model, "max_seq_len": max_seq,
                      "num_slots": num_slots, "kv_layout": "paged",
                      "prefix_cache": cache_on, "kv_offload": cache_on,
                      "sampling": {"temperature": 0.0,
                                   "max_new_tokens": max_new}}
        base = Scripted("tpu-llm", engine_cfg)
        engine = base._get_engine()
        t_warm = time.monotonic()
        engine.warmup(max_prompt_tokens=max_seq - 256, batch_sizes=(1, 3))
        warmup_s = time.monotonic() - t_warm
        sched = SessionScheduler(engine, admit_hold_s=0.25)
        config = make_config()
        entries, session_errors = [], []
        with tempfile.TemporaryDirectory() as root:
            # One root PER SESSION: every discussion runs the IDENTICAL
            # topic (that is the whole point — the radix tree can only
            # match identical token prefixes, and serve fans one topic
            # into K sessions exactly like this), so the session-dir
            # slug dedup must come from the root, not a topic prefix
            # that would destroy the shared head.
            def run_one(i, root=root, config=config, sched=sched,
                        cache_on=cache_on):
                try:
                    sroot = os.path.join(root, f"s{i}")
                    os.makedirs(os.path.join(sroot, ".roundtable",
                                             "sessions"))
                    adapter = Scripted("tpu-llm", engine_cfg)
                    adapter.attach_scheduler(
                        sched, session=f"pr{int(cache_on)}s{i}")
                    t0 = time.monotonic()
                    result = run_discussion(TOPIC, config,
                                            {"tpu-llm": adapter}, sroot,
                                            read_source_code=False)
                    entries.append((result, time.monotonic() - t0))
                except Exception as e:  # noqa: BLE001 — reported below
                    session_errors.append((i, e))

            t0 = time.monotonic()
            threads = [threading.Thread(target=run_one, args=(i,))
                       for i in range(k)]
            for i, th in enumerate(threads):
                if i and stagger_s:
                    time.sleep(stagger_s)
                th.start()
            for th in threads:
                th.join()
            wall = time.monotonic() - t0

            prefill_tokens = reused = prefix_reused = 0
            prefill_seconds = 0.0
            for result, _w in entries:
                metrics = json.loads(open(os.path.join(
                    result.session_path, "metrics.json")).read())
                for r in metrics["rounds"]:
                    for t in r["turns"]:
                        eng_stats = t.get("engine") or {}
                        prefill_tokens += eng_stats.get(
                            "prefill_tokens", 0)
                        reused += eng_stats.get("reused_tokens", 0)
                        prefix_reused += eng_stats.get(
                            "prefix_reused_tokens", 0)
                        prefill_seconds += eng_stats.get(
                            "prefill_seconds", 0.0)
        provenance = sched.describe()
        sched.close()
        if session_errors:
            raise RuntimeError(
                f"prefix-reuse cache_on={cache_on}: "
                f"{len(session_errors)}/{k} session(s) failed: "
                + "; ".join(f"s{i}: {e}" for i, e in session_errors))
        assert len(entries) == k
        led = engine.kv.memory_ledger()
        total_prompt = prefill_tokens + reused
        # Max resident sessions before refusal: the pool's usable pages
        # over the mean EXCLUSIVE per-session footprint — sharing makes
        # the denominator shrink, which IS the capacity multiplier.
        excl_per_session = max(
            (led["exclusive_pages"]) / max(k, 1), 1e-9)
        max_resident_est = int(led["usable_pages"] // excl_per_session)
        result_line = {
            "metric": (f"prefix_reuse_discuss[{model}]"
                       f"[cache={'on' if cache_on else 'off'}]"),
            "value": round(reused / max(total_prompt, 1), 4),
            "unit": "reused_token_fraction",
            "detail": {
                "sessions": k,
                "rounds_per_session": rounds,
                "wall_s": round(wall, 2),
                "prompt_tokens_total": total_prompt,
                "prefill_tokens_computed": prefill_tokens,
                "reused_tokens": reused,
                "prefix_cache_reused_tokens": prefix_reused,
                "prefill_tok_s_effective": round(
                    total_prompt / max(prefill_seconds, 1e-9), 1),
                "prefill_tok_s_computed": round(
                    prefill_tokens / max(prefill_seconds, 1e-9), 1),
                "max_resident_sessions_est": max_resident_est,
                "memory_ledger": {kk: led[kk] for kk in (
                    "pages_in_use", "usable_pages", "shared_pages",
                    "exclusive_pages", "prefix_cache_pages")},
                "prefix_cache": (engine.prefix_cache.describe()
                                 if engine.prefix_cache is not None
                                 else None),
                "kv_offload": (engine.kv_offload.describe()
                               if engine.kv_offload is not None
                               else None),
                "warmup_s": round(warmup_s, 1),
                "platform": jax.devices()[0].platform,
                "scheduler": {kk: vv for kk, vv in provenance.items()
                              if kk != "events"},
                "telemetry": _registry_snapshot(),
                "perf": _perf_block(),
            },
        }
        print(json.dumps(result_line), flush=True)
        # Drop every strong reference to this mode's engine before the
        # next iteration's reset_engines(): loop locals outliving the
        # memo would keep both full engines resident — exactly the
        # 2x-HBM risk the reset exists to prevent.
        base = engine = sched = led = None  # noqa: F841
    return 0


def child() -> int:
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.adapters.tpu_llm import TpuLlmAdapter
    from theroundtaible_tpu.core.orchestrator import run_discussion
    from theroundtaible_tpu.core.types import (ConsensusBlock, KnightConfig,
                                               RoundtableConfig, RulesConfig)
    from theroundtaible_tpu.utils.metrics import aggregate_engine_stats

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    max_new = 48 if on_cpu else 160
    rounds = 5

    # Sampler provenance (ISSUE 3 satellite): config 2 records WHICH
    # sampler path its decode ran — greedy (the temp=0 default), the
    # sort-free candidate-pool fast path, or the exact full-vocab sort —
    # so the unmeasured sort-free sampler gets an attributable number in
    # the same window. Flip the env knobs to measure the sampled paths:
    # ROUNDTABLE_BENCH_TEMPERATURE=0.7 [ROUNDTABLE_BENCH_TOP_P=0.95,
    # ROUNDTABLE_BENCH_TOP_K=40] turns the run sort-free;
    # ROUNDTABLE_BENCH_TOP_K>128 forces the sort fallback.
    temp = float(os.environ.get("ROUNDTABLE_BENCH_TEMPERATURE", "0.0"))
    top_p = float(os.environ.get("ROUNDTABLE_BENCH_TOP_P", "1.0"))
    top_k = int(os.environ.get("ROUNDTABLE_BENCH_TOP_K", "0"))
    from theroundtaible_tpu.engine.sampling import (SamplingParams,
                                                    sampler_mode)
    mode = sampler_mode([SamplingParams(temperature=temp, top_k=top_k,
                                        top_p=top_p)])

    real_parse = {"count": 0, "ok": 0, "seconds": 0.0}

    class ScriptedConsensusAdapter(TpuLlmAdapter):
        """Real engine serving; consensus SCORES scripted per round so the
        discussion terminates at exactly `rounds` rounds — but the real
        parse path is wall-clocked on every turn (VERDICT r2 weak #6):
        the model's raw output gets a canonical consensus JSON appended
        (the forced continuation a real checkpoint would emit) and runs
        through parse_consensus_from_response → ConsensusBlock
        validation, so extraction + repair + validation cost is INSIDE
        the measured wall. Only the resulting score is then overridden."""

        def parse_consensus(self, response, round_num):
            score = 9.5 if round_num >= rounds else 6.0
            forced = response + (
                '\n```json\n{"consensus_score": %s, "agrees_with": '
                '["Knight-A"], "pending_issues": [], "proposal": '
                '"benchmark proposal", "files_to_modify": %s}\n```\n'
                % (score, '["bench.md"]' if score >= 9 else "[]"))
            t0 = time.monotonic()
            parsed = super().parse_consensus(forced, round_num)
            real_parse["seconds"] += time.monotonic() - t0
            real_parse["count"] += 1
            if parsed is not None:
                real_parse["ok"] += 1
                # The scripted score ALWAYS wins (termination guarantee):
                # should the model's raw output ever contain its own
                # parseable consensus block, that block parses first and
                # its arbitrary score must not end the discussion early.
                parsed.consensus_score = score
                parsed.files_to_modify = (["bench.md"] if score >= 9
                                          else [])
                return parsed
            return ConsensusBlock(
                knight=self.name, round=round_num, consensus_score=score,
                agrees_with=[], pending_issues=[],
                proposal="benchmark proposal",
                files_to_modify=["bench.md"] if score >= 9 else [])

    adapter = ScriptedConsensusAdapter(
        "tpu-llm", {"model": model, "max_seq_len": max_seq, "num_slots": 4,
                    "sampling": {"temperature": temp, "top_k": top_k,
                                 "top_p": top_p,
                                 "max_new_tokens": max_new}})

    config = RoundtableConfig(
        version="1.0", project="bench", language="en",
        knights=[
            KnightConfig(name=f"Knight-{c}", adapter="tpu-llm",
                         capabilities=[], priority=i + 1)
            for i, c in enumerate("ABC")],
        rules=RulesConfig(max_rounds=rounds, consensus_threshold=9,
                          timeout_per_turn_seconds=300,
                          escalate_to_user_after=4, auto_execute=False,
                          parallel_rounds=True),
        chronicle="chronicle.md",
        adapter_config={"tpu-llm": {}},
    )

    with tempfile.TemporaryDirectory() as root:
        os.makedirs(os.path.join(root, ".roundtable", "sessions"))
        engine = adapter._get_engine()
        t_warm = time.monotonic()
        engine.warmup(max_prompt_tokens=max_seq - 256, batch_sizes=(1, 3))
        warmup_s = time.monotonic() - t_warm

        reporter = None
        if os.environ.get("ROUNDTABLE_BENCH_DEBUG"):
            from theroundtaible_tpu.commands.reporter import ConsoleReporter
            reporter = ConsoleReporter()
        t0 = time.monotonic()
        result = run_discussion(TOPIC, config, {"tpu-llm": adapter}, root,
                                read_source_code=False, reporter=reporter)
        wall = time.monotonic() - t0

        metrics_path = os.path.join(result.session_path, "metrics.json")
        metrics = json.loads(open(metrics_path).read())

    assert result.consensus, "scripted discussion must reach consensus"
    assert result.rounds == rounds

    totals = metrics["totals"]
    turns = [t for r in metrics["rounds"] for t in r["turns"]]
    agg = aggregate_engine_stats(
        type("T", (), {"engine": t["engine"]})() for t in turns)
    prefill = agg["prefill_tokens"]
    reused = agg["reused_tokens"]
    reuse_pct = 100.0 * reused / max(prefill + reused, 1)

    # The stable greedy metric key is unchanged; a sampled run (the env
    # knobs above) lands under a mode-suffixed key so the two never
    # collide in per-key dedup and each stays attributable.
    metric_key = f"discuss_wall_clock_3knight_{rounds}round[{model}]"
    if mode != "greedy":
        metric_key += f"[{mode}]"
    result_line = {
        "metric": metric_key,
        "value": round(wall, 2),
        "unit": "seconds",
        "vs_baseline": round(A100_OLLAMA_DISCUSS_WALL_S / max(wall, 1e-9),
                             3),
        "detail": {
            "rounds": result.rounds,
            "decode_tokens": agg["decode_tokens"],
            "decode_tps": agg["decode_tps"],
            "prefill_tokens": prefill,
            "reused_tokens": reused,
            "cache_reuse_pct": round(reuse_pct, 1),
            "warmup_s": round(warmup_s, 1),
            "engine_wall_s": totals.get("wall_s"),
            "platform": jax.devices()[0].platform,
            # Per-run sampler attribution: greedy / sort-free / sort
            # (engine/sampling.sampler_mode) + the knobs that chose it.
            "sampler": {"mode": mode, "temperature": temp,
                        "top_k": top_k, "top_p": top_p},
            # Scores are scripted (random weights can't emit the JSON
            # block) but the full parse→validate path ran inside the
            # wall on every turn via a forced continuation:
            "consensus": {
                "scripted_scores": True,
                "real_parse_turns": real_parse["count"],
                "real_parse_ok": real_parse["ok"],
                "real_parse_s": round(real_parse["seconds"], 4),
                # Emergent (unscripted) termination is proven hermetically
                # by tests/test_emergent_consensus.py: a constructed
                # checkpoint's DECODED output carries the consensus JSON
                # and the unmodified adapter+orchestrator terminate on the
                # parsed scores. Scripting here is purely a wall-clock
                # termination guarantee for random bench weights.
                "emergent_consensus_test": "tests/test_emergent_consensus.py",
            },
            # Unified-registry snapshot (ISSUE 5, the int4_paths
            # pattern): every run record carries the window's counters.
            "telemetry": _registry_snapshot(),
            "perf": _perf_block(),
        },
    }
    # flush=True: the watchdog salvages a timeout-killed child's stdout,
    # which only works if the line left this process's buffer.
    print(json.dumps(result_line), flush=True)
    return 0




def lora_child() -> int:
    """Multi-LoRA persona A/B (ISSUE 10 acceptance): the same K-knight
    scripted multi-round load served two ways on the same base model —

    (a) SHARED BASE: one engine + K LoRA persona adapters, all K
        knights co-batched through the session scheduler (mixed-adapter
        decode segments on one resident base);
    (b) K-CHECKPOINT FLEET: K engines with distinct seeds (the
        pre-LoRA diversity recipe — each persona costs a full resident
        model), each serving its knight concurrently.

    Emits ONE JSON line with both modes: aggregate decode tok/s,
    resident HBM bytes (weights + KV + adapter stacks — the acceptance
    bar is shared-base < 1.5x a single base vs ~Kx for the fleet),
    per-knight NEXT-TOKEN DISTRIBUTION divergence (mean pairwise total
    variation on a probe prompt — personas must be measurably distinct
    models, not labels), the mixed-vs-alone token-parity bit, and the
    lora store/path provenance embedded (the int4_paths pattern)."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    import jax.numpy as jnp
    import numpy as np

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.scheduler import SessionScheduler

    on_cpu = jax.devices()[0].platform == "cpu"
    model = "tiny-gemma" if on_cpu else "gemma-2b-it"
    max_seq = 1024 if on_cpu else 2048
    k = int(os.environ.get("ROUNDTABLE_BENCH_LORA_K", "3"))
    rounds = 3
    max_new = 32 if on_cpu else 64
    kw = {}
    if on_cpu:
        kw["mesh_shape"] = {"data": 1, "model": 1}
    personas = {f"persona{i}": {"seed": 11 + i, "init_std": 0.5}
                for i in range(k)}
    lora_scale = 4.0
    checkpoint = ""
    lora_dir = os.environ.get("ROUNDTABLE_BENCH_LORA_DIR")
    if lora_dir:
        # TRAINED personas (bench_realweights --train-lora npzs) in
        # place of the random self-contained defaults — fitted at
        # apply scale 1.0 against the REALWEIGHTS tiny-llama
        # checkpoint, so this mode serves that exact base (A/B shapes
        # are model-shaped; a different base would reject them).
        import glob
        npzs = sorted(glob.glob(os.path.join(lora_dir, "*.npz")))[:k]
        ckpt = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            ".cache", "realweights_ckpt")
        if npzs and os.path.exists(os.path.join(ckpt, "config.json")):
            personas = {os.path.splitext(os.path.basename(f_))[0]:
                        {"path": f_} for f_ in npzs}
            k = len(personas)
            lora_scale = 1.0
            model = "tiny-llama"
            max_seq = 512
            checkpoint = ckpt
    names = list(personas)
    cfg = get_model_config(model, max_seq_len=max_seq)
    lora_cfg = {"rank": 8, "max_adapters": k, "scale": lora_scale,
                "adapters": personas}
    probe = ("The roundtable convenes; the knight weighs the proposal "
             "and begins to speak:")

    def turn_prompt(i: int, rnd: int, transcript: str) -> str:
        return (f"{transcript}\nRound {rnd}, knight {i} argues the "
                "proposal on its merits: ")

    def hbm_resident(engines) -> int:
        total = 0
        for e in engines:
            total += e.perf.param_bytes + e.kv.hbm_bytes()
            if getattr(e, "lora", None) is not None:
                total += e.lora.stack_bytes()
        return total

    def probe_divergence(dists: list[np.ndarray]) -> float:
        """Mean pairwise total-variation distance between the knights'
        next-token distributions — 0 = identical models, 1 = disjoint
        support. The measurable persona-diversity claim."""
        tv = []
        for i in range(len(dists)):
            for j in range(i + 1, len(dists)):
                tv.append(0.5 * float(np.abs(dists[i]
                                             - dists[j]).sum()))
        return round(sum(tv) / max(len(tv), 1), 4)

    def lora_probe_dist(eng, adapter) -> np.ndarray:
        """Next-token distribution of the probe prompt under one
        persona (the engine's own forward with the lora scope — the
        exact serving math, eagerly)."""
        from theroundtaible_tpu.engine.lora import lora_scope
        from theroundtaible_tpu.engine.models.common import forward
        toks = jnp.asarray([eng.tokenizer.encode(probe)], jnp.int32)
        pos = jnp.arange(toks.shape[1], dtype=jnp.int32)[None]
        valid = jnp.asarray([toks.shape[1]], jnp.int32)
        last = valid - 1
        slot = 0 if adapter is None else eng.lora.slot_of(adapter)
        ids = jnp.full((1,), slot, jnp.int32)
        with lora_scope((eng.lora.stacked, ids)):
            logits, _ = forward(eng.params, eng.cfg, toks, pos, None,
                                None, valid, last_pos=last)
        p = jax.nn.softmax(logits[0, 0].astype(jnp.float32))
        return np.asarray(p)

    def base_probe_dist(eng) -> np.ndarray:
        from theroundtaible_tpu.engine.models.common import forward
        toks = jnp.asarray([eng.tokenizer.encode(probe)], jnp.int32)
        pos = jnp.arange(toks.shape[1], dtype=jnp.int32)[None]
        valid = jnp.asarray([toks.shape[1]], jnp.int32)
        logits, _ = forward(eng.params, eng.cfg, toks, pos, None, None,
                            valid, last_pos=valid - 1)
        return np.asarray(jax.nn.softmax(
            logits[0, 0].astype(jnp.float32)))

    def run_shared() -> dict:
        eng = InferenceEngine(
            cfg, checkpoint=checkpoint, num_slots=k + 1,
            kv_layout="paged", num_pages=(k + 1) * max_seq // 128,
            lora=lora_cfg, **kw)
        warm_s = eng.warmup(max_prompt_tokens=256, batch_sizes=(1,))
        sched = SessionScheduler(eng, admit_hold_s=0.25)
        results: dict = {}
        errors: list = []
        dec = {"tokens": 0}
        lock = threading.Lock()

        def knight(i):
            transcript = ""
            try:
                for rnd in range(rounds):
                    txts, stats = sched.submit(
                        f"s{i}", [(f"knight{i}",
                                   turn_prompt(i, rnd, transcript))],
                        max_new_tokens=max_new,
                        adapters_per_turn=[names[i]])
                    transcript += f"\nKnight {i}: {txts[0]}"
                    with lock:
                        dec["tokens"] += stats.decode_tokens
                results[i] = transcript
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append((i, e))

        threads = [threading.Thread(target=knight, args=(i,))
                   for i in range(k)]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.monotonic() - t0
        if errors:
            raise RuntimeError(f"shared-base mode: {errors}")
        # Token parity: round-0 turn re-served ALONE per persona must
        # match what the mixed co-batched run emitted.
        parity = True
        for i in range(k):
            alone = eng.generate_batch(
                [(f"knight{i}", turn_prompt(i, 0, ""))],
                max_new_tokens=max_new, session=f"alone{i}",
                adapters_per_turn=[names[i]])[0]
            if not results[i].startswith(f"\nKnight {i}: {alone}"):
                parity = False
        dists = [lora_probe_dist(eng, names[i]) for i in range(k)]
        sched_d = sched.describe()
        out = {
            "engines": 1,
            "decode_tokens": dec["tokens"],
            "wall_s": round(wall, 2),
            "aggregate_decode_tok_s": round(dec["tokens"]
                                            / max(wall, 1e-9), 1),
            "hbm_resident_bytes": hbm_resident([eng]),
            "weights_bytes": eng.perf.param_bytes,
            "kv_bytes": eng.kv.hbm_bytes(),
            "adapter_stack_bytes": eng.lora.stack_bytes(),
            "divergence_tv": probe_divergence(dists),
            "mixed_vs_alone_parity": parity,
            "warmup_s": round(warm_s, 1),
            "max_occupancy": sched_d["max_occupancy"],
            "lora": eng.lora_describe(),
        }
        sched.close()
        return out, hbm_resident([eng]) - eng.lora.stack_bytes()

    def run_fleet() -> dict:
        engines = [InferenceEngine(
            cfg, checkpoint=checkpoint, num_slots=2, kv_layout="paged",
            num_pages=2 * max_seq // 128, seed=11 + i, **kw)
            for i in range(k)]
        warm_s = sum(e.warmup(max_prompt_tokens=256, batch_sizes=(1,))
                     for e in engines)
        errors: list = []
        dec = {"tokens": 0}
        lock = threading.Lock()

        def knight(i):
            transcript = ""
            try:
                for rnd in range(rounds):
                    txts, stats = engines[i].generate_batch_with_stats(
                        [(f"knight{i}",
                          turn_prompt(i, rnd, transcript))],
                        max_new_tokens=max_new, session=f"f{i}")
                    transcript += f"\nKnight {i}: {txts[0]}"
                    with lock:
                        dec["tokens"] += stats.decode_tokens
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append((i, e))

        threads = [threading.Thread(target=knight, args=(i,))
                   for i in range(k)]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        for th in threads:
            th.join(timeout=600)
        wall = time.monotonic() - t0
        if errors:
            raise RuntimeError(f"fleet mode: {errors}")
        dists = [base_probe_dist(e) for e in engines]
        return {
            "engines": k,
            "decode_tokens": dec["tokens"],
            "wall_s": round(wall, 2),
            "aggregate_decode_tok_s": round(dec["tokens"]
                                            / max(wall, 1e-9), 1),
            "hbm_resident_bytes": hbm_resident(engines),
            "weights_bytes": sum(e.perf.param_bytes for e in engines),
            "kv_bytes": sum(e.kv.hbm_bytes() for e in engines),
            # ONE fleet engine's residency — the honest "single base"
            # denominator for the headline ratios (the shared engine's
            # own bytes include a K-session KV pool, which would
            # inflate the denominator and flatter both ratios).
            "single_base_bytes": hbm_resident(engines[:1]),
            "divergence_tv": probe_divergence(dists),
            "warmup_s": round(warm_s, 1),
        }

    shared, shared_minus_stack = run_shared()
    # Trained-persona mode serves ONE real checkpoint — there is no
    # distinct-seed fleet to honestly compare against, so the A/B leg
    # runs only for the self-contained random-persona default.
    fleet = (run_fleet() if not checkpoint
             else {"skipped": "single trained checkpoint"})
    # Single-base denominator: one FLEET-shaped engine where the A/B
    # leg ran (its KV pool is single-session-sized); the shared
    # engine's own residency minus adapter stacks is the fallback —
    # conservative for the shared ratio (its pool serves K sessions).
    single_base_bytes = fleet.get("single_base_bytes",
                                  shared_minus_stack)
    result_line = {
        "metric": f"multi_lora_personas[{model}][K={k}]",
        "value": shared["aggregate_decode_tok_s"],
        "unit": "aggregate_decode_tok_s_shared_base",
        "detail": {
            "personas": k,
            "rounds": rounds,
            "shared_base_k_adapters": shared,
            "per_checkpoint_fleet": fleet,
            # The acceptance bar: K personas on one base must stay
            # under 1.5x a single base's residency; the fleet pays ~Kx.
            "single_base_bytes": single_base_bytes,
            # The persona-cost axis, KV factored out: serving K
            # personas costs (weights + adapter stacks) / weights of
            # ONE base — the model-size-independent claim (KV pools
            # scale with SESSIONS SERVED on either design, and on a
            # tiny CPU model they dwarf the weights; on a real 2B+
            # model weights dominate and the total ratio converges to
            # this one).
            "weights_ratio_shared_vs_single_base": round(
                (shared["weights_bytes"]
                 + shared["adapter_stack_bytes"])
                / max(shared["weights_bytes"], 1), 3),
            "weights_ratio_fleet_vs_single_base": float(k),
            # The ISSUE 10 acceptance bar, stated against THIS record:
            # on the persona-cost axis it holds here; the total-
            # residency form is weights-dominated only on real chips
            # (this CPU record's pools dwarf the tiny weights), so its
            # on-chip value is the window-3 measurement.
            "acceptance": {
                "criterion": "K-persona resident HBM < 1.5x "
                             "single-base (vs ~Kx per-checkpoint)",
                "weights_axis_ratio": round(
                    (shared["weights_bytes"]
                     + shared["adapter_stack_bytes"])
                    / max(shared["weights_bytes"], 1), 3),
                "meets_on_weights_axis": (
                    shared["weights_bytes"]
                    + shared["adapter_stack_bytes"])
                < 1.5 * shared["weights_bytes"],
                "total_ratio_this_platform": round(
                    shared["hbm_resident_bytes"]
                    / max(single_base_bytes, 1), 3),
                "total_ratio_note": (
                    "KV pools dominate tiny CPU models; on 2B+ "
                    "weights the total converges to the weights "
                    "axis — measured by the window-3 step"),
            },
            "single_base_def": ("one_fleet_engine"
                                if "single_base_bytes" in fleet
                                else "shared_minus_adapter_stacks"),
            "hbm_ratio_shared_vs_single_base": round(
                shared["hbm_resident_bytes"]
                / max(single_base_bytes, 1), 3),
            "hbm_ratio_fleet_vs_single_base": (round(
                fleet["hbm_resident_bytes"]
                / max(single_base_bytes, 1), 3)
                if "hbm_resident_bytes" in fleet else None),
            "hbm_saved_bytes_vs_fleet": (
                fleet["hbm_resident_bytes"]
                - shared["hbm_resident_bytes"]
                if "hbm_resident_bytes" in fleet else None),
            # CPU walls favor the fleet: K tiny engines decode with no
            # scheduler tick/hold overhead, while the shared batch pays
            # per-segment host round-trips that dwarf tiny-model
            # compute (the SPEC_r09 caveat verbatim). The on-chip claim
            # is the HBM column: K personas resident for ~1x one base
            # vs the fleet's ~Kx — the chip count it frees IS the
            # throughput multiplier at fleet scale.
            "cpu_wall_caveat": on_cpu,
            "platform": jax.devices()[0].platform,
            "telemetry": _registry_snapshot(),
        },
    }
    print(json.dumps(result_line), flush=True)
    return 0


def kv_quant_child() -> int:
    """Quantized-KV-page A/B (ISSUE 11 acceptance): the same pool byte
    budget served quant-ON (int8 pages + per-cell scales, in-kernel
    dequant) then quant-OFF (bf16 pages), in ONE record.

    Three measurements per mode, all through the REAL serving path:
    - MAX RESIDENT SESSIONS: admit fixed-shape sessions one at a time
      (offload tier off — no spill valve) until the allocator EVICTS an
      earlier session's pages; the count still fully resident is the
      honest capacity number (the pool refuses by LRU-evicting, not by
      raising). Quantized pools hold page_ratio x the pages in the same
      bytes, so the bar is >= 1.8x at int8.
    - SCHEDULED DECODE tok/s: K concurrent sessions through the
      session scheduler with ROUNDTABLE_RECOMPILE_STRICT=1 armed after
      a warm pass — the record carries the strict-green bit.
    - GREEDY TOKEN PARITY: the probe session's tokens must match
      across modes (the rms-bound acceptance rule's observable).
    """
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine import compile_watch
    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.scheduler import SessionScheduler
    from theroundtaible_tpu.utils import perfmodel

    on_cpu = jax.devices()[0].platform == "cpu"
    kvq_dtype = os.environ.get("ROUNDTABLE_BENCH_KVQ_DTYPE", "int8")
    if on_cpu:
        # head_dim=64: tiny-gemma's D=16 pays its per-cell f32 scale on
        # every 16 payload bytes (page ratio 1.6x); D=64 amortizes to
        # 1.88x so the CPU record exercises the same >= 1.8x bar the
        # chip hits at D=256.
        cfg = get_model_config("tiny-gemma", max_seq_len=512,
                               head_dim=64)
        kw = {"mesh_shape": {"data": 1, "model": 1}}
        page_size, num_slots, max_new, k_sched = 32, 32, 24, 3
    else:
        cfg = get_model_config("gemma-2b-it", max_seq_len=2048)
        kw = {}
        page_size, num_slots, max_new, k_sched = 128, 32, 48, 3
    session_prompt = (TOPIC + " The knight surveys the state of the "
                      "store, weighs the proposal on its merits, and "
                      "answers at length about the event log design. ")
    # The SAME pool byte budget on both sides, stated in pages: bf16
    # gets POOL_PAGES, the quantized pool gets page_ratio x as many —
    # byte-for-byte what the engine's default sizing does, pinned
    # explicitly so the A/B denominator can't drift with num_slots
    # (slots are sized to never bind; PAGES are the contended
    # resource, exactly the production refusal mode).
    from theroundtaible_tpu.engine import kv_quant as kvq_mod
    pool_pages = 6 * (cfg.max_seq_len // page_size)
    spec = kvq_mod.resolve_spec(kvq_dtype)[0]
    quant_pages = int(pool_pages * kvq_mod.page_ratio(
        spec, cfg.head_dim)) if spec is not None else pool_pages

    def build(quant):
        # prefix_cache off: the capacity climb must charge every
        # session its own pages — cache aliasing of the shared topic
        # preamble would make "resident sessions" unbounded and the
        # A/B vacuous. kv_offload off: no spill valve under pressure.
        return InferenceEngine(
            cfg, num_slots=num_slots, kv_layout="paged",
            page_size=page_size, kv_offload=False, prefix_cache=False,
            num_pages=(quant_pages if quant else pool_pages),
            kv_quant=(kvq_dtype if quant else None), **kw)

    def max_resident_sessions(eng) -> int:
        """Admit sessions until the allocator evicts one — the count
        still fully resident right before the first eviction."""
        admitted: list[str] = []
        for i in range(4 * num_slots):
            name = f"cap{i}"
            try:
                eng.generate(f"Distinct transcript {i}: "
                             + session_prompt, slot_name=name,
                             max_new_tokens=8)
            except RuntimeError:
                break           # hard exhaustion also ends the climb
            admitted.append(name)
            resident = set(eng.kv.slot_names())
            if any(a not in resident for a in admitted):
                return len(admitted) - 1
        return len(admitted)

    def run_mode(quant: bool) -> dict:
        eng = build(quant)
        warm_s = eng.warmup(max_prompt_tokens=256, batch_sizes=(1,))
        # Capacity climb on the bare engine (no scheduler spill valve).
        resident = max_resident_sessions(eng)
        eng.kv.revive_if_dead()
        for n in list(eng.kv.slot_names()):
            eng.kv.release(n)
        # Scheduled throughput with STRICT armed after a warm pass.
        sched = SessionScheduler(eng)
        errors: list = []
        dec = {"tokens": 0}
        lock = threading.Lock()

        def knight(i, tag):
            try:
                _, stats = sched.submit(
                    f"{tag}{i}", [(f"knight{i}",
                                   session_prompt + f"Knight {i}: ")],
                    max_new_tokens=max_new)
                with lock:
                    dec["tokens"] += stats.decode_tokens
            except Exception as e:  # noqa: BLE001 — reported below
                errors.append((i, repr(e)))

        def round_of(tag):
            threads = [threading.Thread(target=knight, args=(i, tag))
                       for i in range(k_sched)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=300)

        try:
            round_of("warm")
            compile_watch.install()
            compile_watch.warmup_complete("bench_kvq")
            strict0 = compile_watch.steady_state_compiles()
            os.environ["ROUNDTABLE_RECOMPILE_STRICT"] = "1"
            dec["tokens"] = 0
            t0 = time.monotonic()
            round_of("load")
            wall = time.monotonic() - t0
        finally:
            os.environ.pop("ROUNDTABLE_RECOMPILE_STRICT", None)
            sched.close()
        strict_green = (not errors and
                        compile_watch.steady_state_compiles() == strict0)
        compile_watch.reset_steady_state()
        if errors:
            raise RuntimeError(f"kv_quant bench mode quant={quant}: "
                               f"{errors}")
        # Parity probe: one fresh greedy session, compared across modes.
        probe = eng.generate(session_prompt, slot_name="probe",
                             max_new_tokens=16)
        led = eng.kv.memory_ledger()
        spec = eng.kv_quant_spec
        kv_ctx = cfg.max_seq_len // 2
        roof = perfmodel.roofline_block(
            param_bytes=eng.perf.param_bytes,
            num_params=eng.num_params,
            n_devices=int(eng.mesh.devices.size),
            kv_stream_bytes=kv_ctx * eng.perf.kv_token_bytes,
            kv_dtype=led["kv_dtype"])
        return {
            "kv_dtype": led["kv_dtype"],
            "max_resident_sessions": resident,
            "num_pages": eng.kv.num_pages,
            "decode_tokens": dec["tokens"],
            "wall_s": round(wall, 2),
            "decode_tok_s": round(dec["tokens"] / max(wall, 1e-9), 1),
            "strict_green": strict_green,
            "warmup_s": round(warm_s, 1),
            "ledger": {k: led[k] for k in (
                "kv_dtype", "kv_quant_bits", "kv_bytes_resident",
                "kv_bytes_logical", "kv_quant_bytes_saved",
                "usable_pages", "hbm_bytes")},
            "kv_quant": eng.kv_quant_describe(),
            "kv_bytes_per_token": eng.perf.kv_token_bytes,
            "roofline": roof,
            "group": (spec.effective_group(cfg.head_dim)
                      if spec is not None else None),
            "_probe": probe,
        }

    on = run_mode(True)
    off = run_mode(False)
    parity = on.pop("_probe") == off.pop("_probe")
    ratio = round(on["max_resident_sessions"]
                  / max(off["max_resident_sessions"], 1), 3)
    result_line = {
        "metric": f"kv_quant_pages[{cfg.name}][{kvq_dtype}]",
        "value": ratio,
        "unit": "max_resident_sessions_ratio_quant_vs_bf16",
        "detail": {
            "quant_on": on,
            "quant_off": off,
            "max_resident_sessions_ratio": ratio,
            "greedy_token_parity": parity,
            "strict_green_both_modes": (on["strict_green"]
                                        and off["strict_green"]),
            "decode_ceiling_lift": round(
                on["roofline"]["decode_ceiling_tps"]
                / max(off["roofline"]["decode_ceiling_tps"], 1e-9), 3),
            "acceptance": {
                "criterion": ">= 1.8x max resident sessions at int8 "
                             "vs bf16 on the same pool byte budget, "
                             "greedy parity True, STRICT green",
                "meets": (ratio >= 1.8 and parity
                          and on["strict_green"]
                          and off["strict_green"]),
            },
            "head_dim": cfg.head_dim,
            "page_size": page_size,
            "cpu_wall_caveat": on_cpu,
            "platform": jax.devices()[0].platform,
            "telemetry": _registry_snapshot(),
            "perf": _perf_block(),
        },
    }
    print(json.dumps(result_line), flush=True)
    return 0


def restart_child() -> int:
    """Restart-under-load (ISSUE 12 acceptance): the same K-session
    multi-round scripted load served twice on a paged + host-offload
    engine — fault-free, then with rolling `supervisor.restart()`
    cycles fired mid-run — in ONE record.

    Three claims, all through the REAL serving path (scheduler submit,
    own-slot reuse across rounds, supervisor quiesce → evacuate →
    rebuild → restore):
    - ZERO LOSS: every session completes every round in the restart
      run (sessions_lost == 0, completions match the baseline).
    - RECOVERY WALL: per-restart wall (and p95 across the rolling
      cycles) as reported by the supervisor's restart report.
    - GREEDY TOKEN PARITY: later rounds extend earlier rounds'
      committed KV via own-slot reuse, so the restart run's tokens
      match the fault-free run's exactly IFF the evacuate → restore
      hop was byte-identical.
    """
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import statistics
    import threading

    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.scheduler import SessionScheduler
    from theroundtaible_tpu.engine.supervisor import (EngineSupervisor,
                                                      set_supervisor,
                                                      supervisor)

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        config = {"model": "tiny-gemma", "max_seq_len": 512,
                  "num_slots": 12, "kv_layout": "paged", "page_size": 32,
                  "kv_offload": True,
                  "mesh": {"data": 1, "model": 1},
                  "sampling": {"temperature": 0.0}}
        max_new, rounds, k = 16, 3, 3
    else:
        config = {"model": "gemma-2b-it", "max_seq_len": 2048,
                  "num_slots": 12, "kv_layout": "paged",
                  "kv_offload": True,
                  "sampling": {"temperature": 0.0}}
        max_new, rounds, k = 48, 3, 3
    n_restarts = int(os.environ.get("ROUNDTABLE_BENCH_RESTART_N", "2"))

    def run_mode(restart: bool) -> dict:
        set_supervisor(EngineSupervisor(max_restarts=n_restarts + 2))
        eng = InferenceEngine.from_config(dict(config))
        sched = SessionScheduler(eng)
        produced: dict = {f"s{i}": [] for i in range(k)}
        errors: dict = {}
        lock = threading.Lock()

        def run_session(i: int) -> None:
            sid = f"s{i}"
            transcript = (TOPIC + f" Knight {i} weighs shard {i} of "
                          "the store against the event log proposal.")
            for _r in range(rounds):
                try:
                    texts, _stats = sched.submit(
                        sid, [(f"knight{i}", transcript)],
                        max_new_tokens=max_new, timeout_s=300.0)
                except Exception as e:  # noqa: BLE001 — counted as loss
                    with lock:
                        errors[sid] = repr(e)
                    return
                with lock:
                    produced[sid].append(texts[0])
                transcript += " " + texts[0]

        threads = [threading.Thread(target=run_session, args=(i,),
                                    daemon=True) for i in range(k)]
        t0 = time.monotonic()
        for th in threads:
            th.start()
        restart_walls: list[float] = []
        if restart:
            for cycle in range(1, n_restarts + 1):
                # Rolling restart AFTER round `cycle` has committed
                # everywhere: the next rounds must reuse KV that
                # crossed the evacuate → restore hop.
                while True:
                    with lock:
                        if errors or all(len(v) >= cycle
                                         for v in produced.values()):
                            break
                    time.sleep(0.02)
                if errors:
                    break
                rep = supervisor().restart(
                    sched.engine, reason=f"bench_rolling_{cycle}",
                    scheduler=sched)
                restart_walls.append(rep["wall_s"])
        for th in threads:
            th.join()
        wall = time.monotonic() - t0
        snap = supervisor().snapshot()
        sched.close()
        set_supervisor(None)
        return {
            "wall_s": round(wall, 2),
            "rounds_completed": {s: len(v) for s, v in produced.items()},
            "sessions_failed": errors,
            "restart_walls_s": restart_walls,
            "supervisor": {kk: snap[kk] for kk in (
                "restarts", "sessions_recovered", "sessions_lost")},
            "_tokens": {s: list(v) for s, v in produced.items()},
        }

    base = run_mode(False)
    rec = run_mode(True)
    parity = base.pop("_tokens") == rec.pop("_tokens")
    walls = rec["restart_walls_s"]
    p95 = (statistics.quantiles(walls, n=20)[-1] if len(walls) > 1
           else (walls[0] if walls else None))
    zero_loss = (not rec["sessions_failed"]
                 and rec["rounds_completed"] == base["rounds_completed"]
                 and rec["supervisor"]["sessions_lost"] == 0)
    result_line = {
        "metric": "engine_restart_under_load",
        "value": p95,
        "unit": "recovery_p95_wall_s",
        "detail": {
            "fault_free": base,
            "restart_run": rec,
            "restarts_fired": len(walls),
            "recovery_p95_wall_s": p95,
            "sessions_recovered": rec["supervisor"]["sessions_recovered"],
            "sessions_lost": rec["supervisor"]["sessions_lost"],
            "greedy_token_parity": parity,
            "acceptance": {
                "criterion": "zero sessions lost across rolling "
                             "restarts under load, greedy token parity "
                             "vs the uninterrupted run",
                "meets": bool(zero_loss and parity),
            },
            "cpu_wall_caveat": on_cpu,
            "platform": jax.devices()[0].platform,
            "telemetry": _registry_snapshot(),
            "perf": _perf_block(),
        },
    }
    print(json.dumps(result_line), flush=True)
    return 0


def main() -> int:
    from bench_common import run_watchdogged
    # The offered-load / prefix-reuse sweeps run many scripted
    # discussions in one child — wider attempt window than the single run.
    attempt_s = (2 * ATTEMPT_TIMEOUT_S
                 if os.environ.get("ROUNDTABLE_BENCH_OFFERED_LOAD")
                 or os.environ.get("ROUNDTABLE_BENCH_PREFIX_REUSE")
                 or os.environ.get("ROUNDTABLE_BENCH_SPEC_DECODE")
                 or os.environ.get("ROUNDTABLE_BENCH_LORA")
                 or os.environ.get("ROUNDTABLE_BENCH_KV_QUANT")
                 or os.environ.get("ROUNDTABLE_BENCH_RESTART")
                 else ATTEMPT_TIMEOUT_S)
    return run_watchdogged(os.path.abspath(__file__), [],
                           attempt_s, MAX_ATTEMPTS, RETRY_DELAY_S)


def _run_child() -> int:
    if os.environ.get("ROUNDTABLE_BENCH_RESTART"):
        return restart_child()
    if os.environ.get("ROUNDTABLE_BENCH_KV_QUANT"):
        return kv_quant_child()
    if os.environ.get("ROUNDTABLE_BENCH_LORA"):
        return lora_child()
    if os.environ.get("ROUNDTABLE_BENCH_SPEC_DECODE"):
        return spec_decode_child()
    if os.environ.get("ROUNDTABLE_BENCH_LATE_JOIN"):
        return late_join_child()
    if os.environ.get("ROUNDTABLE_BENCH_PREFIX_REUSE"):
        return prefix_reuse_child()
    if os.environ.get("ROUNDTABLE_BENCH_OFFERED_LOAD"):
        return offered_load_child()
    return child()


if __name__ == "__main__":
    sys.exit(_run_child() if "--child" in sys.argv else main())
