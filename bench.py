"""Benchmark — decode throughput of the flagship model on real hardware.

Prints JSON lines: one per quant config AS EACH MEASUREMENT LANDS
(bf16 first), then the headline line LAST — every line is a complete
{"metric", "value", "unit", "vs_baseline", "detail"} record, so a child
killed mid-int8 has already emitted a usable bf16 number (round-2
lesson: a half-finished child contributed zero; VERDICT.md weak #1c).

Measures BASELINE.md config 1's engine side (gemma-2b, single chip):
chunked prefill + jit'd while_loop decode through the production
InferenceEngine (persistent KV slot, bucketed shapes). The reference
publishes no numbers (BASELINE.md "published: {}"), so vs_baseline is
computed against A100 Ollama gemma-2b decode ≈ 120 tok/s — the
wall-clock-parity target the driver defines (north star: v5e vs A100
Ollama).

Each run dict also carries a `roofline` block with `decode_ceiling_tps`,
`decode_frac` and `prefill_mfu` (VERDICT.md missing #4): decode
is weight-streaming bound at batch=1, so the ceiling is
HBM_bandwidth / streamed_param_bytes (measured from the actual param
tree, so int8 automatically gets its halved-bytes ceiling); prefill is
compute bound, ceiling = peak bf16 FLOP/s with FLOPs/token ≈ 2·params.
KV-read traffic is excluded (gemma-2b MQA at ≤2k ctx reads ~30 MB/token
vs ~5 GB of weights — <1%).

Cold-start discipline (round-1 lesson: the JSON must land well inside
the driver's capture window):
- persistent XLA compilation cache (engine.enable_compilation_cache);
- minimal warmup: ONLY the programs this bench prompt actually
  dispatches, run twice for the donated-buffer layout fixpoint;
- probe-first watchdog (bench_common): a cheap `jax.devices()` child
  must succeed before the heavy child ever starts, so the watchdog
  never kills a claim-holding child on a tunnel that a probe would
  have proven dead anyway.

Driver-channel resilience (VERDICT item 9): when the probe fails (or
every attempt dies recordless), the watchdog re-emits the latest
COMMITTED builder-jsonl headline as an explicitly-marked `cached`
record with commit-hash provenance (bench_common.emit_cached_headlines)
— BENCH_r0N.json is never empty while real numbers exist in the repo,
and a cached number can never masquerade as a fresh one.
"""

from __future__ import annotations

import json
import os
import sys
import time

A100_OLLAMA_GEMMA2B_DECODE_TPS = 120.0  # external anchor, see ANCHOR_PROVENANCE

# VERDICT r4 weak #3: the anchor is an ASSERTED constant, not a
# measurement — every vs_baseline ratio inherits it, so its provenance
# rides along machine-readably in every record. It cannot be measured in
# this environment (zero egress, no A100); the bracket pins it to
# physics: A100-40GB HBM 1555 GB/s over ~2.5 GiB of int8 gemma-2b
# weights gives a ~580 tok/s weight-streaming ceiling, and llama.cpp's
# typical 20-40% of roofline on small models lands 115-230 tok/s; 120 is
# the conservative low edge. Anyone with an A100 reproduces it with the
# command below (Ollama prints "eval rate" per run).
ANCHOR_PROVENANCE = {
    "value": A100_OLLAMA_GEMMA2B_DECODE_TPS,
    "status": "asserted (reference publishes no numbers, BASELINE.md)",
    "reproduce": "ollama run gemma:2b --verbose  # eval rate, A100",
    "bracket_tps": [115, 230],
    "bracket_basis": ("A100-40GB 1555 GB/s / ~2.5 GiB int8 weights "
                      "= ~580 tok/s ceiling x llama.cpp 20-40% typical"),
}

ATTEMPT_TIMEOUT_S = 780.0  # four engines (bf16, int8, int8+paged, int4)
                           # cold; per-run lines flush as they land, so
                           # even a timeout salvages the finished configs
MAX_ATTEMPTS = 2
RETRY_DELAY_S = 20.0

# Roofline constants + ceiling math live in ONE place now (ISSUE 6):
# utils/perfmodel.py. These re-exports keep the historical bench.py
# names alive; the drift test pins them to the shared model.
from theroundtaible_tpu.utils.perfmodel import (V5E_BF16_PEAK_TFLOPS,
                                                V5E_HBM_GBPS)

PROMPT = (
    "You are taking part in a TheRoundtAIble discussion. Topic: should we "
    "refactor the session store before adding the apply pipeline? Consider "
    "the trade-offs carefully and end with a consensus JSON block. " * 8
)


def child() -> int:
    """The actual measurement (runs in a watchdogged subprocess)."""
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    # Local smoke-testing escape hatch: this image's sitecustomize pins
    # JAX_PLATFORMS=axon before user env is consulted, so an env var alone
    # cannot select cpu — mirror tests/conftest.py's config override. Must
    # run before anything initializes the backend (incl. the compilation
    # cache, which checks jax.default_backend()).
    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.sampling import SamplingParams

    devices = jax.devices()
    platform = devices[0].platform
    on_cpu = platform == "cpu"
    if on_cpu:
        cfg = get_model_config("tiny-gemma")
        decode_tokens = 64
    else:
        cfg = get_model_config("gemma-2b-it", max_seq_len=2048)
        decode_tokens = 256

    failed: list[dict] = []  # configs that errored (emit records them)
    base_key = f"decode_tokens_per_sec_per_chip[{cfg.name}]"

    def config_label(quant: str, kv_layout: str) -> str:
        return ("bf16" if quant == "none" else quant) + \
            ("-paged" if kv_layout == "paged" else "")

    def emit(run: dict, headline: bool) -> None:
        """Print one complete result record for `run` (flushed).

        Only the headline line carries the STABLE metric key (exactly
        one such line per successful run, so per-key summing / take-
        first / take-last parsers all agree); per-run lines get a
        config-suffixed key and exist so a child killed mid-run has
        already landed complete, unambiguous records for the finished
        configs."""
        decode_tps = run["decode_tps"]
        label = run["label"]
        detail = {
            "headline": headline,
            "runs": runs if headline else [run],
            "devices": len(devices),
            "platform": platform,
        }
        # Registry snapshot in every run record (ISSUE 5, the
        # int4_paths pattern): BENCH_r*.json carries the window's
        # occupancy/fallback/hang/breaker counters with the same commit
        # provenance as the headline number.
        from theroundtaible_tpu.utils import telemetry
        detail["telemetry"] = telemetry.REGISTRY.snapshot_compact()
        if headline:
            detail["winning_config"] = label  # winner of all runs
            detail["anchor_provenance"] = ANCHOR_PROVENANCE
            # Perf-attribution block (ISSUE 6): roofline gauges, compile
            # observatory summary (how many compiles the measured runs
            # actually paid — cache hit vs fresh), memory ledger, span
            # overheads — the window's numbers arrive with their
            # explanation attached.
            from theroundtaible_tpu.utils import perfmodel
            detail["perf"] = perfmodel.attribution_snapshot()
            if failed:
                detail["failed_configs"] = failed
        rec = {
            "metric": base_key if headline else f"{base_key}[{label}]",
            "value": decode_tps,
            "unit": "tokens/s",
            "vs_baseline": round(
                decode_tps / A100_OLLAMA_GEMMA2B_DECODE_TPS, 3),
            "detail": detail,
        }
        print(json.dumps(rec), flush=True)

    def measure(quant: str, kv_layout: str = "contiguous") -> dict:
        """Build + minimally warm one engine, return its measured run.

        Warmup serves the bench prompt itself on a throwaway slot: this
        compiles exactly the (batch=1, bucket) prefill programs the
        prompt's chunking hits plus the one decode-segment program; the
        second pass reaches the donated-buffer layout fixpoint (see
        InferenceEngine.warmup docstring). Slot released between passes
        so each is an honest full prefill."""
        t_build = time.monotonic()
        engine = InferenceEngine(
            cfg, num_slots=4, quant=quant, kv_layout=kv_layout,
            sampling=SamplingParams(temperature=0.0,
                                    max_new_tokens=decode_tokens))
        build_s = time.monotonic() - t_build
        param_bytes = sum(
            x.size * x.dtype.itemsize
            for x in jax.tree_util.tree_leaves(engine.params))
        t_warm = time.monotonic()
        for _ in range(2):
            engine.kv.release("__bench_warmup")
            engine.generate(PROMPT, slot_name="__bench_warmup",
                            max_new_tokens=decode_tokens)
        engine.kv.release("__bench_warmup")
        warmup_s = time.monotonic() - t_warm
        # Median-of-3 measured runs, each on a freshly released slot (no
        # prefix reuse → honest prefill every repeat). Warmup dominates
        # cold-start cost; the extra two timed runs add only seconds.
        from bench_common import timed_repeats

        def run_once() -> dict:
            engine.kv.release("bench")
            t0 = time.monotonic()
            engine.generate(PROMPT, slot_name="bench",
                            max_new_tokens=decode_tokens)
            wall = time.monotonic() - t0
            s = engine.last_stats
            return {"decode_tps": s.decode_tps,
                    "prefill_tps": s.prefill_tps, "wall_s": wall}

        med, spread, repeats = timed_repeats(run_once)
        s = engine.last_stats
        label = config_label(quant, kv_layout)
        # Path provenance (ISSUE 3): which einsum dispatches compiled to
        # the fused w4a16 kernels vs the XLA dequant fallback — the
        # window's int4 number must be attributable to the kernel, and
        # every decline carries an explicit fallback_reason.
        int4_paths = None
        int4_fallback_dispatches = None
        if quant == "int4":
            rep = engine.int4_path_report()
            if rep is not None:
                # Raw per-(spec, shape) dispatch count — the SAME
                # granularity as the live
                # roundtable_int4_fallback_dispatches gauge, so the
                # bench record and the registry can't disagree (the
                # int4_paths summary below dedupes for readability).
                int4_fallback_dispatches = len(rep["xla_dequant"])
                int4_paths = {
                    "pallas_w4a16": sorted(
                        {e["spec"] for e in rep["pallas_w4a16"]}),
                    "xla_dequant": sorted(
                        {(e["spec"], e.get("fallback_reason", ""))
                         for e in rep["xla_dequant"]}),
                }
        run = {
            "label": label,
            "quant": quant,
            "kv_layout": kv_layout,
            "decode_tps": round(med["decode_tps"], 2),
            "prefill_tps": round(med["prefill_tps"], 1),
            "prefill_tokens": s.prefill_tokens,
            "decode_tokens": s.decode_tokens,
            "wall_s": round(med["wall_s"], 2),
            "build_s": round(build_s, 1),
            "warmup_s": round(warmup_s, 1),
            "param_bytes": param_bytes,
            "repeats": repeats,
            **({"int4_paths": int4_paths} if int4_paths else {}),
            "spread": {
                "decode_tps": [round(spread["decode_tps"][0], 2),
                               round(spread["decode_tps"][1], 2)],
                "prefill_tps": [round(spread["prefill_tps"][0], 1),
                                round(spread["prefill_tps"][1], 1)],
            },
        }
        if not on_cpu:
            # The roofline block is PRODUCED by the shared perfmodel
            # (ISSUE 6): aggregate ceilings scale with the mesh size,
            # streamed bytes come from the actual quantized tree, and
            # the same math backs the live bw_utilization/mfu gauges —
            # bench records and serving gauges can no longer drift.
            from theroundtaible_tpu.utils import perfmodel
            run["roofline"] = perfmodel.roofline_block(
                param_bytes=param_bytes,
                num_params=engine.num_params,
                n_devices=len(devices),
                decode_tps=run["decode_tps"],
                prefill_tps=run["prefill_tps"],
                int4_fallbacks=int4_fallback_dispatches)
        return run

    # Measure bf16, int8 (the reference's llama.cpp baseline serves
    # quantized weights, so int8 is the apples-to-apples config),
    # int8+paged (the pool-direct decode kernel vs the contiguous layout
    # — the paged-vs-contiguous delta VERDICT r2 #7 asks for) and int4
    # (grouped w4a16, engine/quant.py bits=4 — the llama.cpp default
    # precision CLASS, and another ~2× decode ceiling over int8 if the
    # unpack fuses into the matmul operand; its roofline block derives
    # the ceiling from the actual packed bytes either way). Each run's
    # record is printed the moment it lands; the headline (fastest) is
    # printed LAST under the same STABLE metric key (round-over-round
    # comparisons track the key). int4 measures FIRST: it is the config
    # whose number is newest (the shard-aware fused kernels are what the
    # window exists to price), and windows die mid-bench often enough
    # that the least-replaceable measurement must land before the
    # re-measures. Its record carries `int4_paths` so the number is
    # attributable to the kernel path, never a silent XLA fallback.
    runs: list[dict] = []
    for quant, kv_layout in (("int4", "contiguous"),
                             ("none", "contiguous"),
                             ("int8", "contiguous"),
                             ("int8", "paged")):
        # One config failing (e.g. a TPU-compile surprise in a config
        # whose kernels only ever ran on CPU) must not cost the others
        # their records — and above all must not cost the HEADLINE line,
        # the stable metric key the driver tracks round over round.
        # (bench.py is the only multi-config CHILD; bench_suite already
        # isolates each sub-bench in its own watchdogged child, so this
        # loop does not belong in bench_common.)
        try:
            run = measure(quant, kv_layout)
        except Exception as e:  # noqa: BLE001 — recorded, not hidden
            # Full traceback to stderr: run_watchdogged surfaces its
            # tail, so a hardware-window failure stays diagnosable.
            import traceback
            traceback.print_exc(file=sys.stderr)
            label = config_label(quant, kv_layout)
            failed.append({"quant": quant, "kv_layout": kv_layout,
                           "label": label,
                           "error": f"{type(e).__name__}: {e}"[:300]})
            # Complete record under a DISTINCT key: [label][failed] so
            # the forwarder attempt-stamps and dedups it, while a
            # retry's SUCCESS under the clean [label] key still streams
            # through (per-key dedup would suppress it if failures
            # shared the success key).
            print(json.dumps({
                "metric": f"{base_key}[{label}][failed]",
                "value": 0.0, "unit": "tokens/s", "vs_baseline": 0.0,
                "detail": {"failed": True, **failed[-1]},
            }), flush=True)
            continue
        runs.append(run)
        emit(run, headline=False)
    if not runs:
        raise RuntimeError(f"every bench config failed: {failed}")
    emit(max(runs, key=lambda r: r["decode_tps"]), headline=True)
    # Nonzero exit on any per-config failure: the watchdog then retries
    # the whole child, the per-key dedup forwards only records no earlier
    # attempt emitted — i.e. exactly the configs that failed — so a
    # TRANSIENT tunnel error still gets its number. (The attempt-1
    # headline is kept even if a retried config would have won: a stable
    # headline beats a lost one; the per-config records tell the story.)
    return 1 if failed else 0


def main() -> int:
    from bench_common import run_watchdogged
    return run_watchdogged(os.path.abspath(__file__), [],
                           ATTEMPT_TIMEOUT_S, MAX_ATTEMPTS, RETRY_DELAY_S)


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
