"""Benchmark — decode throughput of the flagship model on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures BASELINE.md config 1's engine side (gemma-2b, single chip): chunked
prefill + jit'd while_loop decode through the production InferenceEngine
(persistent KV slot, bf16, bucketed shapes). The reference publishes no
numbers (BASELINE.md "published: {}"), so vs_baseline is computed against
A100 Ollama gemma-2b decode ≈ 120 tok/s — the wall-clock-parity target the
driver defines (north star: v5e vs A100 Ollama).

Cold-start discipline (round-1 lesson: the JSON must land well inside the
driver's capture window):
- persistent XLA compilation cache (engine.enable_compilation_cache) — the
  second-ever process run deserializes instead of compiling;
- minimal warmup: ONLY the programs this bench prompt actually dispatches
  (its prefill buckets + the decode segment), run twice for the donated-
  buffer layout fixpoint — NOT InferenceEngine.warmup()'s full bucket grid;
- watchdog + retry: the single-claim TPU tunnel HANGS (not errors) while
  another process holds the chip, and a hung PJRT init cannot be
  interrupted in-process — so the measurement runs in a child process the
  parent can kill and relaunch with backoff.
"""

from __future__ import annotations

import json
import os
import sys
import time

A100_OLLAMA_GEMMA2B_DECODE_TPS = 120.0  # external anchor, see module docstring

ATTEMPT_TIMEOUT_S = 320.0  # two engines (bf16+int8) ≈140s cold; margin
MAX_ATTEMPTS = 3
RETRY_DELAY_S = 20.0

PROMPT = (
    "You are taking part in a TheRoundtAIble discussion. Topic: should we "
    "refactor the session store before adding the apply pipeline? Consider "
    "the trade-offs carefully and end with a consensus JSON block. " * 8
)


def child() -> int:
    """The actual measurement (runs in a watchdogged subprocess)."""
    import jax

    # Local smoke-testing escape hatch: this image's sitecustomize pins
    # JAX_PLATFORMS=axon before user env is consulted, so an env var alone
    # cannot select cpu — mirror tests/conftest.py's config override. Must
    # run before anything initializes the backend (incl. the compilation
    # cache, which checks jax.default_backend()).
    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache

    enable_compilation_cache()

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.sampling import SamplingParams

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        cfg = get_model_config("tiny-gemma")
        decode_tokens = 64
    else:
        cfg = get_model_config("gemma-2b-it", max_seq_len=2048)
        decode_tokens = 256

    def measure(quant: str) -> dict:
        """Build + minimally warm one engine, return its measured run.

        Warmup serves the bench prompt itself on a throwaway slot: this
        compiles exactly the (batch=1, bucket) prefill programs the
        prompt's chunking hits plus the one decode-segment program; the
        second pass reaches the donated-buffer layout fixpoint (see
        InferenceEngine.warmup docstring). Slot released between passes
        so each is an honest full prefill."""
        t_build = time.monotonic()
        engine = InferenceEngine(
            cfg, num_slots=4, quant=quant,
            sampling=SamplingParams(temperature=0.0,
                                    max_new_tokens=decode_tokens))
        build_s = time.monotonic() - t_build
        t_warm = time.monotonic()
        for _ in range(2):
            engine.kv.release("__bench_warmup")
            engine.generate(PROMPT, slot_name="__bench_warmup",
                            max_new_tokens=decode_tokens)
        engine.kv.release("__bench_warmup")
        warmup_s = time.monotonic() - t_warm
        # Measured run on a fresh slot (no prefix reuse → honest prefill).
        t0 = time.monotonic()
        engine.generate(PROMPT, slot_name="bench",
                        max_new_tokens=decode_tokens)
        wall = time.monotonic() - t0
        s = engine.last_stats
        return {
            "quant": quant,
            "decode_tps": round(s.decode_tps, 2),
            "prefill_tps": round(s.prefill_tps, 1),
            "prefill_tokens": s.prefill_tokens,
            "decode_tokens": s.decode_tokens,
            "wall_s": round(wall, 2),
            "build_s": round(build_s, 1),
            "warmup_s": round(warmup_s, 1),
        }

    # Measure bf16 and int8 (the reference's llama.cpp baseline serves
    # quantized weights, so int8 is the apples-to-apples config; bf16 is
    # reported alongside). Headline value = the faster of the two, under
    # a STABLE metric key (round-over-round comparisons track the key).
    runs = [measure("none"), measure("int8")]
    best = max(runs, key=lambda r: r["decode_tps"])
    decode_tps = best["decode_tps"]
    result = {
        "metric": f"decode_tokens_per_sec_per_chip[{cfg.name}]",
        "value": decode_tps,
        "unit": "tokens/s",
        "vs_baseline": round(decode_tps / A100_OLLAMA_GEMMA2B_DECODE_TPS, 3),
        "detail": {
            "winning_quant": ("bf16" if best["quant"] == "none"
                              else best["quant"]),
            "runs": runs,
            "devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))
    return 0


def main() -> int:
    from bench_common import run_watchdogged
    return run_watchdogged(os.path.abspath(__file__), [],
                           ATTEMPT_TIMEOUT_S, MAX_ATTEMPTS, RETRY_DELAY_S)


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
