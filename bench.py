"""Benchmark — decode throughput of the flagship model on real hardware.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.

Measures BASELINE.md config 1's engine side (gemma-2b, single chip): chunked
prefill + jit'd while_loop decode through the production InferenceEngine
(persistent KV slot, bf16, bucketed shapes). The reference publishes no
numbers (BASELINE.md "published: {}"), so vs_baseline is computed against
A100 Ollama gemma-2b decode ≈ 120 tok/s — the wall-clock-parity target the
driver defines (north star: v5e vs A100 Ollama).
"""

from __future__ import annotations

import json
import sys
import time

A100_OLLAMA_GEMMA2B_DECODE_TPS = 120.0  # external anchor, see module docstring

PROMPT = (
    "You are taking part in a TheRoundtAIble discussion. Topic: should we "
    "refactor the session store before adding the apply pipeline? Consider "
    "the trade-offs carefully and end with a consensus JSON block. " * 8
)


def main() -> int:
    import jax

    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.models.registry import get_model_config
    from theroundtaible_tpu.engine.sampling import SamplingParams

    on_cpu = jax.devices()[0].platform == "cpu"
    if on_cpu:
        cfg = get_model_config("tiny-gemma")
        decode_tokens = 64
    else:
        cfg = get_model_config("gemma-2b-it", max_seq_len=2048)
        decode_tokens = 256

    engine = InferenceEngine(
        cfg, num_slots=4,
        sampling=SamplingParams(temperature=0.0,
                                max_new_tokens=decode_tokens))

    # Compile + layout-stabilize every serving program (two runs per
    # bucket — see InferenceEngine.warmup).
    warmup_s = engine.warmup()

    # Measured run on a fresh slot (no prefix reuse → honest prefill too).
    t0 = time.monotonic()
    engine.generate(PROMPT, slot_name="bench", max_new_tokens=decode_tokens)
    wall = time.monotonic() - t0
    s = engine.last_stats

    decode_tps = s.decode_tps
    result = {
        "metric": f"decode_tokens_per_sec_per_chip[{cfg.name}]",
        "value": round(decode_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": round(decode_tps / A100_OLLAMA_GEMMA2B_DECODE_TPS, 3),
        "detail": {
            "prefill_tps": round(s.prefill_tps, 1),
            "prefill_tokens": s.prefill_tokens,
            "decode_tokens": s.decode_tokens,
            "wall_s": round(wall, 2),
            "warmup_s": round(warmup_s, 1),
            "devices": len(jax.devices()),
            "platform": jax.devices()[0].platform,
        },
    }
    print(json.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
