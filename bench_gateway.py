"""Gateway chaos + overload acceptance (ISSUE 16) — GATEWAY_r16.json.

Runs entirely on CPU against real child gateway processes (the
tests/_gateway_main.py entry), with ROUNDTABLE_RECOMPILE_STRICT=1
armed across every child including the post-crash restart:

(a) **kill -9 mid-stream**: 3 concurrent discussion streams, SIGKILL
    the serving process after each client has read part of its stream,
    restart with `--resume`, reconnect every client via Last-Event-ID
    — zero lost, zero duplicated tokens, greedy parity against an
    uninterrupted reference run of the same prompts.
(b) **open-loop overload**: a burst of requests against a gateway
    capped at ROUNDTABLE_GATEWAY_MAX_INFLIGHT=2 — the excess must shed
    with 429 + Retry-After + a machine-readable reason while the
    admitted requests' p95 TTFT stays bounded.
(c) **preflight invariants**: `roundtable lint` exits 0.

`--smoke` shrinks (a) to one stream and (b) to a small burst for the
run_hw_window3.sh CPU preflight step; the full run writes
GATEWAY_r16.json at the repo root.

`--replicas 2` (ISSUE 17) switches to the router acceptance: a
rolling restart of replica r0 under open-loop multi-turn client load
(zero failed sessions, zero lost/duplicated tokens, greedy parity
across the roll) plus the aggregate-tok/s scaling point at 1 and 2
replicas — written to ROUTER_r17.json. `--smoke --replicas 2` shrinks
it to one client and skips the scaling sweep for the CPU preflight.

`--trace` (ISSUE 20) switches to the end-to-end tracing acceptance —
TRACE_r20.json: a chaos run (device_lost cross-replica failover, then
kill -9 + `--resume`, under concurrent streams) where every client
request stitches to ONE on-disk trace across both process generations
with per-leg stage sums within 5% of the leg wall and zero orphan
legs; an open-loop loadgen sweep whose per-session records join to
retained server-side traces with per-stage p95 attribution; and the
SLO burn monitor staying quiet on a under-SLO baseline while firing
exactly once on an induced breach. `--trace --smoke` shrinks it to
one stream + one sweep point for the run_hw_window3.sh preflight.
"""

from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))

PROMPTS = [
    "The round table met at dawn to discuss the castle walls and the "
    "eastern gate.",
    "A different discussion entirely, about dragons and the kingdom's "
    "gold reserves.",
    "The quartermaster tallies grain, arrows and oil for the winter "
    "siege preparations.",
]


# --- minimal raw-socket HTTP/SSE client (stdlib only) ----------------


class Conn:
    def __init__(self, port, method, path, body=None, headers=None,
                 timeout=180.0):
        self.sock = socket.create_connection(("127.0.0.1", port),
                                             timeout=timeout)
        payload = (json.dumps(body).encode("utf-8")
                   if body is not None else b"")
        head = (f"{method} {path} HTTP/1.1\r\nHost: b\r\n"
                f"Content-Length: {len(payload)}\r\n")
        for k, v in (headers or {}).items():
            head += f"{k}: {v}\r\n"
        self.sock.sendall(head.encode("latin-1") + b"\r\n" + payload)
        self.f = self.sock.makefile("rb")
        self.status = int(self.f.readline().split()[1])
        self.headers = {}
        while True:
            ln = self.f.readline().decode("latin-1").strip()
            if not ln:
                break
            k, _, v = ln.partition(":")
            self.headers[k.lower()] = v.strip()

    def events(self):
        eid, data = None, []
        for raw in self.f:
            ln = raw.decode("utf-8").rstrip("\n")
            if ln.startswith("id: "):
                eid = ln[4:]
            elif ln.startswith("data: "):
                data.append(ln[6:])
            elif ln.startswith(":"):
                continue
            elif ln == "" and data:
                yield eid, "\n".join(data)
                eid, data = None, []

    def body_json(self):
        n = int(self.headers.get("content-length", "0"))
        return json.loads(self.f.read(n).decode("utf-8")) if n else {}

    def close(self):
        try:
            self.f.close()
            self.sock.close()
        except OSError:
            pass


def read_stream(port, path, body=None, method="POST", headers=None):
    """(meta, [(eid, token_event)...], terminal) for one full stream."""
    c = Conn(port, method, path, body=body, headers=headers)
    assert c.status == 200, f"{c.status}: {c.body_json()}"
    meta, toks, terminal = None, [], None
    for eid, data in c.events():
        ev = json.loads(data)
        if ev["type"] == "stream":
            meta = ev
        elif ev["type"] in ("tokens", "summary"):
            toks.append((eid, ev))
        else:
            terminal = ev
            break
    c.close()
    return meta, toks, terminal


def flat_tokens(toks):
    out = []
    for _eid, ev in toks:
        if ev["type"] == "tokens":
            out.extend(ev["tokens"])
        else:
            for _i, d in sorted(ev["rows"].items()):
                out.extend(d["tokens"])
    return out


# --- child lifecycle -------------------------------------------------


def spawn_gateway(jdir, resume=None, extra_env=None, replicas=None):
    cmd = [sys.executable, os.path.join(REPO, "tests",
                                        "_gateway_main.py"),
           "--journal", str(jdir)]
    if resume:
        cmd += ["--resume", str(resume)]
    if replicas is not None:
        cmd += ["--replicas", str(replicas)]
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               ROUNDTABLE_RECOMPILE_STRICT="1",
               ROUNDTABLE_DISABLE_TPU_DETECT="1",
               **(extra_env or {}))
    proc = subprocess.Popen(cmd, env=env, stdout=subprocess.PIPE,
                            stderr=subprocess.STDOUT, text=True)
    port, deadline = None, time.monotonic() + 300
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            break
        if line.startswith("PORT="):
            port = int(line.strip().split("=", 1)[1])
            break
    if port is None:
        proc.kill()
        raise RuntimeError("gateway child never started listening")
    threading.Thread(target=lambda: [None for _ in proc.stdout],
                     daemon=True).start()
    return proc, port


# --- (a) kill -9 chaos ----------------------------------------------


def run_chaos(workdir, n_streams, max_new):
    jdir = os.path.join(workdir, "chaos-journal")
    sessions = [(f"c{i}", PROMPTS[i % len(PROMPTS)])
                for i in range(n_streams)]

    proc, port = spawn_gateway(jdir)
    refs, metas, seen = [], [], []
    conns = []
    t_kill = None
    try:
        # uninterrupted reference (same process = same weights).
        for name, prompt in sessions:
            _m, toks, term = read_stream(
                port, "/v1/discussions",
                {"session": f"ref-{name}", "max_new_tokens": max_new,
                 "turns": [{"knight": "lancelot", "prompt": prompt}]})
            assert term["type"] == "retired"
            refs.append(flat_tokens(toks))

        for name, prompt in sessions:
            c = Conn(port, "POST", "/v1/discussions",
                     body={"session": name, "max_new_tokens": max_new,
                           "turns": [{"knight": "lancelot",
                                      "prompt": prompt}]})
            assert c.status == 200
            conns.append(c)
        for c in conns:
            it = c.events()
            meta = json.loads(next(it)[1])
            metas.append(meta)
            got, last_id = [], None
            for eid, data in it:
                ev = json.loads(data)
                if ev["type"] in ("tokens", "summary"):
                    got.extend(flat_tokens([(eid, ev)]))
                    last_id = eid
                if len(got) >= 2:
                    break
            assert last_id is not None, "no tokens before the crash"
            seen.append((got, last_id))
        t_kill = time.monotonic()
    finally:
        proc.kill()  # SIGKILL mid-stream
        proc.wait(30)
        for c in conns:
            c.close()

    proc2, port2 = spawn_gateway(jdir, resume=jdir)
    t_up = time.monotonic() - t_kill
    lost = dup = 0
    reconnect_walls = []
    try:
        for (name, _p), meta, (got, last_id), ref in zip(
                sessions, metas, seen, refs):
            t0 = time.monotonic()
            _m2, toks2, term2 = read_stream(
                port2, f"/v1/streams/{meta['stream']}", method="GET",
                headers={"Last-Event-ID": last_id})
            reconnect_walls.append(round(time.monotonic() - t0, 3))
            assert term2 and term2["type"] == "retired", \
                f"{name}: resumed stream did not retire"
            full = got + flat_tokens(toks2)
            if full != ref:
                if len(full) < len(ref) or full[:len(ref)] != ref:
                    lost += 1
                else:
                    dup += 1
    finally:
        proc2.kill()
        proc2.wait(30)

    return {
        "streams": n_streams,
        "max_new_tokens": max_new,
        "tokens_seen_before_kill": [len(g) for g, _ in seen],
        "restart_to_listening_wall_s": round(t_up, 3),
        "reconnect_walls_s": reconnect_walls,
        "streams_lost_tokens": lost,
        "streams_duplicated_tokens": dup,
        "greedy_token_parity": lost == 0 and dup == 0,
    }


# --- (b) open-loop overload -----------------------------------------


def run_overload(workdir, burst, max_inflight):
    jdir = os.path.join(workdir, "overload-journal")
    proc, port = spawn_gateway(
        jdir, extra_env={
            "ROUNDTABLE_GATEWAY_MAX_INFLIGHT": str(max_inflight)})
    admitted_ttfts, sheds, bad_sheds = [], [], []
    lock = threading.Lock()

    def one(i):
        t0 = time.monotonic()
        try:
            c = Conn(port, "POST", "/v1/discussions",
                     body={"session": f"ol{i}", "max_new_tokens": 8,
                           "turns": [{"knight": "lancelot",
                                      "prompt": PROMPTS[0]}]})
            if c.status == 200:
                ttft = None
                for eid, data in c.events():
                    ev = json.loads(data)
                    if ev["type"] in ("tokens", "summary"):
                        ttft = time.monotonic() - t0
                    if ev["type"] in ("retired", "failed"):
                        break
                c.close()
                with lock:
                    admitted_ttfts.append(ttft)
            else:
                payload = c.body_json()
                retry = c.headers.get("retry-after")
                c.close()
                entry = {"status": c.status,
                         "reason": payload.get("reason"),
                         "retry_after": retry}
                ok = (c.status in (429, 503) and retry is not None
                      and bool(payload.get("reason")))
                with lock:
                    (sheds if ok else bad_sheds).append(entry)
        except Exception as e:  # noqa: BLE001 — recorded, not fatal
            with lock:
                bad_sheds.append({"error": repr(e)})

    try:
        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(burst)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(300)
    finally:
        proc.kill()
        proc.wait(30)

    ttfts = sorted(t for t in admitted_ttfts if t is not None)
    p95 = (ttfts[min(int(len(ttfts) * 0.95), len(ttfts) - 1)]
           if ttfts else None)
    reasons = {}
    for s in sheds:
        reasons[s["reason"]] = reasons.get(s["reason"], 0) + 1
    return {
        "burst": burst,
        "max_inflight": max_inflight,
        "admitted": len(admitted_ttfts),
        "shed": len(sheds),
        "shed_reasons": reasons,
        "malformed_sheds": bad_sheds,
        "admitted_ttft_p95_s": round(p95, 3) if p95 else None,
        "admitted_ttft_max_s": round(ttfts[-1], 3) if ttfts else None,
        "sheds_well_formed": not bad_sheds,
    }


# --- (c) router: rolling restart + replica scaling (ISSUE 17) --------


def post_json(port, path, body):
    c = Conn(port, "POST", path, body=body)
    status, payload = c.status, c.body_json()
    c.close()
    return status, payload


def stream_turn(port, body, tries=24):
    """One discussion turn as an open-loop client: retries classified
    sheds (429/503 + Retry-After) and reconnects mid-stream failures
    through the Last-Event-ID resume ladder. Returns (tokens,
    reconnects, sheds) or (None, ...) when every try failed."""
    toks, meta, last_id = [], None, None
    reconnects = sheds = 0
    for _ in range(tries):
        try:
            if meta is None:
                c = Conn(port, "POST", "/v1/discussions", body=body)
            else:
                hdrs = ({"Last-Event-ID": last_id} if last_id else {})
                c = Conn(port, "GET",
                         f"/v1/streams/{meta['stream']}",
                         headers=hdrs)
        except OSError:
            time.sleep(0.5)
            continue
        if c.status != 200:
            retry = c.headers.get("retry-after")
            c.body_json()
            c.close()
            if meta is None:
                sheds += 1
                time.sleep(min(float(retry or 0.5), 1.0))
            else:
                reconnects += 1
                time.sleep(0.5)
            continue
        if meta is not None:
            reconnects += 1
        terminal = None
        for eid, data in c.events():
            ev = json.loads(data)
            if ev["type"] == "stream":
                meta = ev
            elif ev["type"] in ("tokens", "summary"):
                toks.append((eid, ev))
                last_id = eid
            else:
                terminal = ev
                break
        c.close()
        if terminal and terminal["type"] == "retired":
            return flat_tokens(toks), reconnects, sheds
        time.sleep(0.5)  # failed/truncated: reconnect and resume
    return None, reconnects, sheds


def run_roll(workdir, n_streams, max_new, turns):
    """Rolling restart of replica r0 in a 2-replica fleet while every
    client is mid-discussion (open-loop: each session runs `turns`
    sequential turns). Zero failed sessions, zero lost/duplicated
    tokens, greedy parity against an unrolled reference fleet."""
    jdir = os.path.join(workdir, "roll-journal")
    proc, port = spawn_gateway(
        jdir, replicas=2,
        extra_env={"ROUNDTABLE_ROUTER_ROLL_TIMEOUT_S": "120"})
    refs = []
    outs = [[None] * turns for _ in range(n_streams)]
    stats = [{"reconnects": 0, "sheds": 0} for _ in range(n_streams)]
    roll_status, roll_payload = None, None
    try:
        for i in range(n_streams):
            per = []
            for t in range(turns):
                _m, toks, term = read_stream(
                    port, "/v1/discussions",
                    {"session": f"ref-roll{i}",
                     "max_new_tokens": max_new,
                     "turns": [{"knight": "lancelot",
                                "prompt": PROMPTS[(i + t)
                                                  % len(PROMPTS)]}]})
                assert term["type"] == "retired"
                per.append(flat_tokens(toks))
            refs.append(per)

        def client(i):
            for t in range(turns):
                got, rc, sh = stream_turn(
                    port, {"session": f"roll{i}",
                           "max_new_tokens": max_new,
                           "turns": [{"knight": "lancelot",
                                      "prompt": PROMPTS[(i + t)
                                                        % len(PROMPTS)]
                                      }]})
                outs[i][t] = got
                stats[i]["reconnects"] += rc
                stats[i]["sheds"] += sh

        threads = [threading.Thread(target=client, args=(i,))
                   for i in range(n_streams)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        time.sleep(0.3)  # land the roll while turn 1 is in flight
        roll_status, roll_payload = post_json(
            port, "/v1/admin/roll", {"replica": "r0"})
        for t in threads:
            t.join(600)
        wall = time.monotonic() - t0
    finally:
        proc.kill()
        proc.wait(30)

    failed_sessions = sum(
        1 for per in outs if any(g is None for g in per))
    lost = dup = 0
    for per, ref_per in zip(outs, refs):
        for got, ref in zip(per, ref_per):
            if got is None or got == ref:
                continue
            if len(got) < len(ref) or got[:len(ref)] != ref:
                lost += 1
            else:
                dup += 1
    rolled = (roll_payload or {}).get("rolled") or []
    return {
        "streams": n_streams,
        "turns_per_session": turns,
        "max_new_tokens": max_new,
        "roll_status": roll_status,
        "roll_reports": rolled,
        "roll_ok": (roll_status == 200
                    and all(r.get("ok") for r in rolled)),
        "failed_sessions": failed_sessions,
        "turns_lost_tokens": lost,
        "turns_duplicated_tokens": dup,
        "reconnects": [s["reconnects"] for s in stats],
        "sheds_retried": [s["sheds"] for s in stats],
        "greedy_token_parity": (failed_sessions == 0 and lost == 0
                                and dup == 0),
        "wall_s": round(wall, 3),
    }


def measure_throughput(workdir, replicas, n_streams, max_new):
    """Aggregate decode tok/s over `n_streams` concurrent sessions —
    the 1 -> 2 replica scaling point. CPU walls: the shape of the
    harness, not a TPU throughput claim (cpu_wall_caveat)."""
    jdir = os.path.join(workdir, f"scale-{replicas}-journal")
    proc, port = spawn_gateway(jdir, replicas=replicas)
    try:
        # Warm the compile caches on EVERY replica so the measured
        # window is decode: the warm streams run at the same
        # concurrency as the measurement, so load-based placement
        # spreads them (and their compiles) across the fleet.
        warm = [threading.Thread(
            target=lambda i=i: read_stream(
                port, "/v1/discussions",
                {"session": f"warm{i}", "max_new_tokens": 4,
                 "turns": [{"knight": "lancelot",
                            "prompt": PROMPTS[0]}]}))
            for i in range(n_streams)]
        for t in warm:
            t.start()
        for t in warm:
            t.join(600)
        counts = [0] * n_streams

        def one(i):
            _m, toks, term = read_stream(
                port, "/v1/discussions",
                {"session": f"s{i}", "max_new_tokens": max_new,
                 "turns": [{"knight": "lancelot",
                            "prompt": PROMPTS[i % len(PROMPTS)]}]})
            if term and term["type"] == "retired":
                counts[i] = len(flat_tokens(toks))

        threads = [threading.Thread(target=one, args=(i,))
                   for i in range(n_streams)]
        t0 = time.monotonic()
        for t in threads:
            t.start()
        for t in threads:
            t.join(600)
        wall = time.monotonic() - t0
    finally:
        proc.kill()
        proc.wait(30)
    total = sum(counts)
    return {
        "replicas": replicas,
        "streams": n_streams,
        "tokens": total,
        "wall_s": round(wall, 3),
        "agg_tok_s": round(total / wall, 2) if wall > 0 else None,
    }


def main_router(args) -> int:
    """--replicas 2 mode: ROUTER_r17.json (ISSUE 17 acceptance)."""
    import tempfile
    n_streams = 1 if args.smoke else 3
    max_new = 8 if args.smoke else 24
    turns = 2 if args.smoke else 3

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="rtbench-") as workdir:
        roll = run_roll(workdir, n_streams, max_new, turns)
        scaling = None
        if not args.smoke:
            scaling = [measure_throughput(workdir, n, 4, 24)
                       for n in (1, 2)]

    meets = (roll["roll_ok"] and roll["greedy_token_parity"]
             and roll["failed_sessions"] == 0)
    if not args.smoke:
        lint = subprocess.run(
            [sys.executable, "-m", "theroundtaible_tpu", "lint"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True)
        meets = (meets and lint.returncode == 0
                 and all(s["agg_tok_s"] for s in scaling))
    record = {
        "metric": "router_rolling_restart",
        "value": roll["wall_s"],
        "unit": "roll_under_load_wall_s",
        "detail": {
            "rolling_restart": roll,
            "replica_scaling": scaling,
            "lint_exit": None if args.smoke else lint.returncode,
            "acceptance": {
                "criterion": "rolling restart of one replica in a "
                             "2-replica fleet under open-loop gateway "
                             "load: zero failed sessions, zero "
                             "lost/duplicated tokens, greedy parity "
                             "across the roll; aggregate tok/s "
                             "recorded at 1 and 2 replicas",
                "meets": meets,
            },
            "cpu_wall_caveat": True,
            "platform": "cpu",
            "wall_s": round(time.monotonic() - t0, 1),
        },
    }
    print(json.dumps(record, indent=1))
    if args.smoke:
        return 0 if meets else 1
    out = args.out or os.path.join(REPO, "ROUTER_r17.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(f"wrote {out}", file=sys.stderr)
    return 0 if meets else 1


# --- (d) end-to-end tracing (ISSUE 20) -------------------------------


def _leg_gap_ok(leg, frac=0.05, floor=0.02):
    """The acceptance invariant: a leg's stage sum telescopes to its
    wall — within 5% (or a small absolute floor for sub-second legs)."""
    return abs(leg.get("stage_gap_s", 0.0)) <= max(
        frac * leg.get("wall_s", 0.0), floor)


def _trace_env(tdir):
    return {"ROUNDTABLE_TRACE_DIR": tdir,
            "ROUNDTABLE_TRACE_SAMPLE": "1",
            "ROUNDTABLE_TELEMETRY": "1"}


def run_trace_chaos(workdir, n_streams, max_new):
    """One trace per client request across the full recovery ladder:
    leg 1 dies with its replica (device_lost), leg 2 is the failover
    restore on the survivor (replica_crossed), kill -9 lands between
    legs, and leg 3 is the post-`--resume` committed replay in a NEW
    process. Every leg is tail-retained or head-sampled at 1.0, so the
    on-disk trace file stitches all generations."""
    from theroundtaible_tpu.utils import tracing

    jdir = os.path.join(workdir, "trace-journal")
    tdir = os.path.join(workdir, "trace-retained")
    env = dict(_trace_env(tdir), ROUNDTABLE_FAULTS="device_lost:1")
    proc, port = spawn_gateway(jdir, replicas=2, extra_env=env)

    clients = [{"session": f"tr{i}", "trace": None, "stream": None,
                "tokens": 0, "failed_leg": False, "last_id": None,
                "walls_s": []} for i in range(n_streams)]
    try:
        conns = []
        t_open = time.monotonic()
        for i, cl in enumerate(clients):
            c = Conn(port, "POST", "/v1/discussions",
                     body={"session": cl["session"],
                           "max_new_tokens": max_new,
                           "turns": [{"knight": "lancelot",
                                      "prompt": PROMPTS[
                                          i % len(PROMPTS)]}]})
            assert c.status == 200
            conns.append(c)
        # Leg 1: read each stream to its terminal. The armed
        # device_lost kills whichever replica dispatches next, so its
        # streams terminate `failed` (their legs finish `interrupted`,
        # flagged, WRITTEN); survivor streams retire clean.
        for cl, c in zip(clients, conns):
            it = c.events()
            meta = json.loads(next(it)[1])
            cl["trace"], cl["stream"] = meta["trace"], meta["stream"]
            assert cl["trace"], "metadata event carries no trace id"
            for eid, data in it:
                ev = json.loads(data)
                if ev["type"] in ("tokens", "summary"):
                    cl["tokens"] += len(flat_tokens([(eid, ev)]))
                    cl["last_id"] = eid
                elif ev["type"] == "failed":
                    cl["failed_leg"] = True
                    break
                elif ev["type"] == "retired":
                    break
            c.close()
            cl["walls_s"].append(round(time.monotonic() - t_open, 3))
        # Leg 2: failed clients reconnect INSIDE the same process —
        # the router failover restores them on the survivor, which is
        # the guaranteed replica_crossed leg. Read to retirement so
        # the leg record flushes before the SIGKILL.
        for cl in clients:
            if not cl["failed_leg"]:
                continue
            t0, deadline = time.monotonic(), time.monotonic() + 90
            done = False
            while not done and time.monotonic() < deadline:
                hdrs = ({"Last-Event-ID": cl["last_id"]}
                        if cl["last_id"] else None)
                try:
                    meta2, toks2, term2 = read_stream(
                        port, f"/v1/streams/{cl['stream']}",
                        method="GET", headers=hdrs)
                except (AssertionError, OSError):
                    time.sleep(0.5)   # failover still settling
                    continue
                assert meta2["trace"] == cl["trace"], \
                    "failover leg minted a NEW trace id"
                cl["tokens"] += len(flat_tokens(toks2))
                if toks2:
                    cl["last_id"] = toks2[-1][0]
                done = term2 is not None and term2["type"] == "retired"
            assert done, f"{cl['session']} never recovered in leg 2"
            cl["walls_s"].append(round(time.monotonic() - t0, 3))
    finally:
        proc.kill()   # SIGKILL between legs: kill -9 crossing
        proc.wait(30)

    # Leg 3: a NEW process resumes the journal; every client
    # reconnects and replays its committed turn under the SAME trace.
    proc2, port2 = spawn_gateway(jdir, resume=jdir, replicas=2,
                                 extra_env=_trace_env(tdir))
    try:
        for cl in clients:
            t0 = time.monotonic()
            meta3, toks3, term3 = read_stream(
                port2, f"/v1/streams/{cl['stream']}", method="GET")
            assert term3 and term3["type"] == "retired", \
                f"{cl['session']}: post-restart replay did not retire"
            assert meta3["trace"] == cl["trace"], \
                "post-restart leg minted a NEW trace id"
            replayed = len(flat_tokens(toks3))
            assert replayed >= cl["tokens"], \
                f"{cl['session']}: replay lost tokens"
            cl["walls_s"].append(round(time.monotonic() - t0, 3))
    finally:
        proc2.kill()
        proc2.wait(30)

    # Judge the retained traces.
    traces = tracing.load_traces(tdir)
    want = {cl["trace"] for cl in clients}
    orphans = sorted(set(traces) - want)
    stitched, gap_violations, crossed = [], [], 0
    max_gap_frac = 0.0
    for cl in clients:
        legs = traces.get(cl["trace"], [])
        for leg in legs:
            if not _leg_gap_ok(leg):
                gap_violations.append(
                    {"trace": cl["trace"],
                     "gap_s": leg.get("stage_gap_s"),
                     "wall_s": leg.get("wall_s")})
            if leg.get("wall_s", 0.0) > 0:
                max_gap_frac = max(
                    max_gap_frac, abs(leg.get("stage_gap_s", 0.0))
                    / leg["wall_s"])
        s = tracing.stitch(legs)
        if "replica_crossed" in s["flags"]:
            crossed += 1
        stitched.append({
            "session": cl["session"], "trace": cl["trace"],
            "legs": s["legs"], "pids": len(s["pids"]),
            "outcome": s["outcome"], "flags": s["flags"],
            "wall_s": s["wall_s"], "stage_sum_s": s["stage_sum_s"],
            "ttft_s": s["ttft_s"], "stages": s["stages"],
            "client_leg_walls_s": cl["walls_s"],
        })
    # Structural orphan check: every retained trace roots in a
    # `request` leg; later legs are `resume` joins, never new roots.
    malformed = [
        tid for tid, legs in traces.items()
        if legs[0].get("kind") != "request"
        or any(leg.get("kind") not in ("request", "resume")
               for leg in legs)]
    one_per_client = (
        len(want) == n_streams
        and all(s["legs"] >= 2 and s["pids"] >= 2 for s in stitched))
    return {
        "streams": n_streams,
        "max_new_tokens": max_new,
        "stitched": stitched,
        "one_stitched_trace_per_client": one_per_client,
        "replicas_crossed": crossed,
        "stage_gap_violations": gap_violations,
        "max_leg_gap_frac": round(max_gap_frac, 4),
        "orphan_traces": orphans,
        "malformed_traces": malformed,
        "zero_orphans": not orphans and not malformed,
        "stage_sum_within_5pct": not gap_violations,
    }


def run_trace_sweep(workdir, smoke):
    """Open-loop loadgen sweep against a traced child gateway: every
    per-session client record carries the trace id from the SSE
    events, and joins to a server-side retained leg — the per-stage
    p95 table attributes the sweep's TTFT tail to named stages."""
    from theroundtaible_tpu.loadgen.arrivals import make_arrivals
    from theroundtaible_tpu.loadgen.driver import GatewayDriver
    from theroundtaible_tpu.loadgen.sweep import run_point
    from theroundtaible_tpu.loadgen.workload import WorkloadMix
    from theroundtaible_tpu.utils import tracing

    jdir = os.path.join(workdir, "sweep-journal")
    tdir = os.path.join(workdir, "sweep-retained")
    proc, port = spawn_gateway(
        jdir, extra_env=dict(_trace_env(tdir),
                             ROUNDTABLE_GATEWAY_MAX_INFLIGHT="4"))
    rates = [2.0, 6.0] if smoke else [2.0, 6.0, 12.0]
    duration_s = 2.0 if smoke else 5.0
    points = []
    try:
        mix = WorkloadMix(max_new_tokens=4, max_turns=1,
                          prompt_words=(3, 12))
        process = make_arrivals("poisson", 7)
        driver = GatewayDriver(port)
        for i, rate in enumerate(rates):
            p = run_point(driver, process, mix, rate_rps=rate,
                          duration_s=duration_s, seed=7,
                          point_index=i + 1, n_devices=1)
            points.append({
                "offered_rps": p["offered_rps"],
                "admitted": p["admitted"], "shed": p["shed"],
                "ttft_p95_s": p.get("ttft_p95_s"),
                "exemplar_traces": p.get("exemplar_traces", []),
            })
    finally:
        proc.kill()
        proc.wait(30)

    legs = [leg for l in tracing.load_traces(tdir).values()
            for leg in l]

    def p95(vals):
        if not vals:
            return None
        v = sorted(vals)
        return round(v[min(int(len(v) * 0.95), len(v) - 1)], 6)

    from theroundtaible_tpu.utils.tracing import STAGES
    stage_p95 = {
        s: p95([leg["stages"][s] for leg in legs
                if s in leg.get("stages", {})])
        for s in STAGES}
    exemplars = [t for p in points for t in p["exemplar_traces"]]
    joined = [t for t in exemplars
              if t in {leg["trace_id"] for leg in legs}]
    return {
        "points": points,
        "retained_legs": len(legs),
        "stage_p95_s": {k: v for k, v in stage_p95.items()
                        if v is not None},
        "stage_gap_p95_s": p95([abs(leg.get("stage_gap_s", 0.0))
                                for leg in legs]),
        "exemplars_joined": f"{len(joined)}/{len(exemplars)}",
        "exemplars_join_retained": (bool(exemplars)
                                    and len(joined) == len(exemplars)),
    }


def run_burn_probe(workdir):
    """The SLO burn monitor's two-sided acceptance in-process: quiet
    on an under-SLO baseline, exactly one flight dump on an induced
    sustained breach (multiwindow rule + per-window cooldown)."""
    os.environ["ROUNDTABLE_TELEMETRY_DIR"] = os.path.join(workdir,
                                                          "dumps")
    from theroundtaible_tpu.utils import tracing

    baseline = tracing.SloBurnMonitor(0.5, error_budget=0.05,
                                      fast_window_s=60,
                                      slow_window_s=600)
    for _ in range(32):
        baseline.note_ttft(0.01)
    induced = tracing.SloBurnMonitor(0.001, error_budget=0.05,
                                     fast_window_s=60,
                                     slow_window_s=600)
    for _ in range(32):
        induced.note_ttft(0.4, trace_id="bench-induced")
    return {
        "baseline_breaches": baseline.breaches,
        "induced_breaches": induced.breaches,
        "induced_dump": os.path.basename(induced.last_dump_path),
        "induced_burn": induced.burn_rates(),
        "quiet_on_baseline": baseline.breaches == 0,
        "fires_once_on_breach": (induced.breaches == 1
                                 and bool(induced.last_dump_path)),
    }


def main_trace(args) -> int:
    """--trace mode: TRACE_r20.json (ISSUE 20 acceptance)."""
    import tempfile
    n_streams = 1 if args.smoke else 3
    max_new = 8 if args.smoke else 24

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="trbench-") as workdir:
        chaos = run_trace_chaos(workdir, n_streams, max_new)
        sweep = run_trace_sweep(workdir, args.smoke)
        burn = run_burn_probe(workdir)

    meets = (chaos["one_stitched_trace_per_client"]
             and chaos["stage_sum_within_5pct"]
             and chaos["zero_orphans"]
             and chaos["replicas_crossed"] >= 1
             and sweep["exemplars_join_retained"]
             and burn["quiet_on_baseline"]
             and burn["fires_once_on_breach"])
    if not args.smoke:
        lint = subprocess.run(
            [sys.executable, "-m", "theroundtaible_tpu", "lint"],
            cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
            capture_output=True, text=True)
        meets = meets and lint.returncode == 0
    record = {
        "metric": "request_tracing",
        "value": chaos["max_leg_gap_frac"],
        "unit": "max_leg_stage_gap_frac",
        "detail": {
            "chaos": chaos,
            "loadgen_sweep": sweep,
            "slo_burn": burn,
            "lint_exit": None if args.smoke else lint.returncode,
            "acceptance": {
                "criterion": "device_lost failover + kill -9 + "
                             "--resume under concurrent streams: one "
                             "stitched on-disk trace per client "
                             "request across process generations, "
                             "per-leg stage sum within 5% of the leg "
                             "wall, zero orphan legs, >=1 "
                             "replica_crossed leg; loadgen exemplar "
                             "traces join retained server legs with "
                             "per-stage p95 attribution; burn monitor "
                             "quiet on baseline, fires once on an "
                             "induced breach",
                "meets": meets,
            },
            "cpu_wall_caveat": True,
            "platform": "cpu",
            "wall_s": round(time.monotonic() - t0, 1),
        },
    }
    print(json.dumps(record, indent=1))
    if args.smoke:
        return 0 if meets else 1
    out = args.out or os.path.join(REPO, "TRACE_r20.json")
    with open(out, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(f"wrote {out}", file=sys.stderr)
    return 0 if meets else 1


# --- driver ----------------------------------------------------------


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="1-stream chaos + small burst; no artifact")
    ap.add_argument("--replicas", type=int, default=1,
                    help=">1 switches to the router acceptance "
                         "(rolling restart + scaling, ROUTER_r17.json)")
    ap.add_argument("--trace", action="store_true",
                    help="end-to-end tracing acceptance "
                         "(chaos stitch + sweep attribution + burn "
                         "monitor, TRACE_r20.json)")
    ap.add_argument("--out", default=None)
    args = ap.parse_args()

    if args.trace:
        return main_trace(args)
    if args.replicas > 1:
        return main_router(args)
    args.out = args.out or os.path.join(REPO, "GATEWAY_r16.json")

    import tempfile
    n_streams = 1 if args.smoke else 3
    # full mode spans two 64-token decode segments so the SIGKILL
    # lands on an UNCOMMITTED turn (reconnect leg 3: greedy
    # regeneration), not just a journaled one (leg 2).
    max_new = 12 if args.smoke else 96
    burst = 4 if args.smoke else 12

    t0 = time.monotonic()
    with tempfile.TemporaryDirectory(prefix="gwbench-") as workdir:
        chaos = run_chaos(workdir, n_streams, max_new)
        overload = run_overload(workdir, burst, max_inflight=2)

    lint = subprocess.run(
        [sys.executable, "-m", "theroundtaible_tpu", "lint"],
        cwd=REPO, env=dict(os.environ, JAX_PLATFORMS="cpu"),
        capture_output=True, text=True)

    meets = (chaos["greedy_token_parity"]
             and overload["sheds_well_formed"]
             and overload["shed"] > 0
             and lint.returncode == 0)
    record = {
        "metric": "gateway_slo_serving",
        "value": chaos["restart_to_listening_wall_s"],
        "unit": "restart_to_listening_wall_s",
        "detail": {
            "chaos_kill9": chaos,
            "open_loop_overload": overload,
            "recompile_strict_armed": True,
            "lint_exit": lint.returncode,
            "acceptance": {
                "criterion": "kill -9 under concurrent streams, "
                             "restart --resume, every client "
                             "reconnects via Last-Event-ID with zero "
                             "lost/duplicated tokens and greedy "
                             "parity; overload sheds carry 429 + "
                             "Retry-After + machine-readable reason "
                             "while admitted p95 TTFT stays bounded; "
                             "lint exits 0 with strict recompile "
                             "armed across the restart",
                "meets": meets,
            },
            "cpu_wall_caveat": True,
            "platform": "cpu",
            "wall_s": round(time.monotonic() - t0, 1),
        },
    }
    print(json.dumps(record, indent=1))
    if args.smoke:
        return 0 if meets else 1
    with open(args.out, "w", encoding="utf-8") as f:
        json.dump(record, f, indent=1)
        f.write("\n")
    print(f"wrote {args.out}", file=sys.stderr)
    return 0 if meets else 1


if __name__ == "__main__":
    sys.exit(main())
