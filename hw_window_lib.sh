# Shared hardware-window plumbing, sourced by run_hw_window*.sh.
# Expects $OUT to be set to the window's JSONL artifact path.
stamp() { date -u +%Y-%m-%dT%H:%M:%SZ; }

run_step() {
  local name="$1"; shift
  echo "=== $(stamp) $name ===" >> "$OUT.log"
  "$@" >> "$OUT" 2>> "$OUT.log"
  local rc=$?
  # add first (-o alone errors on UNTRACKED paths — the first window's
  # artifacts are new files), then commit ONLY the artifact files (-o):
  # anything else staged stays out of the artifact commit. A real commit
  # failure must be loud — the per-step commit IS the durability
  # guarantee this script exists for.
  git add "$OUT" "$OUT.log"
  if ! git commit -q -o "$OUT" -o "$OUT.log" \
      -m "Hardware window: $name artifact (rc=$rc)

No-Verification-Needed: measurement artifact only, no source change"
  then
    echo "WARN: artifact commit failed after $name (rc=$rc)" \
      | tee -a "$OUT.log" >&2
  fi
  return $rc
}
