"""Decode profile — where the 6.8ms/token actually goes.

BENCH_NOTES.md round 3: int8 decode measures ~148 tok/s against a
~326 tok/s weight-streaming ceiling (45% of roofline). Closing that gap
needs evidence, not guesses: this harness wraps a steady-state decode
run in jax.profiler.trace and emits the top device ops by total time as
JSON — the data that says whether the missing milliseconds are in the
int8 dequant (unfused convert materializing bf16 weights), the
attention kernel, the sampling epilogue, or dispatch gaps.

On hardware, main() runs THREE watchdogged children — `--quant int8`,
`--quant int4`, then `--quant int8 --mode prefill` — so each config
gets its own attempt/timeout isolation: a slow int4 trace can never
force an invisible re-run of an already-captured int8 one. int8
attributes the standing roofline gap; int4 answers whether the packed
unpack+scale chain fused into the matmul operand (unfused dequant
would dominate its trace); the prefill child attributes the 29-31%
prefill MFU (VERDICT r4 weak #4) by tracing one fresh full-prompt
prefill.

Usage: python bench_profile.py     (real chip; int8/int4/prefill children)
       ROUNDTABLE_BENCH_CPU=1 ...  (tiny model smoke, decode + prefill)
Same probe-first watchdog as every bench (bench_common).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import sys
import tempfile
import time

ATTEMPT_TIMEOUT_S = 420.0  # per child = per quant config
MAX_ATTEMPTS = 2
RETRY_DELAY_S = 20.0

PROMPT = ("You are taking part in a TheRoundtAIble discussion. Topic: "
          "should we refactor the session store before the apply "
          "pipeline? Answer carefully. " * 8)


def _top_device_ops(trace_dir: str, top_n: int = 14) -> list[dict]:
    """Aggregate per-op durations from the profiler's chrome traces.

    Multi-device/multi-host profiles emit SEVERAL *.trace.json.gz (one
    per host/device group) — aggregating only files[0] silently dropped
    every other chip's ops (ISSUE 6 satellite), so all files aggregate,
    with the device-pid filter applied PER FILE (pids are file-local).
    Device pids (named like '/device:TPU:0') are preferred; when no
    file has any (CPU smoke's host-only trace), all files fall back to
    all pids minus Python-frame noise."""
    from collections import defaultdict

    files = glob.glob(os.path.join(trace_dir, "plugins", "profile", "*",
                                   "*.trace.json.gz"))
    if not files:
        return []
    # One pass per file, retaining only its AGGREGATE (a multi-host
    # trace file is hundreds of MB decompressed — holding every file's
    # event list simultaneously would make peak memory N× one trace).
    # Each file aggregates under its own mode (device-filtered vs the
    # host fallback); the merge below keeps only device aggregates
    # when any file had device pids.
    per_file = []  # (had_device_pids, {name: [dur, count]})
    for path in sorted(files):
        t = json.loads(gzip.open(path).read())
        events = t.get("traceEvents", [])
        pid_names = {e["pid"]: e["args"].get("name", "")
                     for e in events
                     if e.get("ph") == "M"
                     and e.get("name") == "process_name"
                     and "args" in e}
        device_pids = {p for p, n in pid_names.items()
                       if "device" in n.lower() or "tpu" in n.lower()}
        fagg = defaultdict(lambda: [0.0, 0])
        for e in events:
            if e.get("ph") != "X" or not e.get("dur"):
                continue
            name = e.get("name", "")
            if device_pids and e.get("pid") not in device_pids:
                continue
            if not device_pids and (name.startswith("$")
                                    or ".py:" in name
                                    or name.startswith("<")):
                continue
            fagg[name][0] += e["dur"]
            fagg[name][1] += 1
        per_file.append((bool(device_pids), fagg))
        del t, events  # only the aggregate survives this iteration
    any_device = any(had for had, _a in per_file)

    agg = defaultdict(lambda: [0.0, 0])
    for had_device, fagg in per_file:
        if any_device and not had_device:
            # Host-only file next to device traces: its fallback
            # aggregate is Python-frame noise — skip it.
            continue
        for name, (dur, count) in fagg.items():
            agg[name][0] += dur
            agg[name][1] += count
    total = sum(v[0] for v in agg.values()) or 1.0
    out = []
    for name, (dur, count) in sorted(agg.items(), key=lambda kv: -kv[1][0]):
        out.append({"op": name[:120], "total_ms": round(dur / 1e3, 2),
                    "count": count, "pct": round(100.0 * dur / total, 1)})
        if len(out) >= top_n:
            break
    return out


def child() -> int:
    from bench_common import install_sigterm_exit

    install_sigterm_exit()
    import jax

    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        jax.config.update("jax_platforms", "cpu")

    from theroundtaible_tpu.engine import enable_compilation_cache
    enable_compilation_cache()

    from theroundtaible_tpu.engine.models.registry import get_model_config

    on_cpu = jax.devices()[0].platform == "cpu"
    quant = "int8"
    if "--quant" in sys.argv:
        quant = sys.argv[sys.argv.index("--quant") + 1]
    mode = "decode"
    if "--mode" in sys.argv:
        mode = sys.argv[sys.argv.index("--mode") + 1]
    if on_cpu:
        _profile_one(get_model_config("tiny-gemma"), 64, "none", mode)
    else:
        _profile_one(get_model_config("gemma-2b-it", max_seq_len=2048),
                     192, quant, mode)
    return 0


def _profile_one(cfg, decode_tokens: int, quant: str,
                 mode: str = "decode") -> None:
    import jax
    from theroundtaible_tpu.engine.engine import InferenceEngine
    from theroundtaible_tpu.engine.sampling import SamplingParams

    engine = InferenceEngine(
        cfg, num_slots=2, quant=quant,
        sampling=SamplingParams(temperature=0.0,
                                max_new_tokens=decode_tokens))
    # Two warm passes: the profiled run must be pure steady state, no
    # compiles in the trace.
    for _ in range(2):
        engine.kv.release("warm")
        engine.generate(PROMPT, slot_name="warm",
                        max_new_tokens=decode_tokens)
    engine.kv.release("warm")

    if mode == "prefill":
        # Prefill attribution (VERDICT r4 weak #4: MFU 29-31% with no
        # hardware profile): trace ONE fresh full-prompt prefill.
        # The traced call still pays one decode step (max_new_tokens=1
        # is generate's floor), ~4.7 ms against a ~150 ms prefill at
        # the stretched prompt below — a few percent of trace time,
        # and the record carries prefill_seconds vs wall_s so the
        # reader can see the decode share. The prompt is stretched
        # toward the context budget: more prefill per trace means both
        # better MFU statistics and less relative decode contamination.
        # ByteTokenizer maps 1 char → 1 token (a real checkpoint's
        # tokenizer only compresses further, landing safely under
        # budget), so size the prompt in chars against the context.
        budget = max(cfg.max_seq_len - decode_tokens - 128, len(PROMPT))
        long_prompt = (PROMPT * (budget // len(PROMPT) + 1))[:budget]
        engine.kv.release("warm")
        engine.kv.release("prof")
        # warm/rehearse the stretched shape so no compile in the trace
        engine.generate(long_prompt, slot_name="warm", max_new_tokens=1)
        engine.kv.release("warm")
        trace_dir = tempfile.mkdtemp(prefix="rt_profile_pre_")
        t0 = time.monotonic()
        with jax.profiler.trace(trace_dir):
            engine.generate(long_prompt, slot_name="prof",
                            max_new_tokens=1)
        wall = time.monotonic() - t0
        s = engine.last_stats
        rec = {
            "metric": f"prefill_profile[{cfg.name}][{quant}]",
            "value": round(s.prefill_tps, 2),
            "unit": "tokens/s",
            "vs_baseline": 0.0,  # diagnostic record, not a headline
            "detail": {
                "quant": quant,
                "prefill_tokens": s.prefill_tokens,
                "prefill_seconds": round(s.prefill_seconds, 3),
                "wall_s": round(wall, 2),
                "platform": jax.devices()[0].platform,
                "trace_dir": trace_dir,
                "top_ops": _top_device_ops(trace_dir),
            },
        }
        print(json.dumps(rec), flush=True)
        return

    # Prime the slot OUTSIDE the trace, so the profiled call reuses all
    # but one prompt token and the trace is ≥99% decode — otherwise
    # prefill matmuls merge into the same op buckets and contaminate the
    # attribution this harness exists to produce.
    engine.generate(PROMPT, slot_name="prof", max_new_tokens=1)
    # Rehearse the EXACT profiled call once: the 1-token delta prefill
    # hits the smallest bucket program, which the full-prompt warm passes
    # never compiled — without this rehearsal that compile (and the
    # donated-buffer layout settling) lands INSIDE the trace (caught by
    # the CPU smoke: backend_compile dominated the trace). After it the
    # slot's cached tokens still share the whole prompt prefix, so the
    # profiled call repeats the identical 1-token-delta + decode shape.
    engine.generate(PROMPT, slot_name="prof", max_new_tokens=decode_tokens)

    trace_dir = tempfile.mkdtemp(prefix="rt_profile_")
    t0 = time.monotonic()
    with jax.profiler.trace(trace_dir):
        engine.generate(PROMPT, slot_name="prof",
                        max_new_tokens=decode_tokens)
    wall = time.monotonic() - t0
    s = engine.last_stats

    rec = {
        "metric": f"decode_profile[{cfg.name}][{quant}]",
        "value": round(s.decode_tps, 2),
        "unit": "tokens/s",
        "vs_baseline": 0.0,  # diagnostic record, not a headline
        "detail": {
            "quant": quant,
            "decode_tokens": s.decode_tokens,
            "decode_seconds": round(s.decode_seconds, 3),
            "prefill_tokens": s.prefill_tokens,
            "wall_s": round(wall, 2),
            "platform": jax.devices()[0].platform,
            # kept on disk for TensorBoard/Perfetto deep dives
            "trace_dir": trace_dir,
            "top_ops": _top_device_ops(trace_dir),
        },
    }
    print(json.dumps(rec), flush=True)


def main() -> int:
    from bench_common import run_watchdogged
    if os.environ.get("ROUNDTABLE_BENCH_CPU"):
        # CPU smoke covers BOTH branches (decode + prefill) on the tiny
        # model — a hardware window must never be the first executor of
        # either path (this file's own rehearsal comment records a
        # compile-in-trace bug the CPU smoke caught).
        configs = (["--quant", "none"],
                   ["--quant", "none", "--mode", "prefill"])
    else:
        configs = (["--quant", "int8"], ["--quant", "int4"],
                   ["--quant", "int8", "--mode", "prefill"])
    rc = 0
    for args in configs:
        rc |= run_watchdogged(os.path.abspath(__file__), args,
                              ATTEMPT_TIMEOUT_S, MAX_ATTEMPTS,
                              RETRY_DELAY_S)
    return rc


if __name__ == "__main__":
    sys.exit(child() if "--child" in sys.argv else main())
